"""Distribution families (ref: ``python/paddle/distribution/{normal,uniform,
bernoulli,categorical,beta,dirichlet,exponential_family,geometric,gumbel,
laplace,lognormal,multinomial,cauchy}.py`` + incubate families).

Samplers use jax.random primitives; densities are closed-form jnp. All are
pure (jit/vmap/grad-compatible) — the gradient-through-sampling story
(rsample) comes from reparameterization, not the reference's
per-op CUDA samplers.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp
from jax import random as jr
from jax.scipy import special as jsp

from .distribution import Distribution, _as_array, _wrap

__all__ = [
    "Normal", "Uniform", "Bernoulli", "Categorical", "Beta", "Dirichlet",
    "Exponential", "Gamma", "Geometric", "Gumbel", "Laplace", "LogNormal",
    "Multinomial", "Poisson", "Cauchy", "StudentT", "Binomial",
    "ContinuousBernoulli", "ExponentialFamily",
]


class ExponentialFamily(Distribution):
    """Marker base (ref: exponential_family.py); entropy via Bregman
    divergence is replaced by closed forms in each family."""


def _bcast_shape(*arrs):
    return jnp.broadcast_shapes(*(a.shape for a in arrs))


class Normal(ExponentialFamily):
    def __init__(self, loc, scale, name=None):
        self.loc = _as_array(loc)
        self.scale = _as_array(scale)
        super().__init__(_bcast_shape(self.loc, self.scale))

    def _rsample(self, key, shape):
        full = shape + self._batch_shape
        return self.loc + self.scale * jr.normal(key, full,
                                                 dtype=self.loc.dtype)

    def _log_prob(self, v):
        var = self.scale ** 2
        return (-((v - self.loc) ** 2) / (2 * var)
                - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def _entropy(self):
        return jnp.broadcast_to(
            0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale),
            self._batch_shape)

    def _mean(self):
        return jnp.broadcast_to(self.loc, self._batch_shape)

    def _variance(self):
        return jnp.broadcast_to(self.scale ** 2, self._batch_shape)

    def cdf(self, value):
        v = _as_array(value)
        return _wrap(0.5 * (1 + jsp.erf((v - self.loc) /
                                        (self.scale * math.sqrt(2)))))

    def icdf(self, value):
        v = _as_array(value)
        return _wrap(self.loc + self.scale * math.sqrt(2)
                     * jsp.erfinv(2 * v - 1))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _as_array(low)
        self.high = _as_array(high)
        super().__init__(_bcast_shape(self.low, self.high))

    def _rsample(self, key, shape):
        full = shape + self._batch_shape
        u = jr.uniform(key, full, dtype=self.low.dtype)
        return self.low + (self.high - self.low) * u

    def _log_prob(self, v):
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return jnp.where(inside, lp, -jnp.inf)

    def _entropy(self):
        return jnp.broadcast_to(jnp.log(self.high - self.low),
                                self._batch_shape)

    def _mean(self):
        return jnp.broadcast_to((self.low + self.high) / 2,
                                self._batch_shape)

    def _variance(self):
        return jnp.broadcast_to((self.high - self.low) ** 2 / 12,
                                self._batch_shape)


class Bernoulli(ExponentialFamily):
    def __init__(self, probs=None, logits=None, name=None):
        if (probs is None) == (logits is None):
            raise ValueError("pass exactly one of probs/logits")
        if probs is not None:
            self.probs = _as_array(probs)
            self.logits = jnp.log(self.probs) - jnp.log1p(-self.probs)
        else:
            self.logits = _as_array(logits)
            self.probs = jax.nn.sigmoid(self.logits)
        super().__init__(self.probs.shape)

    def _sample(self, key, shape):
        full = shape + self._batch_shape
        return jr.bernoulli(key, self.probs, full).astype(self.probs.dtype)

    def _log_prob(self, v):
        return v * jax.nn.log_sigmoid(self.logits) + \
            (1 - v) * jax.nn.log_sigmoid(-self.logits)

    def _entropy(self):
        p = self.probs
        return -(p * jnp.log(jnp.clip(p, 1e-37)) +
                 (1 - p) * jnp.log(jnp.clip(1 - p, 1e-37)))

    def _mean(self):
        return self.probs

    def _variance(self):
        return self.probs * (1 - self.probs)


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if logits is None and probs is None:
            raise ValueError("pass logits or probs")
        if logits is not None:
            # the reference's Categorical(logits) treats input as
            # UNNORMALIZED nonnegative weights only in legacy mode; modern
            # semantics: logits are log-weights
            self.logits = _as_array(logits)
            self._log_p = jax.nn.log_softmax(self.logits, axis=-1)
        else:
            p = _as_array(probs)
            self._log_p = jnp.log(p / p.sum(-1, keepdims=True))
            self.logits = self._log_p
        super().__init__(self._log_p.shape[:-1])
        self._n = self._log_p.shape[-1]

    def _sample(self, key, shape):
        full = shape + self._batch_shape
        return jr.categorical(key, self._log_p, shape=full)

    def _log_prob(self, v):
        idx = v.astype(jnp.int32)
        return jnp.take_along_axis(
            jnp.broadcast_to(self._log_p, idx.shape + (self._n,)),
            idx[..., None], axis=-1)[..., 0]

    def _entropy(self):
        p = jnp.exp(self._log_p)
        return -(p * self._log_p).sum(-1)

    @property
    def probs_tensor(self):
        return _wrap(jnp.exp(self._log_p))


class Beta(ExponentialFamily):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _as_array(alpha)
        self.beta = _as_array(beta)
        super().__init__(_bcast_shape(self.alpha, self.beta))

    def _rsample(self, key, shape):
        full = shape + self._batch_shape
        return jr.beta(key, self.alpha, self.beta, full)

    def _log_prob(self, v):
        a, b = self.alpha, self.beta
        return ((a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v)
                - (jsp.gammaln(a) + jsp.gammaln(b) - jsp.gammaln(a + b)))

    def _entropy(self):
        a, b = self.alpha, self.beta
        return (jsp.gammaln(a) + jsp.gammaln(b) - jsp.gammaln(a + b)
                - (a - 1) * jsp.digamma(a) - (b - 1) * jsp.digamma(b)
                + (a + b - 2) * jsp.digamma(a + b))

    def _mean(self):
        return self.alpha / (self.alpha + self.beta)

    def _variance(self):
        s = self.alpha + self.beta
        return self.alpha * self.beta / (s ** 2 * (s + 1))


class Dirichlet(ExponentialFamily):
    def __init__(self, concentration, name=None):
        self.concentration = _as_array(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    def _rsample(self, key, shape):
        full = shape + self._batch_shape
        return jr.dirichlet(key, self.concentration, full)

    def _log_prob(self, v):
        c = self.concentration
        return (((c - 1) * jnp.log(v)).sum(-1)
                + jsp.gammaln(c.sum(-1)) - jsp.gammaln(c).sum(-1))

    def _entropy(self):
        c = self.concentration
        c0 = c.sum(-1)
        k = c.shape[-1]
        lnB = jsp.gammaln(c).sum(-1) - jsp.gammaln(c0)
        return (lnB + (c0 - k) * jsp.digamma(c0)
                - ((c - 1) * jsp.digamma(c)).sum(-1))

    def _mean(self):
        return self.concentration / self.concentration.sum(-1, keepdims=True)

    def _variance(self):
        c = self.concentration
        c0 = c.sum(-1, keepdims=True)
        a = c / c0
        return a * (1 - a) / (c0 + 1)


class Exponential(ExponentialFamily):
    def __init__(self, rate, name=None):
        self.rate = _as_array(rate)
        super().__init__(self.rate.shape)

    def _rsample(self, key, shape):
        full = shape + self._batch_shape
        return jr.exponential(key, full, dtype=self.rate.dtype) / self.rate

    def _log_prob(self, v):
        return jnp.log(self.rate) - self.rate * v

    def _entropy(self):
        return 1 - jnp.log(self.rate)

    def _mean(self):
        return 1 / self.rate

    def _variance(self):
        return 1 / self.rate ** 2


class Gamma(ExponentialFamily):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _as_array(concentration)
        self.rate = _as_array(rate)
        super().__init__(_bcast_shape(self.concentration, self.rate))

    def _rsample(self, key, shape):
        full = shape + self._batch_shape
        return jr.gamma(key, self.concentration, full) / self.rate

    def _log_prob(self, v):
        a, b = self.concentration, self.rate
        return (a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v
                - jsp.gammaln(a))

    def _entropy(self):
        a, b = self.concentration, self.rate
        return (a - jnp.log(b) + jsp.gammaln(a)
                + (1 - a) * jsp.digamma(a))

    def _mean(self):
        return self.concentration / self.rate

    def _variance(self):
        return self.concentration / self.rate ** 2


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p, k in {0,1,2,...} (ref geometric.py)."""

    def __init__(self, probs, name=None):
        self.probs = _as_array(probs)
        super().__init__(self.probs.shape)

    def _sample(self, key, shape):
        full = shape + self._batch_shape
        u = jr.uniform(key, full, dtype=self.probs.dtype, minval=1e-7)
        return jnp.floor(jnp.log(u) / jnp.log1p(-self.probs))

    def _log_prob(self, v):
        return v * jnp.log1p(-self.probs) + jnp.log(self.probs)

    def _entropy(self):
        p = self.probs
        return -((1 - p) * jnp.log1p(-p) + p * jnp.log(p)) / p

    def _mean(self):
        return (1 - self.probs) / self.probs

    def _variance(self):
        return (1 - self.probs) / self.probs ** 2


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _as_array(loc)
        self.scale = _as_array(scale)
        super().__init__(_bcast_shape(self.loc, self.scale))

    def _rsample(self, key, shape):
        full = shape + self._batch_shape
        return self.loc + self.scale * jr.gumbel(key, full,
                                                 dtype=self.loc.dtype)

    def _log_prob(self, v):
        z = (v - self.loc) / self.scale
        return -(z + jnp.exp(-z)) - jnp.log(self.scale)

    def _entropy(self):
        return jnp.broadcast_to(jnp.log(self.scale) + 1 + float(np.euler_gamma),
                                self._batch_shape)

    def _mean(self):
        return jnp.broadcast_to(self.loc + self.scale * float(np.euler_gamma),
                                self._batch_shape)

    def _variance(self):
        return jnp.broadcast_to((math.pi ** 2 / 6) * self.scale ** 2,
                                self._batch_shape)


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _as_array(loc)
        self.scale = _as_array(scale)
        super().__init__(_bcast_shape(self.loc, self.scale))

    def _rsample(self, key, shape):
        full = shape + self._batch_shape
        return self.loc + self.scale * jr.laplace(key, full,
                                                  dtype=self.loc.dtype)

    def _log_prob(self, v):
        return -jnp.abs(v - self.loc) / self.scale - jnp.log(2 * self.scale)

    def _entropy(self):
        return jnp.broadcast_to(1 + jnp.log(2 * self.scale),
                                self._batch_shape)

    def _mean(self):
        return jnp.broadcast_to(self.loc, self._batch_shape)

    def _variance(self):
        return jnp.broadcast_to(2 * self.scale ** 2, self._batch_shape)


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _as_array(loc)
        self.scale = _as_array(scale)
        self._base = Normal(loc, scale)
        super().__init__(_bcast_shape(self.loc, self.scale))

    def _rsample(self, key, shape):
        return jnp.exp(self._base._rsample(key, shape))

    def _log_prob(self, v):
        return self._base._log_prob(jnp.log(v)) - jnp.log(v)

    def _entropy(self):
        return self._base._entropy() + self.loc

    def _mean(self):
        return jnp.exp(self.loc + self.scale ** 2 / 2)

    def _variance(self):
        s2 = self.scale ** 2
        return (jnp.exp(s2) - 1) * jnp.exp(2 * self.loc + s2)


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs = _as_array(probs)
        self.probs = self.probs / self.probs.sum(-1, keepdims=True)
        super().__init__(self.probs.shape[:-1], self.probs.shape[-1:])

    def _sample(self, key, shape):
        full = shape + self._batch_shape
        logits = jnp.log(self.probs)
        draws = jr.categorical(key, logits,
                               shape=(self.total_count,) + full)
        k = self.probs.shape[-1]
        one_hot = jax.nn.one_hot(draws, k, dtype=self.probs.dtype)
        return one_hot.sum(0)

    def _log_prob(self, v):
        logits = jnp.log(self.probs)
        return (jsp.gammaln(self.total_count + 1.0)
                - jsp.gammaln(v + 1.0).sum(-1)
                + (v * logits).sum(-1))

    def _mean(self):
        return self.total_count * self.probs

    def _variance(self):
        return self.total_count * self.probs * (1 - self.probs)


class Poisson(ExponentialFamily):
    def __init__(self, rate, name=None):
        self.rate = _as_array(rate)
        super().__init__(self.rate.shape)

    def _sample(self, key, shape):
        full = shape + self._batch_shape
        return jr.poisson(key, self.rate, full).astype(self.rate.dtype)

    def _log_prob(self, v):
        return v * jnp.log(self.rate) - self.rate - jsp.gammaln(v + 1)

    def _mean(self):
        return self.rate

    def _variance(self):
        return self.rate

    def _entropy(self):
        # series approximation (exact only asymptotically), matching the
        # reference's numeric approach
        r = self.rate
        return (0.5 * jnp.log(2 * math.pi * math.e * r)
                - 1 / (12 * r) - 1 / (24 * r ** 2))


class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _as_array(loc)
        self.scale = _as_array(scale)
        super().__init__(_bcast_shape(self.loc, self.scale))

    def _rsample(self, key, shape):
        full = shape + self._batch_shape
        return self.loc + self.scale * jr.cauchy(key, full,
                                                 dtype=self.loc.dtype)

    def _log_prob(self, v):
        z = (v - self.loc) / self.scale
        return -jnp.log(math.pi * self.scale * (1 + z ** 2))

    def _entropy(self):
        return jnp.broadcast_to(jnp.log(4 * math.pi * self.scale),
                                self._batch_shape)


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df = _as_array(df)
        self.loc = _as_array(loc)
        self.scale = _as_array(scale)
        super().__init__(_bcast_shape(self.df, self.loc, self.scale))

    def _rsample(self, key, shape):
        full = shape + self._batch_shape
        return self.loc + self.scale * jr.t(key, self.df, full)

    def _log_prob(self, v):
        d, z = self.df, (v - self.loc) / self.scale
        return (jsp.gammaln((d + 1) / 2) - jsp.gammaln(d / 2)
                - 0.5 * jnp.log(d * math.pi) - jnp.log(self.scale)
                - (d + 1) / 2 * jnp.log1p(z ** 2 / d))

    def _mean(self):
        return jnp.where(self.df > 1, self.loc, jnp.nan)

    def _variance(self):
        d = self.df
        return jnp.where(d > 2, self.scale ** 2 * d / (d - 2),
                         jnp.where(d > 1, jnp.inf, jnp.nan))


class Binomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs = _as_array(probs)
        super().__init__(self.probs.shape)

    def _sample(self, key, shape):
        full = shape + self._batch_shape
        u = jr.uniform(key, (self.total_count,) + full,
                       dtype=self.probs.dtype)
        return (u < self.probs).astype(self.probs.dtype).sum(0)

    def _log_prob(self, v):
        n, p = self.total_count, self.probs
        return (jsp.gammaln(n + 1.0) - jsp.gammaln(v + 1.0)
                - jsp.gammaln(n - v + 1.0)
                + v * jnp.log(p) + (n - v) * jnp.log1p(-p))

    def _mean(self):
        return self.total_count * self.probs

    def _variance(self):
        return self.total_count * self.probs * (1 - self.probs)


class ContinuousBernoulli(Distribution):
    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs = _as_array(probs)
        self._lims = lims
        super().__init__(self.probs.shape)

    def _log_norm_const(self):
        p = self.probs
        safe = jnp.where((p < self._lims[0]) | (p > self._lims[1]),
                         p, self._lims[0] - 1e-2)
        c = jnp.log((2 * jnp.arctanh(1 - 2 * safe)) / (1 - 2 * safe))
        taylor = math.log(2.0) + 4 / 3 * (p - 0.5) ** 2
        return jnp.where((p < self._lims[0]) | (p > self._lims[1]), c,
                         taylor)

    def _log_prob(self, v):
        p = self.probs
        return (v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
                + self._log_norm_const())

    def _rsample(self, key, shape):
        full = shape + self._batch_shape
        u = jr.uniform(key, full, dtype=self.probs.dtype, minval=1e-6,
                       maxval=1 - 1e-6)
        p = self.probs
        safe = jnp.where((p < self._lims[0]) | (p > self._lims[1]),
                         p, self._lims[0] - 1e-2)
        icdf = (jnp.log1p(u * (2 * safe - 1) / (1 - safe))
                / (jnp.log(safe) - jnp.log1p(-safe)))
        return jnp.where((p < self._lims[0]) | (p > self._lims[1]), icdf, u)

    def _mean(self):
        p = self.probs
        safe = jnp.where((p < self._lims[0]) | (p > self._lims[1]),
                         p, self._lims[0] - 1e-2)
        m = safe / (2 * safe - 1) + 1 / (2 * jnp.arctanh(1 - 2 * safe))
        return jnp.where((p < self._lims[0]) | (p > self._lims[1]), m,
                         0.5 + (p - 0.5) / 3)
