"""``paddle.distribution`` — probability distributions.

TPU-native re-design of the reference package
(``python/paddle/distribution/``, 5,994 LoC): the same class surface
(Distribution base, 15+ families, Transform algebra,
TransformedDistribution, Independent, kl_divergence registry), with every
density/sampler expressed as pure jax — samples draw counter-folded threefry
keys (``paddle_tpu.framework.random``), so sampling composes with jit/pjit
instead of relying on a stateful Philox generator
(``paddle/phi/core/generator.cc``).
"""
from .distribution import Distribution  # noqa: F401
from .families import (  # noqa: F401
    Normal, Uniform, Bernoulli, Categorical, Beta, Dirichlet, Exponential,
    Gamma, Geometric, Gumbel, Laplace, LogNormal, Multinomial, Poisson,
    Cauchy, StudentT, Binomial, ContinuousBernoulli, ExponentialFamily,
)
from .transform import (  # noqa: F401
    Transform, AbsTransform, AffineTransform, ChainTransform, ExpTransform,
    IndependentTransform, PowerTransform, ReshapeTransform,
    SigmoidTransform, SoftmaxTransform, StackTransform, StickBreakingTransform,
    TanhTransform,
)
from .transformed_distribution import TransformedDistribution  # noqa: F401
from .independent import Independent  # noqa: F401
from .kl import kl_divergence, register_kl  # noqa: F401

__all__ = [
    "Distribution", "Normal", "Uniform", "Bernoulli", "Categorical", "Beta",
    "Dirichlet", "Exponential", "Gamma", "Geometric", "Gumbel", "Laplace",
    "LogNormal", "Multinomial", "Poisson", "Cauchy", "StudentT", "Binomial",
    "ContinuousBernoulli", "ExponentialFamily", "Transform", "AbsTransform",
    "AffineTransform", "ChainTransform", "ExpTransform",
    "IndependentTransform", "PowerTransform", "ReshapeTransform",
    "SigmoidTransform", "SoftmaxTransform", "StackTransform",
    "StickBreakingTransform", "TanhTransform", "TransformedDistribution",
    "Independent", "kl_divergence", "register_kl",
]
