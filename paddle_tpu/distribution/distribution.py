"""Distribution base class (ref: ``python/paddle/distribution/
distribution.py`` Distribution)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor
from ..framework.random import next_key

__all__ = ["Distribution"]


def _as_array(x, dtype=None):
    if isinstance(x, Tensor):
        x = x._data
    a = jnp.asarray(x)
    if a.dtype == jnp.float64:
        a = a.astype(jnp.float32)
    if dtype is not None:
        a = a.astype(dtype)
    if a.dtype in (jnp.int32, jnp.int64) and dtype is None:
        a = a.astype(jnp.float32)
    return a


def _wrap(x):
    return Tensor(x)


class Distribution:
    """Base of all distributions; subclasses implement the pure-jax
    ``_sample(key, shape)`` / ``_log_prob(value)`` kernels and declare
    ``batch_shape`` / ``event_shape``."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(int(d) for d in batch_shape)
        self._event_shape = tuple(int(d) for d in event_shape)

    # -- shapes -------------------------------------------------------------
    @property
    def batch_shape(self):
        return list(self._batch_shape)

    @property
    def event_shape(self):
        return list(self._event_shape)

    def _extend_shape(self, sample_shape):
        return tuple(sample_shape) + self._batch_shape + self._event_shape

    # -- core API -----------------------------------------------------------
    def sample(self, shape=()):
        """Draw without gradients."""
        return _wrap(jax.lax.stop_gradient(
            self._sample(next_key(), tuple(int(s) for s in shape))))

    def rsample(self, shape=()):
        """Reparameterized draw (gradients flow where supported)."""
        return _wrap(self._rsample(next_key(), tuple(int(s) for s in shape)))

    def log_prob(self, value):
        return _wrap(self._log_prob(_as_array(value)))

    def prob(self, value):
        return _wrap(jnp.exp(self._log_prob(_as_array(value))))

    def entropy(self):
        return _wrap(self._entropy())

    def kl_divergence(self, other):
        from .kl import kl_divergence
        return kl_divergence(self, other)

    # -- hooks ---------------------------------------------------------------
    def _sample(self, key, shape):
        return self._rsample(key, shape)

    def _rsample(self, key, shape):
        raise NotImplementedError(
            f"{type(self).__name__} does not support rsample")

    def _log_prob(self, value):
        raise NotImplementedError

    def _entropy(self):
        raise NotImplementedError

    # -- moments (optional per family) ---------------------------------------
    @property
    def mean(self):
        return _wrap(self._mean())

    @property
    def variance(self):
        return _wrap(self._variance())

    @property
    def stddev(self):
        return _wrap(jnp.sqrt(self._variance()))

    def _mean(self):
        raise NotImplementedError

    def _variance(self):
        raise NotImplementedError
