"""Bijective transforms (ref: ``python/paddle/distribution/transform.py``).

Forward/inverse/log-det-jacobian are pure jnp, so TransformedDistribution
densities compose under jit/grad for free.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..tensor import Tensor
from .distribution import _as_array, _wrap

__all__ = [
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
]


class Transform:
    _event_dim = 0  # event dims consumed by log_det_jacobian

    # public API mirrors the reference
    def forward(self, x):
        return _wrap(self._forward(_as_array(x)))

    def inverse(self, y):
        return _wrap(self._inverse(_as_array(y)))

    def forward_log_det_jacobian(self, x):
        return _wrap(self._fldj(_as_array(x)))

    def inverse_log_det_jacobian(self, y):
        y = _as_array(y)
        return _wrap(-self._fldj(self._inverse(y)))

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)

    # hooks
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _fldj(self, x):
        raise NotImplementedError


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _as_array(loc)
        self.scale = _as_array(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _fldj(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        return x


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _as_array(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _fldj(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _fldj(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _fldj(self, x):
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class AbsTransform(Transform):
    """Non-bijective |x|; inverse returns the positive branch."""

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y

    def _fldj(self, x):
        return jnp.zeros_like(x)


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _fldj(self, x):
        total = 0.0
        for t in self.transforms:
            total = total + t._fldj(x)
            x = t._forward(x)
        return total

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return tuple(shape)

    def inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return tuple(shape)


class IndependentTransform(Transform):
    """Sums the base log-det over the trailing reinterpreted dims."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        self._event_dim = self.rank

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _fldj(self, x):
        ld = self.base._fldj(x)
        return ld.sum(axis=tuple(range(-self.rank, 0)))


class ReshapeTransform(Transform):
    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)
        self._event_dim = len(self.in_event_shape)

    def _forward(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return x.reshape(batch + self.out_event_shape)

    def _inverse(self, y):
        batch = y.shape[:y.ndim - len(self.out_event_shape)]
        return y.reshape(batch + self.in_event_shape)

    def _fldj(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return jnp.zeros(batch, x.dtype)

    def forward_shape(self, shape):
        n = len(self.in_event_shape)
        return tuple(shape[:len(shape) - n]) + self.out_event_shape

    def inverse_shape(self, shape):
        n = len(self.out_event_shape)
        return tuple(shape[:len(shape) - n]) + self.in_event_shape


class SoftmaxTransform(Transform):
    """Non-bijective x -> softmax(x) (ref semantics: forward normalizes,
    inverse maps to log)."""

    _event_dim = 1

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return jnp.log(y)


class StackTransform(Transform):
    """Apply a different transform to each slice along `axis`."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = int(axis)

    def _apply(self, x, method):
        parts = jnp.moveaxis(x, self.axis, 0)
        outs = [getattr(t, method)(parts[i])
                for i, t in enumerate(self.transforms)]
        return jnp.moveaxis(jnp.stack(outs, 0), 0, self.axis)

    def _forward(self, x):
        return self._apply(x, "_forward")

    def _inverse(self, y):
        return self._apply(y, "_inverse")

    def _fldj(self, x):
        return self._apply(x, "_fldj")


class StickBreakingTransform(Transform):
    """R^{K-1} -> simplex^K (ref stickbreaking)."""

    _event_dim = 1

    def _forward(self, x):
        k = x.shape[-1]
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=x.dtype))
        z = jax.nn.sigmoid(x - offset)
        zpad = jnp.concatenate([z, jnp.ones_like(z[..., :1])], -1)
        cum = jnp.cumprod(1 - z, -1)
        cumpad = jnp.concatenate([jnp.ones_like(z[..., :1]), cum], -1)
        return zpad * cumpad

    def _inverse(self, y):
        k = y.shape[-1] - 1
        cum = 1 - jnp.cumsum(y[..., :-1], -1)
        cumshift = jnp.concatenate([jnp.ones_like(y[..., :1]),
                                    cum[..., :-1]], -1)
        z = y[..., :-1] / cumshift
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=y.dtype))
        return jnp.log(z) - jnp.log1p(-z) + offset

    def _fldj(self, x):
        k = x.shape[-1]
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=x.dtype))
        z = jax.nn.sigmoid(x - offset)
        cum = jnp.cumprod(1 - z, -1)
        cumshift = jnp.concatenate(
            [jnp.ones_like(x[..., :1]), cum[..., :-1]], -1)
        return (jnp.log(z) + jnp.log1p(-z) + jnp.log(cumshift)).sum(-1)

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)
