"""KL divergence registry (ref: ``python/paddle/distribution/kl.py``
_REGISTER_TABLE / register_kl / kl_divergence with MRO-closest match)."""
from __future__ import annotations

import math

import jax.numpy as jnp
from jax.scipy import special as jsp

from .distribution import Distribution, _wrap
from . import families as F
from .independent import Independent

__all__ = ["kl_divergence", "register_kl"]

_REGISTER_TABLE: dict = {}


def register_kl(cls_p, cls_q):
    if not (issubclass(cls_p, Distribution)
            and issubclass(cls_q, Distribution)):
        raise TypeError("cls_p and cls_q must be Distribution subclasses")

    def deco(f):
        _REGISTER_TABLE[cls_p, cls_q] = f
        return f

    return deco


def _dispatch(type_p, type_q):
    matches = [(p, q) for (p, q) in _REGISTER_TABLE
               if issubclass(type_p, p) and issubclass(type_q, q)]
    if not matches:
        raise NotImplementedError(
            f"no KL registered for ({type_p.__name__}, {type_q.__name__})")

    def total_order(pair):
        p, q = pair
        return (sum(issubclass(op, p) for (op, _) in matches),
                sum(issubclass(oq, q) for (_, oq) in matches))

    best = min(matches, key=total_order)
    return _REGISTER_TABLE[best]


def kl_divergence(p, q):
    """``paddle.distribution.kl_divergence``."""
    return _wrap(_dispatch(type(p), type(q))(p, q))


# -- closed forms ------------------------------------------------------------
@register_kl(F.Normal, F.Normal)
def _kl_normal(p, q):
    vr = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return 0.5 * (vr + t1 - 1 - jnp.log(vr))


@register_kl(F.Uniform, F.Uniform)
def _kl_uniform(p, q):
    return jnp.log((q.high - q.low) / (p.high - p.low))


@register_kl(F.Bernoulli, F.Bernoulli)
def _kl_bernoulli(p, q):
    a, b = p.probs, q.probs
    eps = 1e-7
    a = jnp.clip(a, eps, 1 - eps)
    b = jnp.clip(b, eps, 1 - eps)
    return a * (jnp.log(a) - jnp.log(b)) + \
        (1 - a) * (jnp.log1p(-a) - jnp.log1p(-b))


@register_kl(F.Categorical, F.Categorical)
def _kl_categorical(p, q):
    pp = jnp.exp(p._log_p)
    return (pp * (p._log_p - q._log_p)).sum(-1)


@register_kl(F.Beta, F.Beta)
def _kl_beta(p, q):
    sp = p.alpha + p.beta
    return (jsp.gammaln(sp) - jsp.gammaln(p.alpha) - jsp.gammaln(p.beta)
            - jsp.gammaln(q.alpha + q.beta) + jsp.gammaln(q.alpha)
            + jsp.gammaln(q.beta)
            + (p.alpha - q.alpha) * (jsp.digamma(p.alpha) - jsp.digamma(sp))
            + (p.beta - q.beta) * (jsp.digamma(p.beta) - jsp.digamma(sp)))


@register_kl(F.Dirichlet, F.Dirichlet)
def _kl_dirichlet(p, q):
    a, b = p.concentration, q.concentration
    a0 = a.sum(-1)
    return (jsp.gammaln(a0) - jsp.gammaln(a).sum(-1)
            - jsp.gammaln(b.sum(-1)) + jsp.gammaln(b).sum(-1)
            + ((a - b) * (jsp.digamma(a)
                          - jsp.digamma(a0)[..., None])).sum(-1))


@register_kl(F.Exponential, F.Exponential)
def _kl_exponential(p, q):
    r = q.rate / p.rate
    return jnp.log(p.rate) - jnp.log(q.rate) + r - 1


@register_kl(F.Gamma, F.Gamma)
def _kl_gamma(p, q):
    return ((p.concentration - q.concentration) * jsp.digamma(p.concentration)
            - jsp.gammaln(p.concentration) + jsp.gammaln(q.concentration)
            + q.concentration * (jnp.log(p.rate) - jnp.log(q.rate))
            + p.concentration * (q.rate / p.rate - 1))


@register_kl(F.Geometric, F.Geometric)
def _kl_geometric(p, q):
    return (-p._entropy()
            - jnp.log(q.probs) - (1 - p.probs) / p.probs
            * jnp.log1p(-q.probs))


@register_kl(F.Laplace, F.Laplace)
def _kl_laplace(p, q):
    # log(b2/b1) + |u1-u2|/b2 + (b1/b2) exp(-|u1-u2|/b1) - 1
    d = jnp.abs(p.loc - q.loc)
    return (jnp.log(q.scale) - jnp.log(p.scale) + d / q.scale
            + (p.scale / q.scale) * jnp.exp(-d / p.scale) - 1)


@register_kl(F.Poisson, F.Poisson)
def _kl_poisson(p, q):
    return p.rate * (jnp.log(p.rate) - jnp.log(q.rate)) - p.rate + q.rate


@register_kl(F.LogNormal, F.LogNormal)
def _kl_lognormal(p, q):
    return _kl_normal(p._base, q._base)


@register_kl(F.Gumbel, F.Gumbel)
def _kl_gumbel(p, q):
    # log(b2/b1) + g*(b1/b2 - 1) + (u1-u2)/b2
    #   + exp((u2-u1)/b2) * Gamma(1 + b1/b2) - 1   (g = Euler-Mascheroni)
    import numpy as np
    euler = float(np.euler_gamma)
    br = p.scale / q.scale
    dz = (p.loc - q.loc) / q.scale
    return (jnp.log(q.scale) - jnp.log(p.scale) + euler * (br - 1) + dz
            + jnp.exp(-dz + jsp.gammaln(1 + br)) - 1)


@register_kl(Independent, Independent)
def _kl_independent(p, q):
    if p.rank != q.rank:
        raise NotImplementedError("mismatched reinterpreted ranks")
    inner = _dispatch(type(p.base), type(q.base))(p.base, q.base)
    if p.rank:
        inner = inner.sum(axis=tuple(range(-p.rank, 0)))
    return inner
