"""TransformedDistribution (ref: ``python/paddle/distribution/
transformed_distribution.py``)."""
from __future__ import annotations

import jax.numpy as jnp

from .distribution import Distribution, _as_array
from .transform import Transform, ChainTransform

__all__ = ["TransformedDistribution"]


class TransformedDistribution(Distribution):
    def __init__(self, base, transforms):
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.base = base
        self.transform = ChainTransform(list(transforms))
        shape = tuple(base.batch_shape) + tuple(base.event_shape)
        out = self.transform.forward_shape(shape)
        nb = len(base.batch_shape)
        super().__init__(out[:nb], out[nb:])

    def _sample(self, key, shape):
        return self.transform._forward(self.base._sample(key, shape))

    def _rsample(self, key, shape):
        return self.transform._forward(self.base._rsample(key, shape))

    def _log_prob(self, value):
        x = self.transform._inverse(value)
        ld = self.transform._fldj(x)
        base_lp = self.base._log_prob(x)
        # reduce per-element log-dets over event dims if the base is
        # scalar-event but the transform didn't reduce
        if hasattr(ld, "shape") and ld.shape != base_lp.shape \
                and ld.ndim > base_lp.ndim:
            ld = ld.sum(axis=tuple(range(base_lp.ndim, ld.ndim)))
        return base_lp - ld
