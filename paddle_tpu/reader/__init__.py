"""``paddle.reader`` decorators (ref:
``python/paddle/reader/decorator.py``): composable generator
transformers from the legacy IO stack. Retained for parity — the modern
path is ``paddle_tpu.io.DataLoader``. ``xmap_readers`` uses threads
(the host-side map is IO-bound; process fan-out belongs to DataLoader's
worker pool)."""
from __future__ import annotations

import itertools
import queue as _queue
import random as _random
import threading

__all__ = ["cache", "map_readers", "shuffle", "chain", "compose",
           "buffered", "firstn", "xmap_readers"]


class _Raise:
    """Exception carrier: worker threads forward errors to the consumer
    instead of dying silently (which would either hang the consumer on a
    missing sentinel or silently truncate the stream)."""

    def __init__(self, exc):
        self.exc = exc


def cache(reader):
    """Materialize once, replay from memory on every epoch."""
    all_data = tuple(reader())

    def cache_reader():
        yield from all_data

    return cache_reader


def map_readers(func, *readers):
    """Element-wise func over zipped readers."""

    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size):
    """Buffered shuffle (reservoir of ``buf_size``)."""

    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return data_reader


def chain(*readers):
    """Concatenate readers back-to-back."""

    def reader():
        for r in readers:
            yield from r()

    return reader


def compose(*readers, **kwargs):
    """Zip readers into flattened tuples; ``check_alignment`` (default
    True) raises if they run out at different lengths."""
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum((make_tuple(o) for o in outputs), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                if any(o is None for o in outputs):
                    raise ValueError(
                        "outputs of readers are not aligned")
                yield sum((make_tuple(o) for o in outputs), ())

    return reader


def buffered(reader, size):
    """Producer-thread prefetch buffer of up to ``size`` items."""
    _end = object()

    def data_reader():
        q = _queue.Queue(maxsize=size)

        def produce():
            try:
                for d in reader():
                    q.put(d)
            except BaseException as e:  # forwarded, not swallowed
                q.put(_Raise(e))
            finally:
                q.put(_end)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _end:
                break
            if isinstance(e, _Raise):
                raise e.exc
            yield e

    return data_reader


def firstn(reader, n):
    """First n elements."""

    def firstn_reader():
        for i, item in enumerate(reader()):
            if i >= n:
                break
            yield item

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map with ``process_num`` worker threads and a
    ``buffer_size`` queue; ``order=True`` preserves input order. Errors
    in the source reader or the mapper propagate to the consumer."""
    _end = object()

    def data_reader():
        in_q = _queue.Queue(buffer_size)
        out_q = _queue.Queue(buffer_size)

        def feed():
            try:
                for i, d in enumerate(reader()):
                    in_q.put((i, d))
            except BaseException as e:
                out_q.put(_Raise(e))
            finally:
                for _ in range(process_num):
                    in_q.put(_end)

        def work():
            try:
                while True:
                    item = in_q.get()
                    if item is _end:
                        return
                    i, d = item
                    out_q.put((i, mapper(d)))
            except BaseException as e:
                out_q.put(_Raise(e))
            finally:
                out_q.put(_end)

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()

        # ordered mode: only this consumer thread touches `results`
        results = {}
        finished = 0
        next_idx = 0
        while finished < process_num:
            item = out_q.get()
            if item is _end:
                finished += 1
                continue
            if isinstance(item, _Raise):
                raise item.exc
            i, d = item
            if not order:
                yield d
                continue
            results[i] = d
            while next_idx in results:
                yield results.pop(next_idx)
                next_idx += 1

    return data_reader
