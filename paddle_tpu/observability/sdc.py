"""Silent-data-corruption sentry: cross-replica consensus fingerprints.

The failure mode this module exists for: a flipped bit in a gradient,
an optimizer slot or a parameter update corrupts training *silently* —
the value is still finite, so the numerics sentinels never trip, the
loss curve drifts instead of exploding, and by the time anyone notices
the run has burned weeks on one bad chip.  At fleet scale this is the
dominant unhandled fault class, and data parallelism already carries
the oracle needed to catch it: dp-replicated ranks hold bit-identical
params after gradient reduction, so any bit-level disagreement between
replicas IS corruption, and a majority vote names the liar.

The device-side half mirrors the numerics health packet exactly:
:func:`fingerprint_outputs` folds one tiny fused reduction per updated
tensor — the wraparound-mod-2^32 sum of the tensor's raw bits viewed
as uint32 words, bitcast to int32 — into the captured step as extra
program outputs.  One compile, bit-identical loss, no host sync on the
hot path: the monitor reads the *previous* step's fingerprint vector
at every ``PT_SDC_CADENCE``-th step, long after the device finished
it.  Any single-bit flip in any element changes the word sum, and the
per-tensor digest vector means the first divergent index names the
first divergent parameter path.

The host-side half compares fingerprints across dp ranks through a
pluggable ``exchange`` callback (:func:`store_exchange` wires it to
the coordination store for multi-process fleets; ``None`` leaves the
monitor in standalone recording mode).  Majority vote over the digest
vectors: a rank in the minority books
``pt_sdc_divergence_total{rank}``, pins a flight dump (reason
``sdc:divergence:<tensor>``) and — with halting armed, the default —
raises :class:`SdcHaltError` so the worker can exit ``EXIT_SDC`` and
the supervisor can charge the failure to hardware and quarantine the
rank.  Majority ranks book the divergent rank's counter and keep
training, so the cluster aggregator sees the divergence even after
the bad rank dies.

Contract (shared with the rest of ``observability``): zero cost while
disabled, never sync the device on the hot path, never take down the
run unless halting is armed, side-effect-free import.

Environment:
  - ``PT_SDC=1``           enable on first ``get_monitor()``
  - ``PT_SDC_CADENCE=n``   host read cadence in steps (default 16)
  - ``PT_SDC_HALT=0``      disarm the EXIT_SDC halt on self-divergence
                           (armed by default: a corrupt rank must not
                           keep training)
"""
from __future__ import annotations

import logging
import os
import sys
import threading
import zlib

logger = logging.getLogger("paddle_tpu.observability.sdc")

__all__ = [
    "SdcMonitor",
    "SdcHaltError",
    "fingerprint_outputs",
    "store_exchange",
    "get_monitor",
    "current_monitor",
    "reset_monitor",
]


class SdcHaltError(RuntimeError):
    """Raised from a monitored step when replica consensus fingered
    THIS rank's state as corrupt and halting is armed; the worker's
    designed response is ``sys.exit(EXIT_SDC)``."""


def _digest(x):
    """Device-side content digest of one tensor: the wraparound sum of
    its raw bits viewed as uint32 words, bitcast to int32.

    Any single-bit flip changes exactly one word, which changes the
    mod-2^32 sum — so the digest is sensitive to every bit while
    costing ONE fused reduction per tensor (the same budget as the
    numerics sentinel's ``sum(x*x)``).  Bitcasting — never a value
    cast — keeps the digest a statement about the bit pattern: two
    NaNs with different payloads, or -0.0 vs +0.0, digest differently.
    """
    import jax.numpy as jnp
    from jax import lax

    x = jnp.asarray(x)
    if x.dtype == jnp.bool_:
        # bools are canonical 0/1; a value cast IS the bit pattern
        words = x.astype(jnp.uint32)
    elif x.dtype.itemsize == 1:
        words = lax.bitcast_convert_type(x, jnp.uint8).astype(jnp.uint32)
    elif x.dtype.itemsize == 2:
        words = lax.bitcast_convert_type(x, jnp.uint16).astype(jnp.uint32)
    else:
        # 4-byte dtypes bitcast in place; 8-byte dtypes gain a trailing
        # axis of two words — both reduce the same way
        words = lax.bitcast_convert_type(x, jnp.uint32)
    s = jnp.sum(words.astype(jnp.uint32), dtype=jnp.uint32)
    return lax.bitcast_convert_type(s, jnp.int32)


def fingerprint_outputs(named):
    """Build the device-side fingerprint program over named arrays.

    Called at *trace time* from inside a jitted step (capture's
    ``pure``), exactly like ``numerics.health_outputs``: the returned
    vector becomes one extra program output, so the fingerprint
    compiles into the same executable — no second program, no extra
    compile, loss untouched.

    Returns ``(names, fp)`` where ``names`` is the host-side tuple
    naming each slot (sorted paths) and ``fp`` is an ``int32[n]``
    device array of per-tensor digests.  Keeping one digest per tensor
    (rather than one per step) is what lets consensus name the FIRST
    divergent parameter path, not just the divergent rank.
    """
    import jax.numpy as jnp

    names = tuple(sorted(named))
    digests = [_digest(named[name]) for name in names]
    fp = (jnp.stack(digests) if digests
          else jnp.zeros((0,), jnp.int32))
    return names, fp


class SdcMonitor:
    """Host-side half of the sentry: holds the latest fingerprint
    packet, materializes the previous one at cadence boundaries,
    exchanges it with peer ranks, and runs the majority vote."""

    def __init__(self):
        self._lock = threading.RLock()
        self.enabled = False
        self.cadence = 16
        self.halt = True
        self.exchange = None   # callable(step, digest_bytes) -> {rank: bytes}
        self.rank = 0
        self._metrics = None
        self._reset_state()

    def _reset_state(self):
        self._pending = None          # (step, names, fp) latest packet
        self._last_read_step = None
        self._steps_observed = 0
        self._reads = 0
        self._votes = 0
        self._divergences = {}        # rank -> count (this rank's view)
        self._last_divergence = None  # {step, rank, tensor, world}
        self._last_fingerprint = None # crc32 hex of the full vector

    # -- lifecycle ---------------------------------------------------

    def enable(self, cadence=None, halt=None, exchange=None, rank=None):
        with self._lock:
            self.enabled = True
            if cadence is not None:
                self.cadence = max(1, int(cadence))
            if halt is not None:
                self.halt = bool(halt)
            if exchange is not None:
                self.exchange = exchange
            if rank is not None:
                self.rank = int(rank)
            self._make_metrics()
        return self

    def disable(self):
        with self._lock:
            self.enabled = False
        return self

    def _make_metrics(self):
        if self._metrics is not None:
            return
        try:
            from .metrics import get_registry
            r = get_registry()
            self._metrics = {
                "divergences": r.counter(
                    "pt_sdc_divergence_total",
                    "Replica fingerprint divergences, by fingered rank",
                    ("rank",)),
            }
        except Exception:  # metrics are optional plumbing
            self._metrics = None

    # -- hot path ----------------------------------------------------

    def watch(self, step, names, fp):
        """Per-step hook from the captured step's replay path.

        Same asynchronous-read discipline as the numerics monitor: the
        packet inspected at a cadence boundary is the *previous* one,
        one full dispatch behind, so ``np.asarray`` finds the buffers
        already materialized and never blocks the step.  Detection
        latency is at most one cadence window.
        """
        if not self.enabled:
            return
        with self._lock:
            prev = self._pending
            self._pending = (int(step), names, fp)
            self._steps_observed += 1
            due = (prev is not None
                   and (self._last_read_step is None
                        or prev[0] - self._last_read_step >= self.cadence))
        if due:
            self._inspect(*prev)

    def flush(self):
        """Materialize and vote on the held packet now (end of run,
        drills, tests). The one place a blocking read is acceptable."""
        with self._lock:
            pending, self._pending = self._pending, None
        if pending is not None:
            self._inspect(*pending)
        return self

    # -- consensus ---------------------------------------------------

    def _inspect(self, step, names, fp):
        import numpy as np

        try:
            vec = np.ascontiguousarray(np.asarray(fp), dtype=np.int32)
        except Exception:
            # a failed read must never take down the run
            logger.debug("sdc fingerprint read failed", exc_info=True)
            return
        digest = vec.tobytes()
        with self._lock:
            self._last_read_step = step
            self._reads += 1
            self._last_fingerprint = format(
                zlib.crc32(digest) & 0xFFFFFFFF, "08x")
            exchange = self.exchange
        if exchange is None:
            return  # standalone recording mode (bench, single process)
        try:
            peers = exchange(step, digest)
        except SdcHaltError:
            raise
        except Exception:
            # a dead peer or store hiccup is a LOUD failure with its
            # own recovery path; the sentry only judges what it can see
            logger.warning("sdc fingerprint exchange failed at step %s",
                           step, exc_info=True)
            return
        self._vote(step, names, vec, digest, dict(peers or {}))

    def _vote(self, step, names, vec, digest, peers):
        import numpy as np

        peers.setdefault(self.rank, digest)
        if len(peers) < 2:
            return  # no quorum of one
        tally = {}
        for _r, d in peers.items():
            tally[d] = tally.get(d, 0) + 1
        majority = max(tally, key=lambda d: (tally[d], d))
        with self._lock:
            self._votes += 1
        if tally[majority] <= len(peers) // 2:
            # no strict majority: an even split names nobody — refuse
            # to guess rather than quarantine half the fleet
            logger.warning(
                "sdc consensus inconclusive at step %s: %d distinct "
                "fingerprints over %d ranks", step, len(tally), len(peers))
            return
        maj_vec = np.frombuffer(majority, dtype=np.int32)
        for rank in sorted(peers):
            if peers[rank] == majority:
                continue
            peer_vec = np.frombuffer(peers[rank], dtype=np.int32)
            tensor = None
            if peer_vec.shape == maj_vec.shape:
                diff = np.nonzero(peer_vec != maj_vec)[0]
                if diff.size and diff[0] < len(names):
                    tensor = names[diff[0]]
            self.record_divergence(rank, tensor=tensor, step=step,
                                   world=len(peers))

    # -- divergence sink ---------------------------------------------

    def record_divergence(self, rank, tensor=None, step=None, world=None):
        """Book one consensus verdict against ``rank``: host counter
        (always), metric counter (when enabled), a warning naming the
        rank and tensor — and, when the fingered rank is THIS process,
        a flight dump plus :class:`SdcHaltError` if halting is armed.
        """
        rank = int(rank)
        is_self = rank == self.rank
        with self._lock:
            self._divergences[rank] = self._divergences.get(rank, 0) + 1
            first = self._divergences[rank] == 1
            self._last_divergence = {
                "step": step, "rank": rank, "tensor": tensor,
                "world": world,
            }
            metrics = self._metrics if self.enabled else None
        if metrics is not None:
            try:
                metrics["divergences"].inc(rank=str(rank))
            except Exception:
                pass
        logger.warning(
            "sdc divergence: rank=%s tensor=%s step=%s%s", rank, tensor,
            step, " (this rank)" if is_self else "")
        if not is_self:
            return
        # the flight dump pins the FIRST self-divergence: the most
        # specific artifact — which tensor's bits disagree — recorded
        # before the halt tears the process down
        reason = "sdc:divergence:%s" % (tensor or "")
        tr_mod = (sys.modules.get("paddle_tpu.observability.trace")
                  if first else None)
        if tr_mod is not None:
            try:
                tr = tr_mod.current_tracer()
                if tr is not None and tr.enabled:
                    tr.flight_dump(reason=reason)
            except Exception:
                pass
        if self.halt:
            raise SdcHaltError(
                "sdc sentry: replica consensus fingered this rank "
                "(process_index %s) as corrupt at step %s, first "
                "divergent tensor %r" % (rank, step, tensor))

    # -- reporting ---------------------------------------------------

    def divergence_count(self, rank=None):
        with self._lock:
            if rank is not None:
                return self._divergences.get(int(rank), 0)
            return sum(self._divergences.values())

    def snapshot(self):
        with self._lock:
            return {
                "enabled": self.enabled,
                "cadence": self.cadence,
                "halt": self.halt,
                "rank": self.rank,
                "steps_observed": self._steps_observed,
                "reads": self._reads,
                "votes": self._votes,
                "divergences": {str(r): n
                                for r, n in sorted(self._divergences.items())},
                "divergences_total": sum(self._divergences.values()),
                "last_divergence": (dict(self._last_divergence)
                                    if self._last_divergence else None),
                "last_fingerprint": self._last_fingerprint,
            }


def store_exchange(store, run_id, rank, world, timeout=30.0):
    """Wire a monitor's ``exchange`` to the coordination store.

    Each rank publishes its digest under an idempotent per-rank key
    (``sdc/<run_id>/<step>/<rank>``, hex-encoded) and polls for every
    peer's with a bounded wait — the all_gather of the fingerprint
    vector, host-side.  A peer that dies before publishing surfaces as
    a TimeoutError, which the monitor downgrades to a warning: dead
    ranks are the supervisor's department, silent ones this module's.
    """
    rank = int(rank)
    world = int(world)

    def exchange(step, digest):
        store.set("sdc/%s/%d/%d" % (run_id, step, rank), digest.hex())
        out = {}
        for r in range(world):
            if r == rank:
                out[r] = digest
                continue
            v = store.get("sdc/%s/%d/%d" % (run_id, step, r),
                          wait=True, timeout=timeout)
            if isinstance(v, bytes):
                v = v.decode("ascii")
            out[r] = bytes.fromhex(v)
        return out

    return exchange


_monitor = None
_monitor_lock = threading.Lock()


def _truthy(v):
    return str(v).lower() not in ("", "0", "false", "no", "off", "none")


def get_monitor():
    """Process singleton; first call applies PT_SDC_* env config."""
    global _monitor
    with _monitor_lock:
        if _monitor is None:
            _monitor = SdcMonitor()
            if _truthy(os.environ.get("PT_SDC", "")):
                _monitor.enable(
                    cadence=os.environ.get("PT_SDC_CADENCE") or None,
                    halt=_truthy(os.environ.get("PT_SDC_HALT", "1")),
                )
        return _monitor


def current_monitor():
    """The singleton if it exists, else None — read-only accessor that
    never triggers env-based enablement (hot paths use this)."""
    return _monitor


def reset_monitor():
    """Drop the singleton (tests)."""
    global _monitor
    with _monitor_lock:
        _monitor = None
