"""Cluster-level observability: scrape every rank, merge, re-serve.

The per-process half of the package gives each rank its own
``/metrics`` endpoint with ``process_index``/``run_id`` const labels
(:meth:`~.metrics.MetricsRegistry.set_const_labels`, stamped by
``TrainingTelemetry.enable``) and publishes the endpoint into the
coordination store (``TrainingTelemetry.publish_endpoint``).  This
module is the other half — the one process that answers cluster-level
questions:

 - :func:`parse_prometheus_text`  text exposition 0.0.4 → families
 - :func:`merge_scrapes`          cross-rank merge: counters summed
   (``process_index`` dropped), histogram buckets summed bucket-by-
   bucket (cumulative counts add because sums of cumulatives are the
   cumulative of the sum; mismatched ``le`` layouts are a
   :class:`MergeConflict`), gauges kept per-rank labeled (an identical
   label set from two ranks is a conflict — it would silently
   last-write-win)
 - :class:`ClusterAggregator`     discovery (store keys or a static
   map) + a bounded-time scrape loop + derived cluster metrics:
   cross-rank step-time skew (max−min of per-rank means), the p95
   straggler ratio (slowest rank's p95 / cluster-median p95), per-rank
   liveness, and a recompile-storm alarm that trips on sentinel counts
   SUMMED across ranks (one rank tripping N times or N ranks tripping
   once look the same to the job)
 - ``python -m paddle_tpu.observability.aggregator``  serves the
   merged view as cluster ``/metrics`` + ``/healthz`` (HTTP 503 while
   the storm alarm is up)
 - :func:`cluster_snapshot`       the compact dict bench records attach

Liveness contract: a rank going silent must never stall the cluster
view.  Every scrape is bounded by ``scrape_timeout``; a rank whose
last good scrape is older than ``stale_after`` is dropped from merges
but stays visible as ``pt_rank_up{process_index=...} 0``.

Import contract: stdlib-only at module level (no jax, no
``paddle_tpu.distributed``) so the aggregator process stays cheap to
spawn; ``ResilientStore`` is imported lazily by the CLI.

Long-horizon view: every render also appends a compact cluster point
(ranks up, skew, straggler ratio, storm count) to a
:class:`RetentionBuffer` — a time-bounded, memory-capped history whose
resolution degrades gracefully with age (old points thin out, recent
points stay dense), so a week-long run's aggregator never grows
without bound.  Window set by ``PT_AGGREGATOR_RETENTION`` seconds
(0 disables).

Env (all read by :func:`main` as flag defaults): ``PT_AGGREGATOR_PORT``
``PT_AGGREGATOR_INTERVAL`` ``PT_AGGREGATOR_STALE_AFTER``
``PT_AGGREGATOR_SCRAPE_TIMEOUT`` ``PT_AGGREGATOR_STORM_THRESHOLD``
``PT_AGGREGATOR_SERVE_THRESHOLD`` ``PT_AGGREGATOR_RETENTION``.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
import threading
import time
import urllib.error
import urllib.request

from .logs import get_logger
from .metrics import _escape_help, _fmt, _labels_text

__all__ = [
    "MergeConflict", "parse_prometheus_text", "merge_scrapes",
    "render_exposition", "bucket_percentile", "RetentionBuffer",
    "ClusterAggregator", "cluster_snapshot", "endpoint_key",
    "world_key", "main",
]

logger = get_logger(__name__)

_INF = float("inf")

# -- store key conventions ---------------------------------------------------
# mirrored as core.store_server.obs_endpoint_key/obs_world_key (which
# stdlib-only tools share) WITHOUT importing core here; the test suite
# pins the two formats equal.


def endpoint_key(run_id, process_index):
    """Store key under which rank ``process_index`` publishes its
    "host:port" metrics endpoint."""
    return f"obs/{run_id}/endpoint/{int(process_index)}"


def world_key(run_id):
    """Store key holding run ``run_id``'s expected world size."""
    return f"obs/{run_id}/world"


# -- exposition parsing ------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)(?:\s+-?\d+)?$")

_HISTO_SUFFIXES = ("_bucket", "_sum", "_count")


def _parse_value(s):
    if s == "+Inf":
        return _INF
    if s == "-Inf":
        return -_INF
    return float(s)  # float("NaN") handles NaN


def _unescape_label(s):
    out = []
    i, n = 0, len(s)
    while i < n:
        c = s[i]
        if c == "\\" and i + 1 < n:
            out.append({"n": "\n", '"': '"', "\\": "\\"}.get(
                s[i + 1], "\\" + s[i + 1]))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _parse_labels(block):
    """``name="value",...`` inside the braces; values may contain
    escaped quotes/backslashes/newlines and commas."""
    labels = {}
    i, n = 0, len(block)
    while i < n:
        if block[i] in ", ":
            i += 1
            continue
        eq = block.find("=", i)
        if eq < 0 or eq + 1 >= n or block[eq + 1] != '"':
            raise ValueError(f"malformed label block: {block!r}")
        name = block[i:eq].strip()
        j = eq + 2
        buf = []
        while j < n and block[j] != '"':
            if block[j] == "\\" and j + 1 < n:
                buf.append(block[j:j + 2])
                j += 2
            else:
                buf.append(block[j])
                j += 1
        if j >= n:
            raise ValueError(f"unterminated label value: {block!r}")
        labels[name] = _unescape_label("".join(buf))
        i = j + 1
    return labels


def parse_prometheus_text(text):
    """Parse text exposition 0.0.4 into ``{family_name: {"kind",
    "help", "samples": [(sample_name, labels_dict, value), ...]}}``.

    Histogram children (``*_bucket``/``*_sum``/``*_count``) are folded
    into their family (declared by the preceding ``# TYPE``).  Raises
    ``ValueError`` on a malformed line — a scrape either parses or is
    discarded whole.
    """
    families: dict = {}

    def fam(name):
        f = families.get(name)
        if f is None:
            f = families[name] = {"kind": "untyped", "help": "",
                                  "samples": []}
        return f

    for line in text.splitlines():
        stripped = line.strip()
        if not stripped:
            continue
        if stripped.startswith("# TYPE "):
            name, _, kind = stripped[len("# TYPE "):].partition(" ")
            fam(name)["kind"] = kind.strip() or "untyped"
            continue
        if stripped.startswith("# HELP "):
            name, _, help_ = stripped[len("# HELP "):].partition(" ")
            fam(name)["help"] = help_
            continue
        if stripped.startswith("#"):
            continue
        m = _SAMPLE_RE.match(stripped)
        if not m:
            raise ValueError(f"unparseable exposition line: {line!r}")
        sname, lblock, raw = m.group(1), m.group(2), m.group(3)
        try:
            value = _parse_value(raw)
        except ValueError:
            raise ValueError(f"bad sample value in line: {line!r}")
        labels = _parse_labels(lblock) if lblock else {}
        family = sname
        for suf in _HISTO_SUFFIXES:
            base = sname[:-len(suf)] if sname.endswith(suf) else None
            if base and base in families \
                    and families[base]["kind"] == "histogram":
                family = base
                break
        fam(family)["samples"].append((sname, labels, value))
    return families


# -- cross-rank merge --------------------------------------------------------


class MergeConflict(ValueError):
    """Two ranks' series cannot be merged: kind mismatch, identical
    gauge label sets, or misaligned histogram bucket layouts."""


def _label_key(labels, drop=()):
    return tuple(sorted((k, v) for k, v in labels.items()
                        if k not in drop))


def _merge_family(m, name, fam, rank, drop):
    kind = m["kind"]
    if kind == "counter":
        for sname, labels, value in fam["samples"]:
            key = _label_key(labels, drop)
            m["series"][key] = m["series"].get(key, 0.0) + value
    elif kind == "histogram":
        staged: dict = {}
        for sname, labels, value in fam["samples"]:
            if sname.endswith("_bucket"):
                le = _parse_value(labels.get("le", "+Inf"))
                rest = {k: v for k, v in labels.items() if k != "le"}
                h = staged.setdefault(_label_key(rest, drop),
                                      {"buckets": {}, "sum": 0.0,
                                       "count": 0.0})
                h["buckets"][le] = value
            elif sname.endswith("_sum"):
                h = staged.setdefault(_label_key(labels, drop),
                                      {"buckets": {}, "sum": 0.0,
                                       "count": 0.0})
                h["sum"] = value
            elif sname.endswith("_count"):
                h = staged.setdefault(_label_key(labels, drop),
                                      {"buckets": {}, "sum": 0.0,
                                       "count": 0.0})
                h["count"] = value
            else:
                raise MergeConflict(
                    f"{name}: unexpected histogram sample {sname!r}")
        for key, h in staged.items():
            cur = m["series"].get(key)
            if cur is None:
                m["series"][key] = h
            else:
                if set(cur["buckets"]) != set(h["buckets"]):
                    raise MergeConflict(
                        f"{name}{dict(key)}: histogram bucket layouts "
                        f"differ across ranks (rank {rank} disagrees) "
                        f"— cumulative counts cannot be summed")
                for le, c in h["buckets"].items():
                    cur["buckets"][le] += c
                cur["sum"] += h["sum"]
                cur["count"] += h["count"]
    else:  # gauge / untyped: keep the full per-rank label set
        for sname, labels, value in fam["samples"]:
            key = _label_key(labels)
            if key in m["series"]:
                raise MergeConflict(
                    f"{name}{dict(key)}: identical label set exported "
                    f"by two scrapes (second seen on rank {rank}) — a "
                    f"per-rank series needs a process_index label, "
                    f"merging would silently last-write-win")
            m["series"][key] = value


def merge_scrapes(scrapes, drop_labels=("process_index",),
                  on_conflict="raise"):
    """Merge per-rank parsed scrapes (``{rank: families}`` as returned
    by :func:`parse_prometheus_text`) into one cluster view.

    Returns ``(merged, conflicts)`` where ``merged`` maps family name →
    ``{"kind", "help", "series"}`` (counter/gauge series keyed by label
    tuple → value; histogram series → ``{"buckets": {le: cum}, "sum",
    "count"}``) and ``conflicts`` lists human-readable rejections.
    ``on_conflict="raise"`` (tests, CI) raises :class:`MergeConflict`
    on the first one; ``"skip"`` (the serving loop) drops the whole
    conflicted family and keeps going — a bad series must not take
    down the cluster view.
    """
    if on_conflict not in ("raise", "skip"):
        raise ValueError(f"on_conflict must be raise|skip, "
                         f"got {on_conflict!r}")
    drop = tuple(drop_labels)
    merged: dict = {}
    rejected: set = set()
    conflicts: list = []
    for rank in sorted(scrapes):
        for name, fam in scrapes[rank].items():
            if name in rejected:
                continue
            m = merged.get(name)
            try:
                if m is None:
                    m = merged[name] = {"kind": fam["kind"],
                                        "help": fam["help"],
                                        "series": {}}
                elif m["kind"] != fam["kind"]:
                    raise MergeConflict(
                        f"{name}: kind {m['kind']} vs {fam['kind']} "
                        f"(rank {rank}) — same name must mean the "
                        f"same instrument on every rank")
                _merge_family(m, name, fam, rank, drop)
            except MergeConflict as e:
                if on_conflict == "raise":
                    raise
                conflicts.append(str(e))
                rejected.add(name)
                merged.pop(name, None)
    return merged, conflicts


def render_exposition(merged):
    """Merged families (from :func:`merge_scrapes`) → exposition text,
    deterministically ordered."""
    out = []
    for name in sorted(merged):
        m = merged[name]
        out.append(f"# HELP {name} {_escape_help(m['help'])}")
        out.append(f"# TYPE {name} {m['kind']}")
        if m["kind"] == "histogram":
            for key in sorted(m["series"]):
                h = m["series"][key]
                names = [k for k, _ in key]
                values = [v for _, v in key]
                for le in sorted(h["buckets"]):
                    lt = _labels_text(names, values,
                                      extra=(("le", _fmt(le)),))
                    out.append(f"{name}_bucket{lt} "
                               f"{_fmt(h['buckets'][le])}")
                lbl = _labels_text(names, values)
                out.append(f"{name}_sum{lbl} {_fmt(h['sum'])}")
                out.append(f"{name}_count{lbl} {_fmt(h['count'])}")
        else:
            for key in sorted(m["series"]):
                names = [k for k, _ in key]
                values = [v for _, v in key]
                out.append(f"{name}{_labels_text(names, values)} "
                           f"{_fmt(m['series'][key])}")
    return "\n".join(out) + ("\n" if out else "")


def bucket_percentile(buckets, count, q):
    """Bucket-interpolated percentile from cumulative ``{le: cum}`` —
    the parsed-scrape twin of :meth:`.metrics.Histogram.percentile`
    (None while empty)."""
    if not count:
        return None
    target = q * count
    prev_cum, lo = 0.0, 0.0
    for le in sorted(buckets):
        cum = buckets[le]
        n = cum - prev_cum
        if cum >= target and n:
            if le == _INF:
                return lo
            return lo + (le - lo) * ((target - prev_cum) / n)
        prev_cum = cum
        if le != _INF:
            lo = le
    return lo


def _rank_step_stats(families):
    """Per-mode ``{count, mean, p50, p95}`` from one rank's
    ``pt_step_time_seconds`` (empty dict when the rank has none)."""
    fam = families.get("pt_step_time_seconds")
    if fam is None:
        return {}
    per_mode: dict = {}
    for sname, labels, value in fam["samples"]:
        mode = labels.get("mode", "")
        rec = per_mode.setdefault(mode, {"buckets": {}, "sum": 0.0,
                                         "count": 0.0})
        if sname.endswith("_bucket"):
            rec["buckets"][_parse_value(labels.get("le", "+Inf"))] = value
        elif sname.endswith("_sum"):
            rec["sum"] = value
        elif sname.endswith("_count"):
            rec["count"] = value
    out = {}
    for mode, rec in per_mode.items():
        c = rec["count"]
        out[mode] = {
            "count": int(c),
            "mean": (rec["sum"] / c) if c else None,
            "p50": bucket_percentile(rec["buckets"], c, 0.50),
            "p95": bucket_percentile(rec["buckets"], c, 0.95),
        }
    return out


def _rank_serve_stats(families):
    """One rank's ``pt_serve_request_latency_seconds`` histogram as
    ``{buckets, sum, count}`` (None when the rank serves nothing).
    Bucket maps are summable across ranks — all serve histograms share
    the default log-bucket ladder."""
    fam = families.get("pt_serve_request_latency_seconds")
    if fam is None:
        return None
    buckets: dict = {}
    total_sum, count = 0.0, 0.0
    for sname, labels, value in fam["samples"]:
        if sname.endswith("_bucket"):
            le = _parse_value(labels.get("le", "+Inf"))
            buckets[le] = buckets.get(le, 0.0) + value
        elif sname.endswith("_sum"):
            total_sum += value
        elif sname.endswith("_count"):
            count += value
    return {"buckets": buckets, "sum": total_sum, "count": count}


def _family_total(families, name):
    """Sum of every sample of a counter family (0.0 when absent)."""
    fam = families.get(name)
    if fam is None:
        return 0.0
    return sum(v for sname, _labels, v in fam["samples"]
               if sname == name)


def _gauge_value(families, name):
    """First sample value of a (labelless) gauge family, or None."""
    fam = families.get(name)
    if fam is None:
        return None
    for sname, _labels, v in fam["samples"]:
        if sname == name:
            return v
    return None


def _labeled_gauge_value(families, name, **labels):
    """First sample of a labeled gauge matching every given label
    pair, or None (identity labels like process_index are ignored —
    the caller matches on semantic labels such as ``stat``)."""
    fam = families.get(name)
    if fam is None:
        return None
    want = {(str(k), str(v)) for k, v in labels.items()}
    for sname, slabels, v in fam["samples"]:
        if sname == name and want.issubset(set(slabels.items())):
            return v
    return None


def _median(xs):
    s = sorted(xs)
    n = len(s)
    if not n:
        return None
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


# -- long-horizon retention --------------------------------------------------


class RetentionBuffer:
    """Time-bounded, memory-capped history of (ts, point) samples.

    Two limits compose: points older than ``retention`` seconds are
    evicted, and the buffer never holds more than ``max_points``
    regardless of the window.  Hitting the cap triggers a halving-style
    downsample — every other point in the OLDER half is dropped — so a
    scrape cadence far faster than the window degrades old-history
    resolution instead of either evicting recent points or growing
    unbounded.  All methods are cheap enough for the render path; the
    caller serializes access (the aggregator renders under one thread).
    """

    def __init__(self, retention=3600.0, max_points=512):
        self.retention = float(retention)
        self.max_points = max(int(max_points), 8)
        self._points: list = []  # [(ts, point), ...] ts-ascending
        self.downsampled_total = 0

    def append(self, ts, point):
        self._points.append((float(ts), point))
        cutoff = float(ts) - self.retention
        i = 0
        n = len(self._points)
        while i < n and self._points[i][0] < cutoff:
            i += 1
        if i:
            del self._points[:i]
        if len(self._points) > self.max_points:
            half = len(self._points) // 2
            old, recent = self._points[:half], self._points[half:]
            kept = old[::2]
            self.downsampled_total += len(old) - len(kept)
            self._points = kept + recent

    def points(self):
        return list(self._points)

    def summary(self):
        pts = self._points
        return {
            "retention_seconds": self.retention,
            "max_points": self.max_points,
            "points": len(pts),
            "span_seconds": (round(pts[-1][0] - pts[0][0], 3)
                             if len(pts) > 1 else 0.0),
            "downsampled_total": self.downsampled_total,
        }


# -- the aggregator ----------------------------------------------------------


class ClusterAggregator:
    """Discover rank endpoints, scrape them on a bounded clock, merge,
    and derive cluster metrics (see module docstring for semantics).

    ``endpoints`` is a static ``{rank: "host:port"}`` map; ``store``
    (any TCPStore-shaped client, normally a ``ResilientStore``) adds
    dynamic discovery through the ``obs/<run_id>/...`` keys — both may
    be used together, the store refreshing/overriding the static map.
    """

    def __init__(self, *, endpoints=None, store=None, run_id="local",
                 stale_after=5.0, scrape_timeout=2.0, storm_threshold=1,
                 anomaly_threshold=10, sdc_threshold=1, mem_threshold=0,
                 serve_threshold=0.0, shed_threshold=0.0,
                 interval=1.0, drop_labels=("process_index",),
                 retention=3600.0, history_max_points=512):
        self.run_id = str(run_id)
        self._history = (RetentionBuffer(retention, history_max_points)
                         if retention and retention > 0 else None)
        self.stale_after = float(stale_after)
        self.scrape_timeout = float(scrape_timeout)
        self.storm_threshold = int(storm_threshold)
        self.anomaly_threshold = int(anomaly_threshold)
        # silent-data-corruption trip: consensus divergence verdicts
        # summed over fresh ranks at/over this flip /healthz to 503
        # (0 disables).  Default 1 — a single fingered rank is already
        # a hardware incident, not noise
        self.sdc_threshold = int(sdc_threshold)
        # near-OOM trip: any rank's bytes_in_use at/over this flips
        # /healthz to 503 (0 disables — there is no portable default
        # limit, HBM size varies by device generation)
        self.mem_threshold = int(mem_threshold or 0)
        # serving saturation trip: cluster p99 request latency at/over
        # this many seconds flips /healthz to 503 (0 disables)
        self.serve_threshold = float(serve_threshold or 0.0)
        # shed-storm trip: fleet shed ratio (shed / (shed + accepted))
        # at/over this fraction flips /healthz to 503 (0 disables) —
        # sustained shedding means the fleet is undersized or a replica
        # fell out and the survivors are drowning
        self.shed_threshold = float(shed_threshold or 0.0)
        self.interval = float(interval)
        self.drop_labels = tuple(drop_labels)
        self._store = store
        self._endpoints = {int(r): str(ep)
                           for r, ep in (endpoints or {}).items()}
        self._scrapes: dict = {}  # rank -> {"ts", "families", "error"}
        self._conflicts_total = 0
        self._scrape_errors_total = 0
        self._lock = threading.Lock()
        self._text = "\n".join([
            "# HELP pt_cluster_ranks_up ranks scraped fresh",
            "# TYPE pt_cluster_ranks_up gauge",
            "pt_cluster_ranks_up 0",
        ]) + "\n"
        self._health = {"ok": True, "run_id": self.run_id,
                        "ranks_discovered": 0, "ranks_up": 0}
        self._thread = None
        self._stop = threading.Event()

    # -- discovery / scraping -----------------------------------------------

    def discover(self):
        """Refresh the rank → endpoint map from the store (no-op
        without one).  Discovery failures are logged, never raised —
        the loop keeps serving the last known endpoints."""
        if self._store is not None:
            try:
                raw = self._store.get(world_key(self.run_id), wait=False)
                world = int(raw.decode("ascii")) if raw else 0
                for r in range(world):
                    v = self._store.get(endpoint_key(self.run_id, r),
                                        wait=False)
                    if v:
                        self._endpoints[r] = \
                            v.decode("ascii").strip()
            except Exception as e:
                logger.warning("aggregator discovery failed (will "
                               "retry): %s", e)
        return dict(self._endpoints)

    def scrape_once(self):
        """One bounded pass: scrape every known endpoint (each GET
        capped at ``scrape_timeout`` — a dead rank costs one timeout,
        never a hang), then re-render the merged view."""
        for rank, ep in sorted(self.discover().items()):
            url = f"http://{ep}/metrics"
            try:
                with urllib.request.urlopen(
                        url, timeout=self.scrape_timeout) as resp:
                    text = resp.read().decode("utf-8")
                families = parse_prometheus_text(text)
            except Exception as e:
                self._scrape_errors_total += 1
                err = f"{type(e).__name__}: {e}"
                prev = self._scrapes.get(rank)
                if prev is None:
                    self._scrapes[rank] = {"ts": None, "families": None,
                                           "error": err}
                else:
                    prev["error"] = err  # keep the last good families
                continue
            self._scrapes[rank] = {"ts": time.monotonic(),
                                   "families": families, "error": None}
        self._render()
        return self

    # -- merged view ----------------------------------------------------------

    def _render(self):
        now = time.monotonic()
        fresh = {}
        meta = {}
        for rank, s in sorted(self._scrapes.items()):
            age = (now - s["ts"]) if s["ts"] is not None else None
            up = age is not None and age <= self.stale_after
            meta[rank] = {"up": up, "age": age, "error": s["error"]}
            if up:
                fresh[rank] = s["families"]
        merged, conflicts = merge_scrapes(
            fresh, drop_labels=self.drop_labels, on_conflict="skip")
        for c in conflicts:
            logger.warning("aggregator merge conflict (family "
                           "dropped): %s", c)
        self._conflicts_total += len(conflicts)

        # derived cluster families, rendered as extra exposition text
        extra = []

        def gauge(name, help_, samples):
            extra.append(f"# HELP {name} {_escape_help(help_)}")
            extra.append(f"# TYPE {name} gauge")
            for labels, value in samples:
                extra.append(f"{name}{_labels_text([], [], extra=labels)}"
                             f" {_fmt(value)}")

        def counter(name, help_, value):
            extra.append(f"# HELP {name} {_escape_help(help_)}")
            extra.append(f"# TYPE {name} counter")
            extra.append(f"{name} {_fmt(value)}")

        gauge("pt_cluster_ranks",
              "ranks with a discovered metrics endpoint",
              [((), len(self._endpoints))])
        gauge("pt_cluster_ranks_up",
              "ranks whose last scrape is fresher than stale_after",
              [((), len(fresh))])
        gauge("pt_rank_up",
              "1 while the rank's scrape is fresh, 0 once stale",
              [((("process_index", str(r)),), 1 if m["up"] else 0)
               for r, m in meta.items()])
        gauge("pt_rank_scrape_age_seconds",
              "age of the rank's last successful scrape",
              [((("process_index", str(r)),), round(m["age"], 3))
               for r, m in meta.items() if m["age"] is not None])

        # per-rank step stats + cross-rank skew / straggler ratio
        stats = {r: _rank_step_stats(f) for r, f in fresh.items()}
        rank_samples = []
        for r, per_mode in sorted(stats.items()):
            for mode, st in sorted(per_mode.items()):
                for qname in ("p50", "p95"):
                    if st[qname] is not None:
                        rank_samples.append((
                            (("mode", mode),
                             ("process_index", str(r)),
                             ("quantile", qname)), st[qname]))
        gauge("pt_rank_step_time_seconds",
              "per-rank step-time quantiles (bucket-interpolated from "
              "the rank's own histogram)", rank_samples)

        modes = sorted({m for per in stats.values() for m in per})
        skew_samples, ratio_samples = [], []
        skew_by_mode, ratio_by_mode = {}, {}
        for mode in modes:
            means = [per[mode]["mean"] for per in stats.values()
                     if mode in per and per[mode]["mean"] is not None]
            p95s = [per[mode]["p95"] for per in stats.values()
                    if mode in per and per[mode]["p95"] is not None]
            if means:
                skew = max(means) - min(means)
                skew_by_mode[mode] = skew
                skew_samples.append(((("mode", mode),), skew))
            med = _median(p95s)
            if med:
                ratio = max(p95s) / med
                ratio_by_mode[mode] = ratio
                ratio_samples.append(((("mode", mode),), ratio))
        gauge("pt_step_time_skew_seconds",
              "cross-rank step-time skew: max minus min of per-rank "
              "mean step time (stragglers dominate synchronous SPMD)",
              skew_samples)
        gauge("pt_step_time_straggler_ratio",
              "slowest rank's p95 step time over the cluster-median "
              "p95 (1.0 = perfectly even)", ratio_samples)

        # recompile-storm alarm on the CROSS-RANK aggregate
        storms_total = sum(
            _family_total(f, "pt_recompile_storms_total")
            for f in fresh.values())
        alarm = (self.storm_threshold > 0
                 and storms_total >= self.storm_threshold)
        counter("pt_cluster_recompile_storms_total",
                "recompile-sentinel trips summed across ranks",
                storms_total)
        gauge("pt_cluster_recompile_storm_alarm",
              "1 while summed sentinel trips >= the storm threshold",
              [((), 1 if alarm else 0)])
        counter("pt_cluster_merge_conflicts_total",
                "families dropped from the merged view over this "
                "aggregator's lifetime", self._conflicts_total)
        counter("pt_cluster_scrape_errors_total",
                "failed scrape attempts (timeouts, refused "
                "connections, parse errors)", self._scrape_errors_total)

        # fleet goodput: the min is the number that matters — one rank
        # stuck compiling or waiting on data gates every synchronous
        # step, so the fleet's effective goodput is its worst rank's
        goodputs = {r: _gauge_value(f, "pt_goodput_fraction")
                    for r, f in fresh.items()}
        goodputs = {r: v for r, v in goodputs.items() if v is not None}
        cluster_goodput = {}
        if goodputs:
            vals = list(goodputs.values())
            cluster_goodput = {"min": min(vals),
                               "mean": sum(vals) / len(vals)}
            gauge("pt_cluster_goodput",
                  "fleet goodput over fresh ranks reporting "
                  "pt_goodput_fraction (min gates synchronous steps)",
                  [((("stat", "min"),), cluster_goodput["min"]),
                   ((("stat", "mean"),), cluster_goodput["mean"])])

        # anomaly-storm alarm, mirroring the recompile-storm trip: a
        # fleet-wide burst of numerics anomalies flips /healthz to 503
        anomalies_total = sum(
            _family_total(f, "pt_numerics_anomalies_total")
            for f in fresh.values())
        anomaly_alarm = (self.anomaly_threshold > 0
                         and anomalies_total >= self.anomaly_threshold)
        counter("pt_cluster_numerics_anomalies_total",
                "numerics anomalies summed across ranks",
                anomalies_total)
        gauge("pt_cluster_numerics_anomaly_alarm",
              "1 while summed numerics anomalies >= the anomaly "
              "threshold", [((), 1 if anomaly_alarm else 0)])

        # silent-data-corruption alarm: consensus fingerprint verdicts
        # booked by ANY fresh rank (pt_sdc_divergence_total carries the
        # fingered rank as a label; the sum counts verdicts fleet-wide)
        sdc_total = sum(
            _family_total(f, "pt_sdc_divergence_total")
            for f in fresh.values())
        sdc_alarm = (self.sdc_threshold > 0
                     and sdc_total >= self.sdc_threshold)
        counter("pt_cluster_sdc_divergences_total",
                "SDC consensus divergence verdicts summed across ranks",
                sdc_total)
        gauge("pt_cluster_sdc_alarm",
              "1 while summed SDC divergence verdicts >= the SDC "
              "threshold", [((), 1 if sdc_alarm else 0)])

        # device-memory skew + the near-OOM trip: a rank whose
        # allocator is pinned at the limit stalls (or kills) every
        # synchronous step, and uneven bytes_in_use across an SPMD
        # fleet means uneven sharding — both are fleet-level signals.
        # The watermark gauge (memory monitor) is preferred; the
        # coarse telemetry gauge is the fallback.
        rank_mem = {}
        for r, f in fresh.items():
            v = _labeled_gauge_value(f, "pt_memory_watermark_bytes",
                                     stat="bytes_in_use")
            if v is None:
                v = _labeled_gauge_value(f, "pt_device_memory_bytes",
                                         stat="bytes_in_use")
            if v is not None:
                rank_mem[r] = v
        mem_skew = (max(rank_mem.values()) - min(rank_mem.values())
                    if rank_mem else None)
        mem_max = max(rank_mem.values()) if rank_mem else None
        mem_alarm = (self.mem_threshold > 0 and mem_max is not None
                     and mem_max >= self.mem_threshold)
        if rank_mem:
            gauge("pt_cluster_memory_bytes",
                  "fleet device-memory bytes_in_use over fresh ranks",
                  [((("stat", "max"),), mem_max),
                   ((("stat", "min"),), min(rank_mem.values()))])
            gauge("pt_cluster_memory_skew_bytes",
                  "cross-rank bytes_in_use skew: max minus min over "
                  "fresh ranks (uneven sharding / leak on one rank)",
                  [((), mem_skew)])
        gauge("pt_cluster_memory_alarm",
              "1 while any rank's bytes_in_use >= the near-OOM "
              "threshold", [((), 1 if mem_alarm else 0)])

        # serving fleet: bucket-merged request-latency percentiles,
        # queue depth, and the saturation trip.  A serving fleet's SLO
        # is the CLUSTER p99 — one saturated replica hides inside
        # per-rank views but dominates the merged tail.
        serve_stats = {}
        for r, f in fresh.items():
            st = _rank_serve_stats(f)
            if st is not None and st["count"]:
                serve_stats[r] = st
        serve_p50 = serve_p99 = None
        serve_count = 0
        if serve_stats:
            merged_buckets: dict = {}
            for st in serve_stats.values():
                for le, cum in st["buckets"].items():
                    merged_buckets[le] = merged_buckets.get(le, 0.0) + cum
            serve_count = sum(st["count"] for st in serve_stats.values())
            serve_p50 = bucket_percentile(merged_buckets, serve_count, 0.50)
            serve_p99 = bucket_percentile(merged_buckets, serve_count, 0.99)
            if serve_p50 is not None:
                gauge("pt_cluster_serve_p50_seconds",
                      "cluster p50 serve request latency "
                      "(bucket-merged over fresh ranks)",
                      [((), serve_p50)])
            if serve_p99 is not None:
                gauge("pt_cluster_serve_p99_seconds",
                      "cluster p99 serve request latency "
                      "(bucket-merged over fresh ranks)",
                      [((), serve_p99)])
        rank_queue = {r: _gauge_value(f, "pt_serve_queue_depth")
                      for r, f in fresh.items()}
        rank_queue = {r: v for r, v in rank_queue.items() if v is not None}
        if rank_queue:
            gauge("pt_cluster_serve_queue_depth",
                  "serve admission-queue depth over fresh ranks (sum = "
                  "fleet backlog; max = worst replica)",
                  [((("stat", "sum"),), sum(rank_queue.values())),
                   ((("stat", "max"),), max(rank_queue.values()))])
        serve_compiles = sum(
            _family_total(f, "pt_serve_unexpected_compiles_total")
            for f in fresh.values())
        if serve_stats or rank_queue or serve_compiles:
            counter("pt_cluster_serve_unexpected_compiles_total",
                    "request-path compiles after warmup summed across "
                    "ranks (any non-zero value is an SLO violation)",
                    serve_compiles)
        serve_alarm = (self.serve_threshold > 0 and serve_p99 is not None
                       and serve_p99 >= self.serve_threshold)
        gauge("pt_cluster_serve_alarm",
              "1 while cluster serve p99 >= the saturation threshold",
              [((), 1 if serve_alarm else 0)])
        # load-shed accounting: the resilience layer's admission
        # refusals (deadline_infeasible/queue_full/draining), summed
        # fleet-wide and expressed as a ratio of admission attempts
        serve_shed = sum(_family_total(f, "pt_serve_shed_total")
                         for f in fresh.values())
        serve_accepted = sum(_family_total(f, "pt_serve_requests_total")
                             for f in fresh.values())
        shed_ratio = None
        if serve_shed or serve_accepted:
            counter("pt_cluster_serve_shed_total",
                    "requests shed at admission summed across ranks, "
                    "all reasons", serve_shed)
            shed_ratio = serve_shed / max(1.0, serve_shed + serve_accepted)
            gauge("pt_cluster_serve_shed_ratio",
                  "fraction of fleet admission attempts shed "
                  "(shed / (shed + accepted)) over fresh ranks",
                  [((), shed_ratio)])
        shed_alarm = (self.shed_threshold > 0 and shed_ratio is not None
                      and shed_ratio >= self.shed_threshold)
        gauge("pt_cluster_serve_shed_alarm",
              "1 while the fleet shed ratio >= the shed-storm threshold",
              [((), 1 if shed_alarm else 0)])

        text = render_exposition(merged) + "\n".join(extra) + "\n"

        ranks_health = {}
        for r, m in sorted(meta.items()):
            entry = {"up": m["up"],
                     "scrape_age_sec": (round(m["age"], 3)
                                        if m["age"] is not None
                                        else None),
                     "error": m["error"]}
            if r in fresh:
                entry["steps"] = int(_family_total(fresh[r],
                                                   "pt_steps_total"))
                entry["step_time"] = {
                    mode: {"count": st["count"],
                           "mean_ms": (round(st["mean"] * 1e3, 3)
                                       if st["mean"] is not None
                                       else None),
                           "p50_ms": (round(st["p50"] * 1e3, 3)
                                      if st["p50"] is not None
                                      else None),
                           "p95_ms": (round(st["p95"] * 1e3, 3)
                                      if st["p95"] is not None
                                      else None)}
                    for mode, st in sorted(stats[r].items())}
                entry["recompile_storms"] = _family_total(
                    fresh[r], "pt_recompile_storms_total")
                if r in goodputs:
                    entry["goodput_fraction"] = round(goodputs[r], 6)
                entry["numerics_anomalies"] = _family_total(
                    fresh[r], "pt_numerics_anomalies_total")
                entry["sdc_divergences"] = _family_total(
                    fresh[r], "pt_sdc_divergence_total")
                if r in rank_mem:
                    entry["memory_bytes_in_use"] = int(rank_mem[r])
            ranks_health[str(r)] = entry
        health = {
            "ok": (not alarm and not anomaly_alarm and not sdc_alarm
                   and not mem_alarm and not serve_alarm
                   and not shed_alarm),
            "run_id": self.run_id,
            "ranks_discovered": len(self._endpoints),
            "ranks_up": len(fresh),
            "stale_ranks": sorted(r for r, m in meta.items()
                                  if not m["up"]),
            "ranks": ranks_health,
            "step_time_skew_seconds": {
                m: round(v, 6) for m, v in skew_by_mode.items()},
            "step_time_straggler_ratio": {
                m: round(v, 4) for m, v in ratio_by_mode.items()},
            "recompile_storms_total": storms_total,
            "storm_alarm": alarm,
            "storm_threshold": self.storm_threshold,
            "cluster_goodput": {k: round(v, 6)
                                for k, v in cluster_goodput.items()},
            "numerics_anomalies_total": anomalies_total,
            "anomaly_alarm": anomaly_alarm,
            "anomaly_threshold": self.anomaly_threshold,
            "sdc_divergences_total": sdc_total,
            "sdc_alarm": sdc_alarm,
            "sdc_threshold": self.sdc_threshold,
            "memory": {
                "bytes_in_use_max": (int(mem_max)
                                     if mem_max is not None else None),
                "skew_bytes": (int(mem_skew)
                               if mem_skew is not None else None),
                "mem_alarm": mem_alarm,
                "mem_threshold": self.mem_threshold,
            },
            "serve": {
                "requests_total": int(serve_count),
                "p50_seconds": (round(serve_p50, 6)
                                if serve_p50 is not None else None),
                "p99_seconds": (round(serve_p99, 6)
                                if serve_p99 is not None else None),
                "queue_depth_sum": (int(sum(rank_queue.values()))
                                    if rank_queue else None),
                "queue_depth_max": (int(max(rank_queue.values()))
                                    if rank_queue else None),
                "unexpected_compiles_total": int(serve_compiles),
                "serve_alarm": serve_alarm,
                "serve_threshold": self.serve_threshold,
                "shed_total": int(serve_shed),
                "shed_ratio": (round(shed_ratio, 6)
                               if shed_ratio is not None else None),
                "shed_alarm": shed_alarm,
                "shed_threshold": self.shed_threshold,
            },
            "merge_conflicts_total": self._conflicts_total,
            "scrape_errors_total": self._scrape_errors_total,
        }
        if self._history is not None:
            self._history.append(time.time(), {
                "ranks_up": len(fresh),
                "skew": {m: round(v, 6)
                         for m, v in skew_by_mode.items()},
                "straggler": {m: round(v, 4)
                              for m, v in ratio_by_mode.items()},
                "storms": storms_total,
            })
            health["history"] = self._history.summary()
        with self._lock:
            self._text = text
            self._health = health

    # -- serving --------------------------------------------------------------

    def prometheus_text(self):
        with self._lock:
            return self._text

    def healthz(self):
        with self._lock:
            return dict(self._health)

    def history(self):
        """The retained (ts, point) cluster history (empty when
        retention is disabled)."""
        with self._lock:
            return self._history.points() if self._history is not None \
                else []

    def start(self):
        """Run the scrape loop on a daemon thread. Idempotent."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def _loop():
            while not self._stop.is_set():
                try:
                    self.scrape_once()
                except Exception as e:
                    logger.warning("aggregator scrape cycle failed: "
                                   "%s", e)
                self._stop.wait(self.interval)

        self._thread = threading.Thread(
            target=_loop, name="pt-cluster-aggregator", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10.0)


# -- bench snapshot ----------------------------------------------------------


def cluster_snapshot(url=None, timeout=3.0, storm_threshold=1):
    """Compact cluster dict for bench/MULTICHIP JSON records: skew,
    per-rank step p50/p95, total recompile storms.

    With ``url`` (normally ``$PT_AGGREGATOR_URL``) the running
    aggregator's ``/healthz`` IS the snapshot (a 503 body — alarm up —
    still counts as a successful fetch); without one, the local
    process's registry is summarized as a single-rank cluster so the
    record shape is identical either way.  Never raises: failures come
    back as ``{"error": ...}``.
    """
    if url:
        target = url.rstrip("/")
        if not target.endswith("/healthz"):
            target += "/healthz"
        try:
            with urllib.request.urlopen(target, timeout=timeout) as r:
                snap = json.loads(r.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            try:
                snap = json.loads(e.read().decode("utf-8"))
            except Exception:
                return {"error": f"HTTP {e.code}", "source": target}
        except Exception as e:
            return {"error": f"{type(e).__name__}: {e}",
                    "source": target}
        snap["source"] = target
        return snap
    from .metrics import get_registry
    from .telemetry import get_telemetry
    tel = get_telemetry()
    agg = ClusterAggregator(run_id=tel.run_id,
                            storm_threshold=storm_threshold)
    try:
        families = parse_prometheus_text(
            get_registry().prometheus_text())
    except ValueError as e:
        return {"error": str(e), "source": "local"}
    agg._endpoints[tel.process_index] = "local"
    agg._scrapes[tel.process_index] = {"ts": time.monotonic(),
                                       "families": families,
                                       "error": None}
    agg._render()
    snap = agg.healthz()
    snap["source"] = "local"
    return snap


# -- CLI ---------------------------------------------------------------------


def _write_endpoint_atomic(path, host, port):
    # local copy of the atomic publish pattern (tmp + fsync + rename)
    # so this module needs nothing from paddle_tpu.distributed
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="ascii") as f:
        f.write(f"{host}:{port}\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _env(name, default):
    v = os.environ.get(name, "").strip()
    return v if v else default


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.aggregator",
        description="Scrape every rank's /metrics, merge, and serve "
                    "the cluster-level /metrics + /healthz.")
    ap.add_argument("--run-id",
                    default=_env("PT_RUN_ID", "local"),
                    help="run whose obs/<run_id>/... keys to watch")
    ap.add_argument("--store-endpoint-file", default=None,
                    help="coordination-store endpoint file (discovery "
                         "survives master respawn)")
    ap.add_argument("--store", default=None, metavar="HOST:PORT",
                    help="fixed coordination-store master address")
    ap.add_argument("--endpoints", default=None,
                    metavar="RANK=HOST:PORT,...",
                    help="static endpoint map (no store needed)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int,
                    default=int(_env("PT_AGGREGATOR_PORT", "0")),
                    help="cluster endpoint port (0 = ephemeral)")
    ap.add_argument("--port-file", default=None,
                    help="atomically publish the bound host:port here")
    ap.add_argument("--interval", type=float,
                    default=float(_env("PT_AGGREGATOR_INTERVAL", "1.0")))
    ap.add_argument("--stale-after", type=float,
                    default=float(_env("PT_AGGREGATOR_STALE_AFTER",
                                       "5.0")),
                    help="seconds without a good scrape before a rank "
                         "is dropped from merges")
    ap.add_argument("--scrape-timeout", type=float,
                    default=float(_env("PT_AGGREGATOR_SCRAPE_TIMEOUT",
                                       "2.0")))
    ap.add_argument("--storm-threshold", type=int,
                    default=int(_env("PT_AGGREGATOR_STORM_THRESHOLD",
                                     "1")),
                    help="summed sentinel trips that flip /healthz to "
                         "503 (0 disables the alarm)")
    ap.add_argument("--anomaly-threshold", type=int,
                    default=int(_env("PT_AGGREGATOR_ANOMALY_THRESHOLD",
                                     "10")),
                    help="summed numerics anomalies that flip /healthz "
                         "to 503 (0 disables the alarm)")
    ap.add_argument("--sdc-threshold", type=int,
                    default=int(_env("PT_AGGREGATOR_SDC_THRESHOLD",
                                     "1")),
                    help="summed SDC consensus divergence verdicts "
                         "that flip /healthz to 503 (0 disables the "
                         "alarm)")
    ap.add_argument("--mem-threshold", type=int,
                    default=int(_env("PT_AGGREGATOR_MEM_THRESHOLD",
                                     "0")),
                    help="near-OOM trip: any rank's bytes_in_use at/"
                         "over this many bytes flips /healthz to 503 "
                         "(0 disables the alarm)")
    ap.add_argument("--serve-threshold", type=float,
                    default=float(_env("PT_AGGREGATOR_SERVE_THRESHOLD",
                                       "0")),
                    help="serving saturation trip: cluster p99 request "
                         "latency at/over this many seconds flips "
                         "/healthz to 503 (0 disables the alarm)")
    ap.add_argument("--shed-threshold", type=float,
                    default=float(_env("PT_AGGREGATOR_SHED_THRESHOLD",
                                       "0")),
                    help="shed-storm trip: fleet shed ratio "
                         "(shed / (shed + accepted)) at/over this "
                         "fraction flips /healthz to 503 (0 disables)")
    ap.add_argument("--retention", type=float,
                    default=float(_env("PT_AGGREGATOR_RETENTION",
                                       "3600")),
                    help="seconds of downsampled cluster history to "
                         "retain, memory-capped (0 disables)")
    ap.add_argument("--store-deadline", type=float, default=5.0,
                    help="ResilientStore per-op retry budget")
    ap.add_argument("--once", action="store_true",
                    help="single scrape pass; merged exposition to "
                         "stdout, exit 0")
    args = ap.parse_args(argv)

    endpoints = {}
    if args.endpoints:
        for part in args.endpoints.split(","):
            r, sep, ep = part.partition("=")
            if not sep:
                ap.error(f"--endpoints entry {part!r} is not "
                         f"RANK=HOST:PORT")
            endpoints[int(r)] = ep.strip()
    store = None
    if args.store_endpoint_file or args.store:
        # the one non-stdlib dependency, loaded only when store
        # discovery is requested (keeps `--endpoints` mode jax-free)
        from ..distributed.resilient_store import ResilientStore
        if args.store_endpoint_file:
            store = ResilientStore(
                endpoint_file=args.store_endpoint_file,
                deadline=args.store_deadline)
        else:
            host, sep, port = args.store.rpartition(":")
            if not sep:
                ap.error(f"--store {args.store!r} is not HOST:PORT")
            store = ResilientStore(host, int(port),
                                   deadline=args.store_deadline)
    if store is None and not endpoints:
        ap.error("need --store-endpoint-file, --store, or --endpoints")

    agg = ClusterAggregator(
        endpoints=endpoints, store=store, run_id=args.run_id,
        stale_after=args.stale_after,
        scrape_timeout=args.scrape_timeout,
        storm_threshold=args.storm_threshold,
        anomaly_threshold=args.anomaly_threshold,
        sdc_threshold=args.sdc_threshold,
        mem_threshold=args.mem_threshold,
        serve_threshold=args.serve_threshold,
        shed_threshold=args.shed_threshold,
        interval=args.interval, retention=args.retention)
    if args.once:
        agg.scrape_once()
        sys.stdout.write(agg.prometheus_text())
        return 0

    from .server import MetricsServer
    srv = MetricsServer(metrics_cb=agg.prometheus_text,
                        health_cb=agg.healthz, host=args.host,
                        port=args.port).start()
    agg.start()
    if args.port_file:
        _write_endpoint_atomic(args.port_file, args.host, srv.port)
    logger.info("cluster aggregator for run %s on http://%s:%d "
                "(interval=%.2fs stale_after=%.2fs storm_threshold=%d)",
                args.run_id, args.host, srv.port, args.interval,
                args.stale_after, args.storm_threshold)

    import signal
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, lambda *_: stop.set())
        except (ValueError, OSError):
            pass
    while not stop.is_set():
        stop.wait(3600.0)
    agg.stop()
    srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
