"""Thread-safe, label-aware metrics registry.

The in-process analog of the reference's stat registry
(``paddle/fluid/platform/monitor.cc`` STAT_INT/STAT_FLOAT families),
grown Prometheus-shaped: three instrument kinds —

 - :class:`Counter`   monotone float, ``inc()``
 - :class:`Gauge`     last-write-wins float, ``set()`` / ``inc()``
 - :class:`Histogram` fixed-bucket distribution, ``observe()``

each optionally split by a fixed tuple of label names.  A registry
renders every instrument as Prometheus exposition text (scraped by the
``/metrics`` endpoint in :mod:`.server`) or as a plain-dict JSON
snapshot (attached to bench records, JSONL events).

Contract with the rest of the package: creating registries and
instruments does no I/O, starts no threads, and touches no device —
it's all dicts behind one lock, safe to do at any point including
while telemetry is disabled.  Getter methods are idempotent: asking
for an existing (name, kind, labelnames) returns the same instrument;
asking with a conflicting signature raises.
"""
from __future__ import annotations

import json
import math
import threading

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "get_registry", "reset_registry", "log_buckets",
    "DEFAULT_TIME_BUCKETS",
]


def log_buckets(lo, hi, per_decade=3):
    """Log-spaced bucket upper bounds covering [lo, hi] inclusive."""
    if not (lo > 0 and hi > lo):
        raise ValueError(f"need 0 < lo < hi, got ({lo}, {hi})")
    n = int(round(per_decade * math.log10(hi / lo)))
    out = [lo * (hi / lo) ** (i / n) for i in range(n + 1)]
    # snap to short decimals so exposition text stays readable
    return [float(f"{b:.3g}") for b in out]


# 100 us .. 100 s: spans a single eager op dispatch up to a cold
# XLA compile; 3 buckets per decade keeps the series at 19 + Inf.
DEFAULT_TIME_BUCKETS = tuple(log_buckets(1e-4, 100.0, per_decade=3))

_INF = float("inf")


def _fmt(v):
    """Prometheus sample value formatting (integers without the .0)."""
    if v == _INF:
        return "+Inf"
    if v == -_INF:
        return "-Inf"
    if v != v:  # NaN
        return "NaN"
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_help(s):
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s):
    return (str(s).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _labels_text(names, values, extra=()):
    pairs = [f'{n}="{_escape_label(v)}"' for n, v in zip(names, values)]
    pairs.extend(f'{n}="{_escape_label(v)}"' for n, v in extra)
    return "{" + ",".join(pairs) + "}" if pairs else ""


class _Metric:
    """Base: one named instrument, children keyed by label values."""

    kind = "untyped"

    def __init__(self, name, help, labelnames=(), lock=None):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = lock if lock is not None else threading.Lock()
        self._children = {}

    def _key(self, labels):
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: got labels {sorted(labels)}, "
                f"declared {sorted(self.labelnames)}")
        return tuple(str(labels[n]) for n in self.labelnames)

    def _child(self, labels):
        key = self._key(labels)
        with self._lock:
            c = self._children.get(key)
            if c is None:
                c = self._children[key] = self._new_child()
            return c

    def _items(self):
        with self._lock:
            return sorted(self._children.items())


class _CounterValue:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0


class Counter(_Metric):
    kind = "counter"

    def _new_child(self):
        return _CounterValue()

    def inc(self, amount=1.0, **labels):
        if amount < 0:
            raise ValueError("counters only go up")
        c = self._child(labels)
        with self._lock:
            c.value += amount

    def value(self, **labels):
        return self._child(labels).value

    def expose(self, out, const=()):
        for key, c in self._items():
            out.append(f"{self.name}"
                       f"{_labels_text(self.labelnames, key, extra=const)} "
                       f"{_fmt(c.value)}")

    def snapshot_values(self):
        return {key: c.value for key, c in self._items()}


class Gauge(Counter):
    kind = "gauge"

    def inc(self, amount=1.0, **labels):
        c = self._child(labels)
        with self._lock:
            c.value += amount

    def dec(self, amount=1.0, **labels):
        self.inc(-amount, **labels)

    def set(self, value, **labels):
        c = self._child(labels)
        with self._lock:
            c.value = float(value)


class _HistogramValue:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets):
        self.counts = [0] * n_buckets  # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help, labelnames=(), buckets=None, lock=None):
        super().__init__(name, help, labelnames, lock=lock)
        bs = sorted(float(b) for b in (buckets or DEFAULT_TIME_BUCKETS))
        if not bs:
            raise ValueError("histogram needs at least one bucket")
        if bs[-1] != _INF:
            bs.append(_INF)
        self.buckets = tuple(bs)

    def _new_child(self):
        return _HistogramValue(len(self.buckets))

    def observe(self, value, **labels):
        c = self._child(labels)
        v = float(value)
        with self._lock:
            for i, b in enumerate(self.buckets):
                if v <= b:
                    c.counts[i] += 1
                    break
            c.sum += v
            c.count += 1

    def expose(self, out, const=()):
        for key, c in self._items():
            cum = 0
            for b, n in zip(self.buckets, c.counts):
                cum += n
                le = _labels_text(self.labelnames, key,
                                  extra=tuple(const) + (("le", _fmt(b)),))
                out.append(f"{self.name}_bucket{le} {cum}")
            lbl = _labels_text(self.labelnames, key, extra=const)
            out.append(f"{self.name}_sum{lbl} {_fmt(c.sum)}")
            out.append(f"{self.name}_count{lbl} {cum}")

    def snapshot_values(self):
        out = {}
        for key, c in self._items():
            cum, rows = 0, []
            for b, n in zip(self.buckets, c.counts):
                cum += n
                rows.append(["+Inf" if b == _INF else b, cum])
            out[key] = {"buckets": rows, "sum": c.sum, "count": c.count}
        return out

    def percentile(self, q, **labels):
        """Bucket-interpolated percentile (None while empty)."""
        c = self._child(labels)
        with self._lock:
            total = c.count
            if not total:
                return None
            target, cum, lo = q * total, 0, 0.0
            for b, n in zip(self.buckets, c.counts):
                if cum + n >= target and n:
                    if b == _INF:
                        return lo
                    frac = (target - cum) / n
                    return lo + (b - lo) * frac
                cum += n
                lo = b if b != _INF else lo
            return lo


class MetricsRegistry:
    """Named instruments; one lock per registry (coarse on purpose —
    every operation is sub-microsecond dict work)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}
        self._const_labels: tuple = ()

    def set_const_labels(self, **labels):
        """Labels stamped on EVERY exposed sample (after each metric's
        declared labels, before a histogram's ``le``) — the identity of
        this process in a cluster scrape: ``process_index``, ``run_id``.
        Idempotent; sorted by name so exposition text is stable."""
        with self._lock:
            self._const_labels = tuple(
                sorted((str(k), str(v)) for k, v in labels.items()))
        return self

    @property
    def const_labels(self):
        with self._lock:
            return dict(self._const_labels)

    def _get_or_make(self, cls, name, help, labelnames, **kw):
        labelnames = tuple(labelnames)
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls or m.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{m.kind} with labels {m.labelnames}")
                return m
            m = cls(name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help="", labelnames=()):
        return self._get_or_make(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()):
        return self._get_or_make(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(), buckets=None):
        return self._get_or_make(Histogram, name, help, labelnames,
                                 buckets=buckets)

    def collect(self):
        with self._lock:
            return [self._metrics[n] for n in sorted(self._metrics)]

    def prometheus_text(self):
        """Prometheus text exposition format 0.0.4."""
        with self._lock:
            const = self._const_labels
        out = []
        for m in self.collect():
            out.append(f"# HELP {m.name} {_escape_help(m.help)}")
            out.append(f"# TYPE {m.name} {m.kind}")
            m.expose(out, const=const)
        return "\n".join(out) + ("\n" if out else "")

    def snapshot(self):
        """JSON-serializable dict of every instrument's current state."""
        out = {}
        for m in self.collect():
            series = {}
            for key, val in m.snapshot_values().items():
                lbl = ",".join(f"{n}={v}"
                               for n, v in zip(m.labelnames, key))
                series[lbl] = val
            out[m.name] = {"kind": m.kind, "help": m.help,
                           "series": series}
        return out

    def snapshot_json(self, **json_kw):
        return json.dumps(self.snapshot(), **json_kw)


_registry: MetricsRegistry | None = None
_registry_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-global registry (created on first use)."""
    global _registry
    if _registry is None:
        with _registry_lock:
            if _registry is None:
                _registry = MetricsRegistry()
    return _registry


def reset_registry():
    """Drop the global registry (test isolation)."""
    global _registry
    with _registry_lock:
        _registry = None
