"""Wall-clock goodput ledger.

The span tracer (``observability/trace.py``) already records every
phase of every step — compute spans from captured replays, data_wait /
checkpoint host spans, collective spans, compile spans for the first
call of each captured program. What was missing is the *decomposition*:
of the wall-clock this process spent, how much was productive training
math and how much was overhead, by cause? That single fraction — the
fleet's goodput — is the number a capacity owner actually watches, and
it is what the aggregator rolls up across ranks as
``pt_cluster_goodput``.

Classification over the tracer's span ring:

  - ``compute`` spans (forward/backward/optimizer, captured replays)
    are **productive**; overlapping compute intervals are merged first
    so concurrent streams don't double-count.
  - ``data_wait`` and ``checkpoint`` spans are **badput** under their
    own cause.
  - ``collective`` spans are badput only for their **exposed** part —
    the sub-interval not hidden under merged compute (the overlap
    machinery the tracer already uses for
    ``pt_compute_collective_overlap_fraction``).
  - ``compile`` spans (capture's first call, name ``compile:<entry>``)
    are badput under ``compile``.
  - restart replay — steps re-run after an elastic restore — is fed
    explicitly via :meth:`GoodputLedger.record_restart_replay`, since
    by construction those spans look like ordinary compute.
  - any other host span is badput under ``host_other``.

``pt_goodput_fraction`` = productive / (productive + total badput),
refreshed from ``telemetry.observe_step`` (same sys.modules-gated feed
the tracer uses), plus per-cause ``pt_badput_seconds{cause}`` gauges.
Every bench record attaches :meth:`GoodputLedger.snapshot`.

Environment: ``PT_GOODPUT=1`` enables on first ``get_goodput()``.
"""
from __future__ import annotations

import os
import sys
import threading

__all__ = [
    "GoodputLedger",
    "decompose_spans",
    "get_goodput",
    "current_ledger",
    "reset_goodput",
]

# span-name → badput cause for host-cat spans
_HOST_CAUSES = ("data_wait", "checkpoint")
CAUSES = ("data_wait", "checkpoint", "collective_exposed", "compile",
          "restart_replay", "host_other")


def _merge(intervals):
    """Merge overlapping (t0, t1) intervals; returns disjoint sorted."""
    merged = []
    for t0, t1 in sorted(intervals):
        if merged and t0 <= merged[-1][1]:
            if t1 > merged[-1][1]:
                merged[-1] = (merged[-1][0], t1)
        else:
            merged.append((t0, t1))
    return merged


def _overlap_ns(t0, t1, merged):
    hidden = 0
    for c0, c1 in merged:
        lo, hi = max(t0, c0), min(t1, c1)
        if hi > lo:
            hidden += hi - lo
        if c0 >= t1:
            break
    return hidden


def decompose_spans(spans):
    """Pure classification of a span list into productive seconds and
    per-cause badput seconds. Unit-testable against a hand-computed
    decomposition; the ledger and the bench block both go through
    here."""
    compute, collectives = [], []
    badput = {}

    def _add(cause, ns):
        badput[cause] = badput.get(cause, 0.0) + ns / 1e9

    for s in spans:
        dur = s.t1_ns - s.t0_ns
        if dur <= 0:
            continue
        if s.cat == "compute":
            compute.append((s.t0_ns, s.t1_ns))
        elif s.cat == "collective":
            collectives.append((s.t0_ns, s.t1_ns))
        elif s.name in _HOST_CAUSES:
            _add(s.name, dur)
        elif s.name == "compile" or s.name.startswith("compile:"):
            _add("compile", dur)
        else:
            _add("host_other", dur)
    merged = _merge(compute)
    productive_ns = sum(t1 - t0 for t0, t1 in merged)
    for t0, t1 in collectives:
        exposed = (t1 - t0) - _overlap_ns(t0, t1, merged)
        if exposed > 0:
            _add("collective_exposed", exposed)
    productive = productive_ns / 1e9
    total_bad = sum(badput.values())
    wall = productive + total_bad
    return {
        "productive_seconds": productive,
        "badput_seconds": badput,
        "badput_total_seconds": total_bad,
        "accounted_seconds": wall,
        "goodput_fraction": (productive / wall) if wall > 0 else None,
    }


class GoodputLedger:
    """Windowed goodput over the tracer's span ring plus explicit
    cumulative feeds for causes spans can't express."""

    def __init__(self):
        self._lock = threading.RLock()
        self.enabled = False
        self._metrics = None
        self._restart_s = 0.0
        self._extra_compile_s = 0.0
        self._last = None  # last decomposition dict

    def enable(self):
        with self._lock:
            self.enabled = True
            self._make_metrics()
        return self

    def disable(self):
        with self._lock:
            self.enabled = False
        return self

    def _make_metrics(self):
        if self._metrics is not None:
            return
        try:
            from .metrics import get_registry
            r = get_registry()
            self._metrics = {
                "fraction": r.gauge(
                    "pt_goodput_fraction",
                    "Productive fraction of accounted wall-clock "
                    "(windowed over the span ring)"),
                "badput": r.gauge(
                    "pt_badput_seconds",
                    "Overhead wall-clock by cause, over the span "
                    "window", ("cause",)),
            }
        except Exception:
            self._metrics = None

    # -- explicit feeds ----------------------------------------------

    def record_restart_replay(self, seconds):
        """Steps re-executed after an elastic restore: indistinguishable
        from productive compute in the span stream, so the restore path
        reports them here."""
        if not self.enabled:
            return
        with self._lock:
            self._restart_s += float(seconds)

    def record_compile(self, seconds):
        """Compile time observed outside a traced span (e.g. AOT warmup
        with tracing off)."""
        if not self.enabled:
            return
        with self._lock:
            self._extra_compile_s += float(seconds)

    # -- refresh / summary -------------------------------------------

    def refresh(self, spans=None):
        """Recompute the decomposition (from the tracer ring unless a
        span list is given) and publish the gauges. Called from
        ``telemetry.observe_step`` once per step — pure host arithmetic
        over the in-memory ring, never touches the device."""
        if not self.enabled:
            return None
        if spans is None:
            tr_mod = sys.modules.get("paddle_tpu.observability.trace")
            if tr_mod is None:
                return None
            tr = tr_mod.current_tracer()
            if tr is None or not tr.enabled:
                return None
            spans = tr.spans()
        dec = decompose_spans(spans)
        with self._lock:
            bad = dict(dec["badput_seconds"])
            if self._restart_s > 0:
                bad["restart_replay"] = (
                    bad.get("restart_replay", 0.0) + self._restart_s)
            if self._extra_compile_s > 0:
                bad["compile"] = bad.get("compile", 0.0) \
                    + self._extra_compile_s
            total_bad = sum(bad.values())
            wall = dec["productive_seconds"] + total_bad
            dec = dict(dec, badput_seconds=bad,
                       badput_total_seconds=total_bad,
                       accounted_seconds=wall,
                       goodput_fraction=(dec["productive_seconds"] / wall
                                         if wall > 0 else None))
            self._last = dec
            metrics = self._metrics
        if metrics is not None:
            try:
                if dec["goodput_fraction"] is not None:
                    metrics["fraction"].set(dec["goodput_fraction"])
                for cause, sec in dec["badput_seconds"].items():
                    metrics["badput"].set(sec, cause=cause)
            except Exception:
                pass
        return dec

    def snapshot(self):
        """JSON-ready block for bench records; refreshes first so the
        block reflects the final span window."""
        dec = self.refresh()
        with self._lock:
            if dec is None:
                dec = self._last
            return {
                "enabled": self.enabled,
                "restart_replay_seconds": self._restart_s,
                **({k: (round(v, 6) if isinstance(v, float) else
                        {c: round(s, 6) for c, s in v.items()}
                        if isinstance(v, dict) else v)
                    for k, v in dec.items()} if dec else {}),
            }


_ledger = None
_ledger_lock = threading.Lock()


def _truthy(v):
    return str(v).lower() not in ("", "0", "false", "no", "off", "none")


def get_goodput():
    """Process singleton; first call applies PT_GOODPUT env config."""
    global _ledger
    with _ledger_lock:
        if _ledger is None:
            _ledger = GoodputLedger()
            if _truthy(os.environ.get("PT_GOODPUT", "")):
                _ledger.enable()
        return _ledger


def current_ledger():
    """The singleton if it exists, else None (no env enablement)."""
    return _ledger


def reset_goodput():
    """Drop the singleton (tests)."""
    global _ledger
    with _ledger_lock:
        _ledger = None
