"""Step-phase span tracing, analytic MFU accounting, and the crash
flight recorder.

:class:`Tracer` is the process-wide span sink every instrumented layer
feeds: ``core.RecordEvent`` begin/end pairs, the profiler's
``export_chrome_tracing``, and the step-phase hooks in ``hapi.Model``,
``jit.capture``, ``DataLoader`` and the eager collectives.  It follows
the same contract as :class:`~.telemetry.TrainingTelemetry`:

1. **Zero cost while disabled.**  Every hook starts with a plain
   attribute check; importing this module creates no threads, files or
   jax backends, and ``get_tracer()`` only flips itself on when
   ``PT_TRACE`` / ``PT_FLIGHT_RECORDER`` say so.
2. **Lock-light.**  Spans land in a bounded ``deque(maxlen=...)`` ring
   buffer — appends are GIL-atomic, so the hot path takes no lock; the
   lock guards only rare operations (enable/export/flight dumps).
3. **Tracer-safe.**  Wall-clock phase spans are skipped inside a jax
   trace (``jax.core.trace_state_clean``, same guard as
   ``distributed.collective._timed``): timing a tracer would record the
   trace, not the step.
4. **Never sync the device, never take down the run.**  Spans carry
   host timestamps only; export/dump failures are swallowed after
   bumping a drop counter.

Every span is stamped with this process's ``(process_index, run_id)``
identity so per-rank Chrome exports stitch into one cluster timeline
(``python -m paddle_tpu.observability.merge --trace``, rank as pid).

**Phases** (``pt_step_phase_seconds{phase}``): ``data_wait`` /
``forward`` / ``backward`` / ``optimizer`` / ``checkpoint`` /
``collective``.  ``backward`` covers the fused forward+backward
``value_and_grad`` program in jitted train steps — XLA runs them as one
program, so the host boundary cannot split them.  The derived
``pt_compute_collective_overlap_fraction`` gauge is the fraction of
collective wall time overlapped by compute spans — the measurement half
of the GC3 overlap item (ROADMAP).

**Analytic MFU** (``pt_mfu_analytic``): per-compiled-program FLOPs are
harvested from XLA's ``cost_analysis`` at compile time
(:func:`program_flops`, cached per program name alongside the compile
counter) and divided by step wall time times the device's peak FLOP/s
(:data:`PEAK_FLOPS`), so every bench record carries an MFU estimate
even when the real TPU is unreachable.

**Flight recorder** (``PT_FLIGHT_RECORDER=<dir>``): the last-N spans +
a telemetry snapshot are dumped to ``flight-<run_id>-<rank>.json`` on
SIGTERM (via ``exp/_preempt.ExpRunGuard``), on crash (a chained
``sys.excepthook``), and on a watchdog cadence from the hot path — the
periodic refresh is what leaves a fresh file behind a SIGKILL, which
runs no handlers at all.  The current path is surfaced in ``/healthz``.

Env: ``PT_TRACE=1`` enables tracing, ``PT_TRACE_DIR`` sets the Chrome
export directory, ``PT_FLIGHT_RECORDER`` names the flight-dump
directory (and implies enable).  All checked lazily on the first
``get_tracer()`` call.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque, namedtuple

from .logs import get_logger
from .metrics import get_registry, log_buckets

__all__ = [
    "Tracer", "Span", "PHASES", "PEAK_FLOPS", "peak_flops",
    "program_flops", "get_tracer", "current_tracer", "reset_tracer",
]

logger = get_logger(__name__)

_TRUTHY = {"1", "true", "yes", "on"}

# the step-phase taxonomy every instrumented layer reports against
PHASES = ("data_wait", "forward", "backward", "optimizer", "checkpoint",
          "collective")

# phase -> span category; the overlap fraction intersects "collective"
# spans with "compute" spans (data_wait/checkpoint are host work —
# overlapping a collective with those is not latency hiding)
_PHASE_CAT = {
    "data_wait": "host", "checkpoint": "host",
    "forward": "compute", "backward": "compute", "optimizer": "compute",
    "collective": "collective",
}

# bf16 peak FLOP/s per chip by device kind (public spec sheets).  The
# "cpu" entry is a nominal one-core figure so CPU-only bench records
# still carry an MFU estimate (the point is trend, not absolute truth).
PEAK_FLOPS = {
    "TPU v4": 275e12, "TPU v5": 459e12, "TPU v5p": 459e12,
    "TPU v5e": 197e12, "TPU v5 lite": 197e12, "TPU v6e": 918e12,
    "TPU v6 lite": 918e12, "TPU v3": 123e12, "TPU v2": 45e12,
    "cpu": 1e11,
}

# seconds between watchdog flight-recorder refreshes from the hot path
_FLIGHT_REFRESH_SEC = 2.0

Span = namedtuple("Span", ("name", "cat", "t0_ns", "t1_ns", "tid"))


def _env_flag(name):
    return os.environ.get(name, "").strip().lower() in _TRUTHY


def peak_flops(device_kind):
    """Peak FLOP/s for ``device_kind`` (longest-prefix match so
    "TPU v5 lite" never matches "TPU v5"); None when unknown."""
    kind = (device_kind or "").lower()
    for k in sorted(PEAK_FLOPS, key=len, reverse=True):
        if kind.startswith(k.lower()):
            return PEAK_FLOPS[k]
    return None


def _device_kind():
    """device_kind of the first local device, or None — NEVER
    initializes a jax backend just to ask (same rule as
    ``TrainingTelemetry.device_memory``)."""
    jax = sys.modules.get("jax")
    xb = sys.modules.get("jax._src.xla_bridge")
    if jax is None or xb is None or not getattr(xb, "_backends", None):
        return None
    try:
        devs = jax.local_devices()
        return devs[0].device_kind if devs else None
    except Exception:
        return None


def _tracing():
    """True when called under an open jax trace (or when jax's trace
    state cannot be read — assume the worst, skip wall timing)."""
    jax = sys.modules.get("jax")
    if jax is None:
        return False
    try:
        return not jax.core.trace_state_clean()
    except Exception:
        return True


def program_flops(jitted, *args, **kwargs):
    """Analytic FLOPs of one jitted program from XLA's cost analysis
    (None when the backend can't say).  Lowers + compiles AOT — call at
    compile time, not per step."""
    try:
        cost = jitted.lower(*args, **kwargs).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        f = float(cost.get("flops", 0.0))
        return f or None
    except Exception:
        return None


class _PhaseSpan:
    """``with tracer.phase("backward"):`` — wall-clock one phase.
    A no-op while the tracer is disabled or a jax trace is open."""

    __slots__ = ("_tr", "_phase", "_t0")

    def __init__(self, tracer, phase):
        self._tr = tracer
        self._phase = phase
        self._t0 = None

    def __enter__(self):
        if self._tr.enabled and not _tracing():
            self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._t0 is not None and exc_type is None:
            self._tr.phase_record(self._phase, self._t0,
                                  time.perf_counter_ns())
        return False


class Tracer:
    """Process-wide span recorder (see module docstring for contract)."""

    def __init__(self, capacity=4096):
        self.enabled = False
        from .telemetry import _resolve_identity
        self.process_index, self.run_id = _resolve_identity()
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=int(capacity))
        # counter samples ((name, t_ns, ((series, value), ...))) feed
        # Chrome ph:"C" counter tracks — the memory watermark timeline
        self._counters: deque = deque(maxlen=int(capacity))
        self._metrics_made = False
        self.trace_dir = None
        self.flight_dir = None
        self.flight_path = None
        self._flight_last_ns = 0
        self._prev_excepthook = None
        self.dropped = 0          # export/dump failures (never raised)
        self._program_flops: dict = {}
        self._last_step_seconds = None
        self._last_mfu = None
        self._last_overlap = None
        # perf_counter -> unix epoch anchor so per-rank exports share a
        # wall clock and stitch into one aligned cluster timeline
        self._epoch_ns = time.time_ns() - time.perf_counter_ns()

    # -- lifecycle ----------------------------------------------------------

    def enable(self, trace_dir=None, flight_dir=None, capacity=None,
               process_index=None, run_id=None):
        """Turn tracing on (idempotent).  ``trace_dir`` is where
        :meth:`export_chrome` writes by default; ``flight_dir`` arms
        the flight recorder (crash hook + watchdog refresh).  Returns
        self."""
        with self._lock:
            if process_index is not None:
                self.process_index = int(process_index)
            if run_id is not None:
                self.run_id = str(run_id)
            if capacity is not None and int(capacity) != self._spans.maxlen:
                self._spans = deque(self._spans, maxlen=int(capacity))
            if trace_dir is not None:
                self.trace_dir = str(trace_dir)
            if flight_dir is not None:
                self.flight_dir = str(flight_dir)
                self.flight_path = os.path.join(
                    self.flight_dir,
                    f"flight-{self.run_id}-{self.process_index}.json")
                if self._prev_excepthook is None:
                    self._prev_excepthook = sys.excepthook
                    sys.excepthook = self._excepthook
            if not self.enabled:
                self.enabled = True
                self._make_metrics()
        if flight_dir is not None:
            # arm → dump immediately: a SIGKILL can land before the
            # first watchdog refresh and must still find a file
            self.flight_dump(reason="armed")
        return self

    def disable(self):
        with self._lock:
            self.enabled = False
            if self._prev_excepthook is not None:
                sys.excepthook = self._prev_excepthook
                self._prev_excepthook = None
            self.flight_dir = None
            self.flight_path = None
        return self

    def _make_metrics(self):
        if self._metrics_made:
            return
        self._metrics_made = True
        r = get_registry()
        self._m_phase = r.histogram(
            "pt_step_phase_seconds",
            "wall time per step phase (data_wait/forward/backward/"
            "optimizer/checkpoint/collective)", ("phase",))
        self._m_overlap = r.gauge(
            "pt_compute_collective_overlap_fraction",
            "fraction of collective wall time overlapped by compute "
            "spans in the recent span window (GC3 measurement)")
        self._m_mfu = r.gauge(
            "pt_mfu_analytic",
            "analytic MFU: cost_analysis FLOPs per step / (step wall "
            "time * device peak FLOP/s)")
        self._m_flops = r.gauge(
            "pt_program_flops",
            "analytic FLOPs of each compiled program (cost_analysis, "
            "cached at compile time)", ("program",))

    # -- span feeds ---------------------------------------------------------

    def phase(self, phase):
        """Context manager timing one phase (histogram + ring buffer)."""
        return _PhaseSpan(self, phase)

    def phase_record(self, phase, t0_ns, t1_ns):
        """One completed phase with caller-measured endpoints (ns,
        ``time.perf_counter_ns`` clock)."""
        if not self.enabled:
            return
        self._m_phase.observe((t1_ns - t0_ns) / 1e9, phase=phase)
        cat = _PHASE_CAT.get(phase, "host")
        self._spans.append(Span(phase, cat, int(t0_ns), int(t1_ns),
                                threading.get_ident() & 0xFFFFFF))
        self._maybe_flight_refresh(t1_ns)

    def record_span(self, name, cat, t0_ns, t1_ns, tid=None):
        """Raw span feed (``core.RecordEvent`` forwarding, drills).
        ``cat`` is free-form; "compute"/"collective" participate in the
        overlap fraction."""
        if not self.enabled:
            return
        if tid is None:
            tid = threading.get_ident() & 0xFFFFFF
        self._spans.append(Span(str(name), str(cat), int(t0_ns),
                                int(t1_ns), int(tid)))
        self._maybe_flight_refresh(t1_ns)

    def record_counter(self, name, t_ns, values):
        """One counter sample (e.g. the memory watermark): ``values``
        is ``{series: number}``, exported as a Chrome ``ph:"C"``
        counter event so the merged cluster timeline carries a
        per-rank track. Appends are GIL-atomic like spans."""
        if not self.enabled:
            return
        self._counters.append((str(name), int(t_ns),
                               tuple((str(k), float(v))
                                     for k, v in values.items())))

    def counters(self):
        """Snapshot of the counter-sample ring (oldest first)."""
        return [(n, t, dict(vals)) for n, t, vals in self._counters]

    def spans(self):
        """Snapshot of the ring buffer (oldest first)."""
        return list(self._spans)

    def clear(self):
        self._spans.clear()
        self._counters.clear()

    # -- analytic MFU -------------------------------------------------------

    def record_program_flops(self, name, flops):
        """Cache one compiled program's analytic FLOPs (from
        ``cost_analysis`` at compile time)."""
        if flops is None:
            return
        with self._lock:
            self._program_flops[str(name)] = float(flops)
        if self.enabled:
            self._m_flops.set(float(flops), program=str(name))

    def flops_per_step(self):
        """Sum of all registered programs' FLOPs — the analytic cost of
        one step under the convention that each registered program runs
        once per step (true for the one-jitted-program train steps this
        framework builds)."""
        with self._lock:
            return sum(self._program_flops.values()) or None

    def mfu_analytic(self, step_seconds=None):
        """FLOPs/step / (step time * device peak); None when any factor
        is unknown."""
        dt = step_seconds if step_seconds is not None \
            else self._last_step_seconds
        flops = self.flops_per_step()
        peak = peak_flops(_device_kind())
        if not (dt and flops and peak):
            return None
        return flops / (dt * peak)

    # -- derived gauges (fed from telemetry.observe_step) -------------------

    def on_step(self, seconds):
        """One step finished: refresh the overlap + MFU gauges."""
        if not self.enabled:
            return
        self._last_step_seconds = float(seconds)
        ov = self.overlap_fraction()
        if ov is not None:
            self._last_overlap = ov
            self._m_overlap.set(ov)
        mfu = self.mfu_analytic(seconds)
        if mfu is not None:
            self._last_mfu = mfu
            self._m_mfu.set(mfu)
        self._maybe_flight_refresh(time.perf_counter_ns())

    def overlap_fraction(self):
        """Fraction of collective span time overlapped by compute spans
        over the current ring-buffer window; None without collectives."""
        comp, coll = [], []
        for s in self._spans:
            if s.cat == "compute":
                comp.append((s.t0_ns, s.t1_ns))
            elif s.cat == "collective":
                coll.append((s.t0_ns, s.t1_ns))
        if not coll:
            return None
        total = sum(t1 - t0 for t0, t1 in coll)
        if total <= 0:
            return None
        merged = []
        for t0, t1 in sorted(comp):
            if merged and t0 <= merged[-1][1]:
                merged[-1][1] = max(merged[-1][1], t1)
            else:
                merged.append([t0, t1])
        covered = 0
        for c0, c1 in coll:
            for m0, m1 in merged:
                lo, hi = max(c0, m0), min(c1, m1)
                if lo < hi:
                    covered += hi - lo
        return min(covered / total, 1.0)

    # -- Chrome trace export ------------------------------------------------

    def default_trace_path(self):
        if self.trace_dir is None:
            return None
        return os.path.join(
            self.trace_dir,
            f"trace-{self.run_id}-{self.process_index}.json")

    def chrome_events(self):
        """Chrome trace-event dicts for the current span window: "X"
        (complete) events, ts/dur in microseconds on the unix-epoch
        clock, pid = this rank."""
        events = [{
            "name": "process_name", "ph": "M", "pid": self.process_index,
            "tid": 0,
            "args": {"name": f"rank{self.process_index} "
                             f"({self.run_id})"},
        }]
        for s in self._spans:
            events.append({
                "name": s.name, "cat": s.cat, "ph": "X",
                "ts": (s.t0_ns + self._epoch_ns) / 1e3,
                "dur": max(s.t1_ns - s.t0_ns, 0) / 1e3,
                "pid": self.process_index, "tid": s.tid,
                "args": {"run_id": self.run_id},
            })
        for name, t_ns, vals in self._counters:
            events.append({
                "name": name, "ph": "C",
                "ts": (t_ns + self._epoch_ns) / 1e3,
                "pid": self.process_index, "tid": 0,
                "args": dict(vals),
            })
        return events

    def export_chrome(self, path=None):
        """Write the span window as Chrome trace-event JSON; returns the
        path, or None on failure (counted in ``dropped``, never
        raised)."""
        path = path or self.default_trace_path()
        if path is None:
            raise ValueError("export_chrome: no path and no trace_dir — "
                             "enable(trace_dir=...) or pass a path")
        doc = {"traceEvents": self.chrome_events(),
               "displayTimeUnit": "ms"}
        try:
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)
            tmp = f"{path}.tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
            return path
        except OSError as e:
            self.dropped += 1
            logger.warning("trace export failed: %s", e)
            return None

    # -- flight recorder ----------------------------------------------------

    def flight_dump(self, reason="manual", last_n=256, extra=None):
        """Dump the last ``last_n`` spans + a telemetry snapshot to the
        flight file; returns the path or None.  ``extra`` (a JSON-ready
        dict) rides along under ``"extra"`` — the OOM postmortem books
        its census/footprint/watermark evidence through it.  Safe from
        signal handlers and excepthooks (never raises)."""
        path = self.flight_path
        if path is None:
            return None
        try:
            spans = list(self._spans)[-int(last_n):]
            try:
                from .telemetry import get_telemetry
                tel_snap = get_telemetry().snapshot()
            except Exception:
                tel_snap = None
            doc = {
                "reason": str(reason),
                "ts": time.time(),
                "pid": os.getpid(),
                "process_index": self.process_index,
                "run_id": self.run_id,
                "last_step_seconds": self._last_step_seconds,
                "overlap_fraction": self._last_overlap,
                "mfu_analytic": self._last_mfu,
                "program_flops": dict(self._program_flops),
                "spans": [{"name": s.name, "cat": s.cat,
                           "t0_ns": s.t0_ns, "t1_ns": s.t1_ns,
                           "tid": s.tid} for s in spans],
                "telemetry": tel_snap,
            }
            if extra:
                doc["extra"] = dict(extra)
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)
            tmp = f"{path}.tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
            self._flight_last_ns = time.perf_counter_ns()
            return path
        except Exception as e:
            self.dropped += 1
            try:
                logger.warning("flight dump failed: %s", e)
            except Exception:
                pass
            return None

    def _maybe_flight_refresh(self, now_ns):
        """Watchdog half of the flight recorder: keep the on-disk dump
        at most ``_FLIGHT_REFRESH_SEC`` stale so a SIGKILL (which runs
        no handlers) still leaves a recent record behind."""
        if self.flight_path is None:
            return
        if now_ns - self._flight_last_ns >= _FLIGHT_REFRESH_SEC * 1e9:
            self.flight_dump(reason="watchdog")

    def _excepthook(self, exc_type, exc, tb):
        self.flight_dump(reason=f"crash:{exc_type.__name__}")
        prev = self._prev_excepthook or sys.__excepthook__
        prev(exc_type, exc, tb)

    # -- snapshots ----------------------------------------------------------

    def phase_percentiles_ms(self):
        """{phase: {p50, p95}} in ms from the phase histogram (only
        phases that saw samples)."""
        if not self._metrics_made:
            return {}
        out = {}
        for phase in PHASES:
            p50 = self._m_phase.percentile(0.50, phase=phase)
            if p50 is None:
                continue
            p95 = self._m_phase.percentile(0.95, phase=phase)
            out[phase] = {"p50": round(p50 * 1000, 3),
                          "p95": round(p95 * 1000, 3)}
        return out

    def snapshot(self):
        """Compact JSON-ready trace summary (attached to bench
        records)."""
        kind = _device_kind()
        ov = self.overlap_fraction()
        mfu = self.mfu_analytic()
        return {
            "enabled": self.enabled,
            "process_index": self.process_index,
            "run_id": self.run_id,
            "spans": len(self._spans),
            "counters": len(self._counters),
            "phase_ms": self.phase_percentiles_ms(),
            "overlap_fraction": (round(ov, 4) if ov is not None
                                 else None),
            "flops_per_step": self.flops_per_step(),
            "device_kind": kind,
            "device_peak_flops": peak_flops(kind),
            "mfu_analytic": (round(mfu, 6) if mfu is not None else None),
            "flight_recorder": self.flight_path,
            "dropped": self.dropped,
        }


# -- process singleton ------------------------------------------------------

_tracer: Tracer | None = None
_tracer_lock = threading.Lock()


def get_tracer() -> Tracer:
    """The process-global tracer.  Created (disabled) on first call;
    auto-enabled iff ``PT_TRACE`` is truthy or ``PT_FLIGHT_RECORDER``
    names a dump directory — env consulted lazily so plain imports stay
    side-effect-free."""
    global _tracer
    if _tracer is None:
        with _tracer_lock:
            if _tracer is None:
                t = Tracer()
                flight = os.environ.get("PT_FLIGHT_RECORDER", "").strip()
                if _env_flag("PT_TRACE") or flight:
                    t.enable(
                        trace_dir=(os.environ.get("PT_TRACE_DIR")
                                   or None),
                        flight_dir=flight or None)
                _tracer = t
    return _tracer


def current_tracer() -> Tracer | None:
    """The singleton if it already exists, else None — for callers
    (healthz, telemetry hooks) that must not trigger env-based
    enablement as a side effect."""
    return _tracer


def reset_tracer():
    """Drop the global tracer (test isolation)."""
    global _tracer
    with _tracer_lock:
        t, _tracer = _tracer, None
    if t is not None:
        t.disable()
