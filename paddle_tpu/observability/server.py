"""Stdlib-only HTTP endpoint: ``/metrics`` + ``/healthz``.

A daemon-threaded ``ThreadingHTTPServer`` bound to localhost by
default, serving

 - ``/metrics``  Prometheus text exposition of the metrics registry
 - ``/healthz``  JSON liveness summary (HTTP 503 when unhealthy)

Nothing here runs unless explicitly started (``MetricsServer.start`` /
``start_http_server`` / ``PT_METRICS_PORT``); the import does not bind
a socket or spawn a thread.  ``port=0`` binds an ephemeral port and
publishes it on ``server.port`` — the test-friendly default.
"""
from __future__ import annotations

import json
import threading

from .logs import get_logger
from .metrics import get_registry

__all__ = ["MetricsServer", "start_http_server"]

logger = get_logger(__name__)

CONTENT_TYPE_METRICS = "text/plain; version=0.0.4; charset=utf-8"


class MetricsServer:
    def __init__(self, registry=None, health_cb=None, host="127.0.0.1",
                 port=0, metrics_cb=None):
        """``metrics_cb`` (a zero-arg callable returning exposition
        text) overrides the registry render — how the cluster
        aggregator re-serves its merged view through this same
        endpoint."""
        self._registry = registry if registry is not None \
            else get_registry()
        self._metrics_cb = metrics_cb
        self._health_cb = health_cb
        self._host = host
        self._requested_port = int(port)
        self._httpd = None
        self._thread = None
        self.port = None

    @property
    def host(self):
        return self._host

    def start(self):
        """Bind + serve on a daemon thread. Idempotent."""
        if self._httpd is not None:
            return self
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        registry = self._registry
        metrics_cb = (self._metrics_cb if self._metrics_cb is not None
                      else registry.prometheus_text)
        health_cb = self._health_cb

        class _Handler(BaseHTTPRequestHandler):
            def _send(self, code, ctype, body):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        body = metrics_cb().encode("utf-8")
                        self._send(200, CONTENT_TYPE_METRICS, body)
                    elif path == "/healthz":
                        health = (health_cb() if health_cb is not None
                                  else {"ok": True})
                        code = 200 if health.get("ok", True) else 503
                        self._send(code, "application/json",
                                   (json.dumps(health) + "\n").encode())
                    else:
                        self._send(404, "text/plain; charset=utf-8",
                                   b"not found; try /metrics /healthz\n")
                except Exception as e:
                    logger.warning("metrics endpoint error on %s: %s",
                                   path, e)
                    try:
                        self._send(500, "text/plain; charset=utf-8",
                                   f"error: {e}\n".encode())
                    except OSError:
                        pass  # client went away mid-reply

            def log_message(self, fmt, *args):
                logger.debug("metrics-server: " + fmt, *args)

        self._httpd = ThreadingHTTPServer(
            (self._host, self._requested_port), _Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="pt-metrics-server",
            daemon=True)
        self._thread.start()
        logger.info("metrics endpoint on http://%s:%d (/metrics, "
                    "/healthz)", self._host, self.port)
        return self

    def stop(self):
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.port = None


def start_http_server(port=0, registry=None, health_cb=None,
                      host="127.0.0.1"):
    """One-call endpoint bring-up; returns the started server (read
    ``.port`` for the bound port)."""
    return MetricsServer(registry=registry, health_cb=health_cb,
                         host=host, port=port).start()
