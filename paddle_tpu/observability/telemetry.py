"""Step telemetry: wall time, throughput, device memory, compile events.

``TrainingTelemetry`` is the process singleton every instrumented hot
path talks to (``hapi.Model`` loops, ``auto_parallel.Engine.fit``,
``CheckpointManager``, elastic heartbeats, collectives, ``DataLoader``).
Design rules, in priority order:

1. **Zero cost while disabled.**  Every hook starts with a plain
   attribute check (``if not self.enabled: return``); no metric objects
   exist, no file/socket/thread is ever created, and nothing touches
   jax.  ``import paddle_tpu.observability`` is side-effect-free.
2. **Never sync the device.**  Step timing is host wall-clock around
   the (async-dispatch) step call; collective byte counts come from
   array metadata; device memory uses ``Device.memory_stats()`` only
   when a backend already exists.  The telemetry layer must not create
   the host round-trips tpu-lint exists to catch.
3. **Never take down the run.**  Sink write failures are counted and
   dropped; the compile-log filter swallows its own exceptions.

Compile visibility: jax logs every XLA compile ("Compiling <fn> with
global shapes and types ...", ``jax/_src/interpreters/pxla.py``) when
``jax_log_compiles`` is on.  :class:`CompileWatcher` flips that config
and installs a ``logging.Filter`` on the emitting loggers, which sees
each record's structured args (function name + abstract signature),
feeds the metrics/sentinel, and suppresses the record so user stderr
stays clean (unless the user had the config on already).  The
:class:`RecompileSentinel` is the dynamic twin of lint rule TPU001's
retrace-storm heuristics: N compiles of the SAME callable with N
distinct signatures means shape/weak-type churn, and it names the
offender at runtime.

Enable explicitly (``configure(enabled=True, ...)``) or via env:
``PT_TELEMETRY=1`` [+ ``PT_TELEMETRY_DIR``, ``PT_METRICS_PORT``],
checked once, lazily, on the first ``get_telemetry()`` call.
"""
from __future__ import annotations

import logging
import os
import sys
import threading
import time
from collections import deque

from .events import EventSink
from .logs import get_logger
from .metrics import get_registry

__all__ = [
    "TrainingTelemetry", "StepTimer", "CompileWatcher",
    "RecompileSentinel", "get_telemetry", "configure", "reset",
]

logger = get_logger(__name__)

_TRUTHY = {"1", "true", "yes", "on"}

# loggers jax emits per-compile records on (jit/pjit path + dispatch)
_JAX_COMPILE_LOGGERS = ("jax._src.interpreters.pxla", "jax._src.dispatch")


def _env_flag(name):
    return os.environ.get(name, "").strip().lower() in _TRUTHY


def _resolve_identity():
    """(process_index, run_id) of this process in a cluster launch.

    ``PT_PROCESS_INDEX`` wins over the launcher-set
    ``PADDLE_TRAINER_ID``; both default to 0 (a single-process run IS
    rank 0 of a world of 1).  ``PT_RUN_ID`` defaults to ``"local"``.
    Pids are deliberately NOT part of the identity — they change on
    every elastic restart while (run_id, rank) survives.
    """
    raw = (os.environ.get("PT_PROCESS_INDEX")
           or os.environ.get("PADDLE_TRAINER_ID") or "").strip()
    try:
        idx = int(raw) if raw else 0
    except ValueError:
        idx = 0
    run_id = (os.environ.get("PT_RUN_ID") or "").strip() or "local"
    return idx, run_id


class RecompileSentinel:
    """Detects recompile storms and names the offending callable.

    Trips when one callable has been compiled ``threshold`` times with
    ``threshold`` distinct signatures — steady-state training compiles a
    step function once (or once per real shape bucket); per-step fresh
    signatures mean the input shapes / weak types churn every call.
    """

    def __init__(self, threshold=5, keep_recent=4):
        self.threshold = max(2, int(threshold))
        self._keep_recent = keep_recent
        self._lock = threading.Lock()
        self._state: dict = {}
        self._tripped: dict = {}

    def observe(self, name, signature=""):
        """Record one compile; returns trip info the first time ``name``
        crosses the threshold, else None."""
        with self._lock:
            st = self._state.get(name)
            if st is None:
                st = self._state[name] = {
                    "count": 0, "sig_hashes": set(),
                    "recent": deque(maxlen=self._keep_recent)}
            st["count"] += 1
            if len(st["sig_hashes"]) < 4096:
                st["sig_hashes"].add(hash(signature))
            if signature:
                st["recent"].append(str(signature)[:400])
            if (name not in self._tripped
                    and st["count"] >= self.threshold
                    and len(st["sig_hashes"]) >= self.threshold):
                info = {"callable": name,
                        "compiles": st["count"],
                        "distinct_signatures": len(st["sig_hashes"]),
                        "recent_signatures": list(st["recent"])}
                self._tripped[name] = info
                return info
        return None

    def compile_counts(self):
        with self._lock:
            return {n: st["count"] for n, st in self._state.items()}

    def tripped(self):
        """{callable_name: trip info} for every storm seen so far."""
        with self._lock:
            return dict(self._tripped)


class _CompileLogFilter:
    """``logging.Filter`` duck-type: parses jax's per-compile records,
    optionally suppressing them (when WE turned the logging on)."""

    def __init__(self, telemetry, swallow):
        self._tel = telemetry
        self._swallow = swallow

    def filter(self, record):
        try:
            msg = record.msg if isinstance(record.msg, str) else ""
            if msg.startswith("Compiling ") and record.args:
                args = (record.args if isinstance(record.args, tuple)
                        else (record.args,))
                name = str(args[0])
                sig = "; ".join(str(a)[:400] for a in args[1:])
                self._tel._on_compile(name, sig)
                return not self._swallow
            if msg.startswith("Finished "):
                # log_elapsed_time spans ("Finished tracing...", "Finished
                # XLA compilation...") promoted to WARNING by the very
                # config we flipped on; drop them unless the user had
                # jax_log_compiles enabled themselves
                return not self._swallow
        except Exception:  # a broken filter must never break jax logging
            return True
        return True


class CompileWatcher:
    """Hooks jax's compile path via ``jax_log_compiles`` + log filters.

    Install is lazy and idempotent: a no-op until jax has been imported
    by someone else (telemetry never imports jax itself), retried from
    the step hooks so late jax imports still get coverage.  Uninstall
    restores the user's prior ``jax_log_compiles`` value.
    """

    def __init__(self, telemetry):
        self._tel = telemetry
        self._filters: list = []
        self._prev_log_compiles = None
        self.installed = False

    def install(self):
        if self.installed or "jax" not in sys.modules:
            return self.installed
        try:
            jax = sys.modules["jax"]
            prev = bool(jax.config.jax_log_compiles)
            if not prev:
                jax.config.update("jax_log_compiles", True)
            self._prev_log_compiles = prev
        except Exception as e:
            logger.debug("compile watcher: cannot enable "
                         "jax_log_compiles: %s", e)
            return False
        for name in _JAX_COMPILE_LOGGERS:
            f = _CompileLogFilter(self._tel, swallow=not prev)
            logging.getLogger(name).addFilter(f)
            self._filters.append((name, f))
        self.installed = True
        return True

    def uninstall(self):
        if not self.installed:
            return
        for name, f in self._filters:
            logging.getLogger(name).removeFilter(f)
        self._filters = []
        if self._prev_log_compiles is False:
            try:
                sys.modules["jax"].config.update("jax_log_compiles", False)
            except Exception as e:
                logger.debug("compile watcher: restore failed: %s", e)
        self.installed = False


class StepTimer:
    """``with tel.step(batch_size=..., mode=...):`` convenience span."""

    __slots__ = ("_tel", "_mode", "_batch_size", "_token")

    def __init__(self, telemetry, mode="train", batch_size=None):
        self._tel = telemetry
        self._mode = mode
        self._batch_size = batch_size
        self._token = None

    def __enter__(self):
        self._token = self._tel.step_start()
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self._tel.step_end(self._token, batch_size=self._batch_size,
                               mode=self._mode)
        return False


class TrainingTelemetry:
    """Process-wide telemetry hub (see module docstring for contract)."""

    def __init__(self):
        self.enabled = False
        self.process_index, self.run_id = _resolve_identity()
        self._lock = threading.RLock()
        self.sentinel = RecompileSentinel(
            threshold=int(os.environ.get("PT_RECOMPILE_THRESHOLD") or 5))
        self._watcher = CompileWatcher(self)
        self.sink: EventSink | None = None
        self.server = None
        self._metrics_made = False
        self._start_ts = time.time()
        self._steps = 0
        self._step_times = deque(maxlen=512)
        self._last_step_ts = None
        self._last_ckpt_step = None
        self._last_heartbeat_ts = None
        self._lease_ttl = None
        self._store_last_ok_ts = None
        self._store_last_fail_ts = None
        self._store_generation = None
        self._capture_hits = 0
        self._capture_misses: dict = {}
        self._fusion_rewrites: dict = {}
        self._fusion_fallbacks: dict = {}
        self._compile_listeners: list = []
        # refresh device-memory gauges every N steps (stats read is a
        # host-side allocator query, cheap but not free)
        self._mem_every = 32

    # -- lifecycle ----------------------------------------------------------

    @property
    def registry(self):
        return get_registry()

    def enable(self, jsonl_dir=None, http_port=None, compile_watch=True,
               process_index=None, run_id=None):
        """Turn telemetry on (idempotent; each facility added at most
        once).  ``http_port=0`` binds an ephemeral port; ``None`` means
        no endpoint.  ``process_index``/``run_id`` override the
        env-resolved identity stamped on every metric series and JSONL
        record.  Returns self."""
        with self._lock:
            if process_index is not None:
                self.process_index = int(process_index)
            if run_id is not None:
                self.run_id = str(run_id)
            if not self.enabled:
                self.enabled = True
                self._make_metrics()
            self.registry.set_const_labels(
                process_index=self.process_index, run_id=self.run_id)
            if compile_watch:
                self._watcher.install()
            if jsonl_dir is not None and self.sink is None:
                self.sink = EventSink(str(jsonl_dir),
                                      run_id=self.run_id,
                                      process_index=self.process_index)
            if http_port is not None and self.server is None:
                from .server import MetricsServer
                self.server = MetricsServer(self.registry,
                                            health_cb=self.healthz,
                                            port=int(http_port))
                self.server.start()
        return self

    def publish_endpoint(self, store, world_size=None):
        """Publish this rank's ``/metrics`` endpoint into the
        coordination store under ``obs/<run_id>/endpoint/<rank>`` so the
        cluster aggregator can discover it; also (re)sets
        ``obs/<run_id>/world`` when ``world_size`` is given — EVERY rank
        writing it keeps discovery alive across a master respawn with a
        partial WAL.  ``store`` is any TCPStore-shaped client; pass a
        :class:`~paddle_tpu.distributed.resilient_store.ResilientStore`
        to survive master failover.  Returns the published "host:port".
        """
        with self._lock:
            server = self.server
        if server is None or server.port is None:
            raise RuntimeError(
                "publish_endpoint: no metrics server is running — "
                "enable(http_port=...) first")
        from .aggregator import endpoint_key, world_key
        ep = f"{server.host}:{server.port}"
        store.set(endpoint_key(self.run_id, self.process_index),
                  ep.encode("ascii"))
        if world_size is not None:
            store.set(world_key(self.run_id),
                      str(int(world_size)).encode("ascii"))
        logger.info("published metrics endpoint %s as rank %d of run "
                    "%s", ep, self.process_index, self.run_id)
        return ep

    def disable(self):
        with self._lock:
            self.enabled = False
            self._watcher.uninstall()
            if self.server is not None:
                self.server.stop()
                self.server = None
            if self.sink is not None:
                self.sink.close()
                self.sink = None
        return self

    def _make_metrics(self):
        if self._metrics_made:
            return
        self._metrics_made = True
        r = self.registry
        self._m_steps = r.counter(
            "pt_steps_total", "training/eval steps completed", ("mode",))
        self._m_step_time = r.histogram(
            "pt_step_time_seconds", "per-step wall time", ("mode",))
        self._m_throughput = r.gauge(
            "pt_throughput_samples_per_second",
            "samples/sec of the most recent step", ("mode",))
        self._m_last_step_ts = r.gauge(
            "pt_last_step_timestamp_seconds",
            "unix time the last step finished")
        self._m_compiles = r.counter(
            "pt_compiles_total", "XLA compilations observed", ("fn",))
        self._m_storms = r.counter(
            "pt_recompile_storms_total",
            "callables that tripped the recompile sentinel")
        self._m_data_wait = r.histogram(
            "pt_data_wait_seconds",
            "time the training loop waited for the next batch")
        self._m_batches = r.counter(
            "pt_data_batches_total", "batches produced by DataLoader")
        self._m_coll_ops = r.counter(
            "pt_collective_ops_total", "collective op invocations",
            ("op",))
        self._m_coll_bytes = r.counter(
            "pt_collective_bytes_total",
            "input bytes entering collectives (metadata-derived)",
            ("op",))
        from .metrics import log_buckets
        self._m_coll_bytes_hist = r.histogram(
            "pt_collective_bytes",
            "per-invocation input bytes of collectives "
            "(metadata-derived distribution; the ROADMAP 'time + "
            "bytes' pair with pt_collective_time_seconds)", ("op",),
            buckets=log_buckets(1e2, 1e9, per_decade=1))
        self._m_coll_time = r.histogram(
            "pt_collective_time_seconds",
            "host-boundary wall time of eagerly dispatched collectives "
            "(not recorded inside traces)", ("op",))
        self._m_grad_buckets = r.counter(
            "pt_grad_buckets_total",
            "gradient-reduction buckets built by train-step tracing, "
            "by reduction kind (all_reduce = fused dp pmean; "
            "reduce_scatter = planned ZeRO hierarchical schedule)",
            ("kind",))
        self._m_grad_bucket_bytes = r.histogram(
            "pt_grad_bucket_bytes",
            "flat-concatenated payload bytes of each gradient bucket "
            "(the fused all-reduce granularity, vs the per-parameter "
            "sizes it replaced)",
            buckets=log_buckets(1e2, 1e9, per_decade=1))
        self._m_ckpt_ops = r.counter(
            "pt_checkpoint_ops_total", "checkpoint operations",
            ("op", "status"))
        self._m_ckpt_save_s = r.histogram(
            "pt_checkpoint_save_seconds", "checkpoint commit duration")
        self._m_ckpt_restore_s = r.histogram(
            "pt_checkpoint_restore_seconds",
            "checkpoint restore duration")
        self._m_ckpt_latest = r.gauge(
            "pt_checkpoint_latest_step",
            "newest committed checkpoint step")
        self._m_ckpt_gc = r.counter(
            "pt_checkpoint_gc_deleted_total",
            "checkpoint directories removed by retention GC")
        self._m_ckpt_barrier_s = r.histogram(
            "pt_checkpoint_barrier_wait_seconds",
            "time spent in the multi-host commit barrier", ("status",))
        self._m_ckpt_swept = r.counter(
            "pt_checkpoint_staging_orphans_swept_total",
            "orphaned staging/partial-commit dirs removed by the "
            "startup janitor")
        self._m_hb = r.counter(
            "pt_elastic_heartbeats_total", "elastic store heartbeats",
            ("status",))
        self._m_hb_ts = r.gauge(
            "pt_elastic_last_heartbeat_timestamp_seconds",
            "unix time of the last successful heartbeat")
        self._m_mem = r.gauge(
            "pt_device_memory_bytes",
            "allocator stats summed over local devices", ("stat",))
        self._m_store_reconnects = r.counter(
            "pt_store_reconnects_total",
            "TCPStore client reconnect attempts (transient master "
            "outages absorbed by ResilientStore)", ("op",))
        self._m_store_unavail_s = r.histogram(
            "pt_store_unavailable_seconds",
            "time spent retrying before declaring the store master "
            "unavailable")
        self._m_store_gen = r.gauge(
            "pt_store_generation",
            "master generation last observed by this process")
        self._m_store_ok_ts = r.gauge(
            "pt_store_last_ok_timestamp_seconds",
            "unix time of the last successful store op")
        self._m_capture_hits = r.counter(
            "pt_capture_cache_hits_total",
            "captured-step signature-cache hits (replays with no retrace)")
        self._m_capture_misses = r.counter(
            "pt_capture_cache_misses_total",
            "captured-step cache misses", ("reason",))
        self._m_fusion_rewrites = r.counter(
            "pt_fusion_rewrites_total",
            "fusion-pass clusters rewritten to block-fused kernels",
            ("pattern",))
        self._m_fusion_fallbacks = r.counter(
            "pt_fusion_fallbacks_total",
            "fusion-pass clusters dispatched to the XLA fallback",
            ("pattern", "reason"))

    # -- step timing --------------------------------------------------------

    def step(self, mode="train", batch_size=None):
        return StepTimer(self, mode=mode, batch_size=batch_size)

    def step_start(self):
        """Opaque token for ``step_end`` (None while disabled — both
        hooks are no-ops then)."""
        if not self.enabled:
            return None
        return time.perf_counter()

    def step_end(self, token, batch_size=None, mode="train"):
        if token is None or not self.enabled:
            return
        dt = time.perf_counter() - token
        self.observe_step(dt, mode=mode, batch_size=batch_size)

    def observe_step(self, seconds, mode="train", batch_size=None):
        """Record one completed step of ``seconds`` wall time."""
        if not self.enabled:
            return
        now = time.time()
        self._m_steps.inc(mode=mode)
        self._m_step_time.observe(seconds, mode=mode)
        self._m_last_step_ts.set(now)
        throughput = None
        if batch_size and seconds > 0:
            throughput = batch_size / seconds
            self._m_throughput.set(throughput, mode=mode)
        with self._lock:
            self._steps += 1
            steps = self._steps
            self._last_step_ts = now
            self._step_times.append(float(seconds))
        if not self._watcher.installed:
            self._watcher.install()  # jax may have appeared since enable
        if steps % self._mem_every == 0:
            self._update_memory_gauges()
        if self.sink is not None:
            self.sink.emit("step", step=steps, mode=mode,
                           duration_sec=round(float(seconds), 6),
                           batch_size=batch_size,
                           throughput=(round(throughput, 2)
                                       if throughput else None))
        # derived trace gauges (overlap fraction, analytic MFU) refresh
        # per step; sys.modules-gated so a run that never imported the
        # tracer pays nothing here
        tr_mod = sys.modules.get("paddle_tpu.observability.trace")
        if tr_mod is not None:
            tr = tr_mod.current_tracer()
            if tr is not None and tr.enabled:
                tr.on_step(seconds)
        # goodput gauges refresh per step over the same span ring; same
        # sys.modules gate — never imports, never touches the device
        gp_mod = sys.modules.get("paddle_tpu.observability.goodput")
        if gp_mod is not None:
            gp = gp_mod.current_ledger()
            if gp is not None and gp.enabled:
                gp.refresh()
        # memory watermark timeline samples at step boundaries through
        # the same gate — allocator reads only, never a device sync
        mem_mod = sys.modules.get("paddle_tpu.observability.memory")
        if mem_mod is not None:
            mm = mem_mod.current_memory_monitor()
            if mm is not None and mm.enabled:
                mm.on_step(steps)

    # -- data / collectives -------------------------------------------------

    def data_wait(self, seconds):
        if not self.enabled:
            return
        self._m_data_wait.observe(seconds)
        self._m_batches.inc()

    def collective_op(self, op, nbytes=0):
        if not self.enabled:
            return
        self._m_coll_ops.inc(op=op)
        if nbytes:
            self._m_coll_bytes.inc(nbytes, op=op)
            self._m_coll_bytes_hist.observe(nbytes, op=op)

    def collective_time(self, op, seconds):
        """Host wall time around ONE eager collective dispatch (the
        caller guarantees it is not tracing — see
        ``distributed.collective._timed``)."""
        if not self.enabled:
            return
        self._m_coll_time.observe(float(seconds), op=op)

    def grad_bucket(self, nbytes, kind="all_reduce"):
        """One gradient bucket materialized at train-step trace time;
        ``nbytes`` is the flat-concatenated payload of its fused
        reduction (recorded once per trace — the honest count, like
        ``collective_op``) and ``kind`` the reduction it compiles to."""
        if not self.enabled:
            return
        self._m_grad_buckets.inc(kind=kind)
        self._m_grad_bucket_bytes.observe(float(nbytes))

    # -- checkpoints ----------------------------------------------------------

    def record_checkpoint_save(self, seconds, step=None, mode="sync",
                               ok=True):
        if not self.enabled:
            return
        self._m_ckpt_ops.inc(op="save",
                             status="ok" if ok else f"{mode}_error")
        self._m_ckpt_save_s.observe(seconds)
        if ok and step is not None:
            with self._lock:
                self._last_ckpt_step = int(step)
            self._m_ckpt_latest.set(int(step))
        if self.sink is not None:
            self.sink.emit("checkpoint_save", step=step, mode=mode,
                           ok=ok, duration_sec=round(float(seconds), 6))

    def record_checkpoint_restore(self, seconds, step=None, ok=True):
        if not self.enabled:
            return
        self._m_ckpt_ops.inc(op="restore", status="ok" if ok else "error")
        self._m_ckpt_restore_s.observe(seconds)
        if ok and step is not None:
            with self._lock:
                self._last_ckpt_step = int(step)
            self._m_ckpt_latest.set(int(step))
        if self.sink is not None:
            self.sink.emit("checkpoint_restore", step=step, ok=ok,
                           duration_sec=round(float(seconds), 6))

    def record_checkpoint_gc(self, deleted):
        if not self.enabled or not deleted:
            return
        self._m_ckpt_gc.inc(deleted)

    def record_barrier_wait(self, seconds, ok=True):
        """Time one process spent in the checkpoint commit barrier —
        a stalled barrier (straggler or dead rank) shows up here long
        before the timeout names the missing ranks."""
        if not self.enabled:
            return
        self._m_ckpt_barrier_s.observe(seconds,
                                       status="ok" if ok else "timeout")
        if not ok and self.sink is not None:
            self.sink.emit("checkpoint_barrier_timeout",
                           duration_sec=round(float(seconds), 6))

    def record_staging_sweep(self, n):
        """The startup janitor removed ``n`` orphaned staging dirs /
        partial marker sets (crash debris of dead save attempts)."""
        if not self.enabled or not n:
            return
        self._m_ckpt_swept.inc(n)
        if self.sink is not None:
            self.sink.emit("checkpoint_staging_swept", count=int(n))

    def record_async_save_failure(self, step, error):
        """Async writer failed — the manager re-raises it on the next
        call, but the metric/event makes the failure visible NOW."""
        if not self.enabled:
            return
        self._m_ckpt_ops.inc(op="save", status="async_error")
        if self.sink is not None:
            self.sink.emit("checkpoint_async_save_failed", step=step,
                           error=str(error)[:400])

    # -- elastic heartbeats -------------------------------------------------

    def heartbeat(self, ok=True, lease_ttl=None):
        if not self.enabled:
            return
        self._m_hb.inc(status="ok" if ok else "error")
        if lease_ttl is not None:
            with self._lock:
                self._lease_ttl = float(lease_ttl)
        if ok:
            now = time.time()
            self._m_hb_ts.set(now)
            with self._lock:
                self._last_heartbeat_ts = now

    # -- coordination store -------------------------------------------------

    def record_store_op(self, generation=None):
        """One store op succeeded (through ResilientStore).  Feeds the
        ``store`` healthz block: last-ok age + current generation."""
        if not self.enabled:
            return
        now = time.time()
        self._m_store_ok_ts.set(now)
        with self._lock:
            self._store_last_ok_ts = now
            if generation is not None:
                self._store_generation = int(generation)
        if generation is not None:
            self._m_store_gen.set(int(generation))

    def record_store_reconnect(self, op):
        """A store op hit a transient connection failure and is being
        retried against a (possibly respawned) master."""
        if not self.enabled:
            return
        self._m_store_reconnects.inc(op=str(op))
        if self.sink is not None:
            self.sink.emit("store_reconnect", op=str(op))

    def record_store_unavailable(self, seconds, op=None, endpoint=None):
        """ResilientStore exhausted its deadline — the master stayed
        unreachable for ``seconds``.  Positive evidence for healthz."""
        if not self.enabled:
            return
        self._m_store_unavail_s.observe(float(seconds))
        with self._lock:
            self._store_last_fail_ts = time.time()
        if self.sink is not None:
            self.sink.emit("store_unavailable", op=op, endpoint=endpoint,
                           duration_sec=round(float(seconds), 3))

    # -- capture cache (jit.capture_step) -----------------------------------

    def capture_cache_hit(self):
        """One captured-step call replayed from the signature cache."""
        self._capture_hits += 1  # GIL-atomic; host-side counter feeds
        if self.enabled:         # snapshot() even while metrics are off
            self._m_capture_hits.inc()

    def capture_cache_miss(self, reason):
        """One captured-step call that could not replay; ``reason`` is
        one of first_trace / signature_change / capture_unsafe /
        unsupported_args."""
        reason = str(reason)
        self._capture_misses[reason] = \
            self._capture_misses.get(reason, 0) + 1
        if self.enabled:
            self._m_capture_misses.inc(reason=reason)

    # -- graph-level fusion pass (ops.fusion_pass) --------------------------

    def fusion_rewrite(self, pattern):
        """One jaxpr cluster rewritten to a block-fused kernel call."""
        pattern = str(pattern)
        self._fusion_rewrites[pattern] = \
            self._fusion_rewrites.get(pattern, 0) + 1
        if self.enabled:
            self._m_fusion_rewrites.inc(pattern=pattern)

    def fusion_fallback(self, pattern, reason):
        """One rewritten cluster dispatched to the XLA fallback;
        ``reason`` is tpu_unreachable or canary_failed."""
        pattern, reason = str(pattern), str(reason)
        key = f"{pattern}:{reason}"
        self._fusion_fallbacks[key] = \
            self._fusion_fallbacks.get(key, 0) + 1
        if self.enabled:
            self._m_fusion_fallbacks.inc(pattern=pattern, reason=reason)

    # -- compiles (called from the log filter) ------------------------------

    def record_compile(self, name, signature=""):
        """Public compile-event feed for sources other than jax's
        compile log (AOT pipelines, drills) — same metrics/sentinel
        path as the log filter."""
        self._on_compile(name, signature)

    def ensure_compile_watch(self):
        """Install the jax compile-log watcher without flipping the rest
        of telemetry on.  Lets the serving engine's zero-compile
        sentinel see compile events even when metrics are disabled
        (compile events still reach listeners/sentinel; only metric
        booking is gated on ``enabled``)."""
        return self._watcher.install()

    def add_compile_listener(self, fn):
        """Register ``fn(name, signature)`` to be invoked on every
        observed compile (log-filter or :meth:`record_compile`).
        Listener exceptions are swallowed — observers must not break
        the compile path."""
        with self._lock:
            if fn not in self._compile_listeners:
                self._compile_listeners.append(fn)

    def remove_compile_listener(self, fn):
        with self._lock:
            try:
                self._compile_listeners.remove(fn)
            except ValueError:
                pass

    def _on_compile(self, name, signature=""):
        for fn in list(self._compile_listeners):
            try:
                fn(name, signature)
            except Exception:
                pass
        if self.enabled:
            self._m_compiles.inc(fn=name)
        if self.sink is not None:
            self.sink.emit("compile", fn=name,
                           signature=signature[:400] or None)
        trip = self.sentinel.observe(name, signature)
        if trip is not None:
            if self.enabled:
                self._m_storms.inc()
            logger.warning(
                "recompile storm: %s compiled %d times with %d distinct "
                "signatures — input shape/weak-type churn; pad to fixed "
                "shapes or mark changing args static",
                name, trip["compiles"], trip["distinct_signatures"])
            if self.sink is not None:
                self.sink.emit("recompile_storm", **trip)

    # -- device memory ------------------------------------------------------

    def device_memory(self):
        """Summed allocator stats over local devices; {} when no jax
        backend exists yet (never initializes one just to ask).
        Delegates to the one guarded read in ``observability.memory``
        — the consolidation point shared with the ``device.cuda``
        parity shims."""
        from .memory import device_memory_stats
        return device_memory_stats()

    def _update_memory_gauges(self):
        mem = self.device_memory()
        if not mem:
            return
        for k, v in mem.items():
            self._m_mem.set(v, stat=k)

    # -- snapshots / health -------------------------------------------------

    def step_percentiles_ms(self):
        """Exact host-side p50/p95 over the last <=512 steps."""
        with self._lock:
            times = sorted(self._step_times)
        if not times:
            return {"p50": None, "p95": None}
        def pick(q):
            i = min(len(times) - 1, int(q * (len(times) - 1) + 0.5))
            return round(times[i] * 1000, 3)
        return {"p50": pick(0.50), "p95": pick(0.95)}

    def snapshot(self):
        """Compact JSON-ready health summary (attached to bench
        records; the full registry dump is ``registry.snapshot()``)."""
        compile_counts = self.sentinel.compile_counts()
        top = sorted(compile_counts.items(), key=lambda kv: -kv[1])[:8]
        pct = self.step_percentiles_ms()
        with self._lock:
            steps = self._steps
            last_ckpt = self._last_ckpt_step
        mem = self.device_memory()
        # numerics block: anomaly counts (incl. AMP scaler skips) ride
        # along in every snapshot. sys.modules-gated like the tracer
        # feed — read-only, never triggers enablement.
        numerics = None
        n_mod = sys.modules.get("paddle_tpu.observability.numerics")
        if n_mod is not None:
            m = n_mod.current_monitor()
            if m is not None:
                ns = m.snapshot()
                numerics = {
                    "enabled": ns["enabled"],
                    "anomalies": ns["anomalies"],
                    "anomalies_total": ns["anomalies_total"],
                    "last_anomaly": ns["last_anomaly"],
                    "reads": ns["reads"],
                }
        goodput = None
        gp_mod = sys.modules.get("paddle_tpu.observability.goodput")
        if gp_mod is not None:
            gp = gp_mod.current_ledger()
            if gp is not None and gp.enabled:
                dec = gp.refresh()
                if dec is not None:
                    goodput = {
                        "goodput_fraction": dec["goodput_fraction"],
                        "badput_seconds": dec["badput_seconds"],
                    }
        memory = None
        mem_mod = sys.modules.get("paddle_tpu.observability.memory")
        if mem_mod is not None:
            mm = mem_mod.current_memory_monitor()
            if mm is not None:
                ms = mm.snapshot()
                memory = {
                    "enabled": ms["enabled"],
                    "fit_ok": ms["fit_ok"],
                    "programs": len(ms["programs"]),
                    "fragmentation_bytes": ms["fragmentation_bytes"],
                    "oom_events": ms["oom_events"],
                    "last_oom": ms["last_oom"],
                }
        return {
            "enabled": self.enabled,
            "pid": os.getpid(),
            "process_index": self.process_index,
            "run_id": self.run_id,
            "steps": steps,
            "step_ms_p50": pct["p50"],
            "step_ms_p95": pct["p95"],
            "compiles": sum(compile_counts.values()),
            "compiles_by_fn": dict(top),
            "recompile_storms": sorted(self.sentinel.tripped()),
            "capture": {"hits": self._capture_hits,
                        "misses": dict(self._capture_misses)},
            "fusion": {"rewrites": dict(self._fusion_rewrites),
                       "fallbacks": dict(self._fusion_fallbacks)},
            "peak_device_memory_bytes": mem.get("peak_bytes_in_use"),
            "device_memory_bytes": mem.get("bytes_in_use"),
            "last_checkpoint_step": last_ckpt,
            "events_dropped": self.sink.dropped if self.sink else 0,
            "numerics": numerics,
            "goodput": goodput,
            "memory": memory,
        }

    def healthz(self):
        """Liveness summary served on ``/healthz``.  ``ok`` is False
        only on positive evidence of trouble (an expired heartbeat
        lease) — a run that simply has no elastic layer is healthy."""
        now = time.time()
        with self._lock:
            last_step_ts = self._last_step_ts
            last_hb = self._last_heartbeat_ts
            ttl = self._lease_ttl
            steps = self._steps
            last_ckpt = self._last_ckpt_step
            store_ok_ts = self._store_last_ok_ts
            store_fail_ts = self._store_last_fail_ts
            store_gen = self._store_generation
        elastic = None
        lease_ok = None
        if last_hb is not None:
            age = now - last_hb
            lease_ok = (age <= ttl) if ttl is not None else True
            elastic = {"last_heartbeat_age_sec": round(age, 3),
                       "lease_ttl_sec": ttl, "lease_ok": lease_ok}
        # store block: unhealthy only on positive evidence — a declared
        # unavailability NOT followed by a later successful op.  A run
        # with no store, or one that recovered, is healthy.
        store = None
        store_ok = None
        if store_ok_ts is not None or store_fail_ts is not None:
            store_ok = not (store_fail_ts is not None
                            and (store_ok_ts is None
                                 or store_fail_ts > store_ok_ts))
            store = {
                "last_ok_age_sec": (round(now - store_ok_ts, 3)
                                    if store_ok_ts is not None else None),
                "generation": store_gen,
                "ok": store_ok,
            }
        # flight-recorder path (if the tracer exists and has one armed)
        # — read-only: healthz must never trigger env-based enablement
        flight = None
        tr_mod = sys.modules.get("paddle_tpu.observability.trace")
        if tr_mod is not None:
            tr = tr_mod.current_tracer()
            if tr is not None:
                flight = tr.flight_path
        return {
            "ok": lease_ok is not False and store_ok is not False,
            "pid": os.getpid(),
            "process_index": self.process_index,
            "run_id": self.run_id,
            "uptime_sec": round(now - self._start_ts, 1),
            "steps": steps,
            "last_step_age_sec": (round(now - last_step_ts, 3)
                                  if last_step_ts is not None else None),
            "last_checkpoint_step": last_ckpt,
            "elastic": elastic,
            "store": store,
            "recompile_storms": len(self.sentinel.tripped()),
            "flight_recorder": flight,
        }


# -- process singleton ------------------------------------------------------

_telemetry: TrainingTelemetry | None = None
_telemetry_lock = threading.Lock()


def get_telemetry() -> TrainingTelemetry:
    """The process-global telemetry hub.  Created (disabled) on first
    call; auto-enabled here iff ``PT_TELEMETRY`` is truthy — the env is
    consulted lazily so plain imports stay side-effect-free."""
    global _telemetry
    if _telemetry is None:
        with _telemetry_lock:
            if _telemetry is None:
                t = TrainingTelemetry()
                if _env_flag("PT_TELEMETRY"):
                    port = os.environ.get("PT_METRICS_PORT", "").strip()
                    t.enable(
                        jsonl_dir=(os.environ.get("PT_TELEMETRY_DIR")
                                   or None),
                        http_port=int(port) if port else None)
                _telemetry = t
    return _telemetry


def configure(enabled=True, jsonl_dir=None, http_port=None,
              compile_watch=True) -> TrainingTelemetry:
    """Programmatic switch: ``configure(enabled=True, ...)`` turns the
    global hub on (see :meth:`TrainingTelemetry.enable`);
    ``enabled=False`` turns it off."""
    t = get_telemetry()
    if enabled:
        t.enable(jsonl_dir=jsonl_dir, http_port=http_port,
                 compile_watch=compile_watch)
    else:
        t.disable()
    return t


def reset():
    """Tear down the global hub AND the global registry (test
    isolation; not needed in production)."""
    global _telemetry
    with _telemetry_lock:
        t, _telemetry = _telemetry, None
    if t is not None:
        t.disable()
    from .trace import reset_tracer
    reset_tracer()  # its metric handles die with the registry below
    from .numerics import reset_monitor
    reset_monitor()
    from .sdc import reset_monitor as reset_sdc_monitor
    reset_sdc_monitor()
    from .goodput import reset_goodput
    reset_goodput()
    from .memory import reset_memory_monitor
    reset_memory_monitor()
    from .metrics import reset_registry
    reset_registry()
