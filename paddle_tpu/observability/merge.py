"""Stitch per-process telemetry JSONL streams into one cluster stream.

``python -m paddle_tpu.observability.merge <files-or-dirs> [-o OUT]``

Inputs are :class:`~.events.EventSink` files: the identity-aware
``telemetry-<run_id>-<rank>.jsonl`` (plus its rotated ``.jsonl.1``
generation) and the legacy ``telemetry-<pid>.jsonl``.  The output is
one time-ordered JSONL stream in which every record carries
``process_index`` and ``run_id`` — taken from the record itself when
present (pids are not stable across elastic restarts, so in-record
identity always wins) and otherwise recovered from the filename;
legacy pid-named files with no in-record identity keep ``null`` there
rather than inventing one.  Ordering is by timestamp with (input file,
line number) as a stable tiebreaker, so equal-timestamp records never
shuffle between runs.  Corrupt lines — the torn tail of a SIGKILLed
rank — are skipped and counted on stderr, never fatal.

``--trace`` switches to Chrome trace-event mode: inputs are the
per-rank ``trace-<run_id>-<rank>.json`` files the step tracer exports
(``Tracer.export_chrome``), and the output is ONE schema-valid Chrome
trace document whose pid axis is the rank — every rank's timeline in
one chrome://tracing / Perfetto view.  Per-rank files share a wall-
clock epoch anchor, so cross-rank span alignment is real time, not
per-process monotonic origins.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
from datetime import datetime

__all__ = ["discover_files", "merge_records", "discover_trace_files",
           "merge_traces", "main"]

# telemetry-<run_id>-<rank>.jsonl[.1] — run_id may itself contain
# dashes, so the rank is the LAST -<digits> group (greedy run match).
# The legacy telemetry-<pid>.jsonl form has only ONE dash group and
# deliberately does not match: a pid is not a rank.
_NEW_NAME = re.compile(
    r"^(?P<prefix>.+)-(?P<run>.+)-(?P<rank>\d+)\.jsonl(?:\.1)?$")


def _file_identity(path):
    """(run_id, rank) recovered from an EventSink filename; (None,
    None) for the legacy pid-named form (a pid is not a rank)."""
    name = os.path.basename(path)
    m = _NEW_NAME.match(name)
    if m:
        return m.group("run"), int(m.group("rank"))
    return None, None


def discover_files(paths):
    """Expand directories into their telemetry JSONL files; explicit
    file paths pass through.  Rotated ``.jsonl.1`` generations sort
    before their live file (they hold the OLDER records)."""
    out = []
    for p in paths:
        if os.path.isdir(p):
            for name in sorted(os.listdir(p)):
                if name.endswith(".jsonl") or name.endswith(".jsonl.1"):
                    out.append(os.path.join(p, name))
        else:
            out.append(p)

    def order(path):
        base = os.path.basename(path)
        return (base.replace(".jsonl.1", ".jsonl"),
                0 if base.endswith(".jsonl.1") else 1)

    out.sort(key=order)
    return out


# fromisoformat before 3.11 only accepts 3- or 6-digit fractions;
# telemetry from other writers may carry any width
_FRACTION = re.compile(r"\.(\d+)")


def _parse_ts(raw):
    s = str(raw)
    try:
        return datetime.fromisoformat(s).timestamp()
    except (ValueError, TypeError):
        pass
    try:
        fixed = _FRACTION.sub(
            lambda m: "." + m.group(1)[:6].ljust(6, "0"), s, count=1)
        return datetime.fromisoformat(fixed).timestamp()
    except (ValueError, TypeError):
        return None


def merge_records(files):
    """Read every file, label records with identity, sort by time.

    Returns ``(records, skipped)`` — ``skipped`` counts unparseable
    lines and unreadable files (both survivable by design: a SIGKILLed
    rank may leave a torn final line).
    """
    keyed = []
    skipped = 0
    for order, path in enumerate(files):
        f_run, f_rank = _file_identity(path)
        try:
            fh = open(path, "r", encoding="utf-8")
        except OSError as e:
            print(f"merge: cannot read {path}: {e}", file=sys.stderr)
            skipped += 1
            continue
        with fh:
            for lineno, line in enumerate(fh):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    skipped += 1
                    continue
                if not isinstance(rec, dict):
                    skipped += 1
                    continue
                if rec.get("process_index") is None:
                    rec["process_index"] = f_rank
                if rec.get("run_id") is None:
                    rec["run_id"] = f_run
                ts = _parse_ts(rec.get("ts"))
                keyed.append((ts if ts is not None else float("inf"),
                              order, lineno, rec))
    keyed.sort(key=lambda item: item[:3])
    return [item[3] for item in keyed], skipped


# trace-<run_id>-<rank>.json — same last--<digits> rank rule as the
# JSONL form above.
_TRACE_NAME = re.compile(r"^(?P<prefix>.+)-(?P<run>.+)-(?P<rank>\d+)\.json$")


def _trace_identity(path):
    name = os.path.basename(path)
    m = _TRACE_NAME.match(name)
    if m:
        return m.group("run"), int(m.group("rank"))
    return None, None


def discover_trace_files(paths):
    """Expand directories into their per-rank Chrome trace exports
    (``trace-*.json``); explicit file paths pass through."""
    out = []
    for p in paths:
        if os.path.isdir(p):
            for name in sorted(os.listdir(p)):
                if name.startswith("trace-") and name.endswith(".json"):
                    out.append(os.path.join(p, name))
        else:
            out.append(p)
    return out


def merge_traces(files):
    """Stitch per-rank Chrome trace docs into one cluster timeline.

    The pid of every event becomes the rank — recovered from the
    filename when possible, else taken from the event's own pid (the
    tracer already stamps pid=process_index).  Returns ``(doc,
    skipped)``; ``doc`` is a dict ready for ``json.dump``.
    """
    events = []
    skipped = 0
    seen_ranks = {}
    for path in files:
        _run, rank = _trace_identity(path)
        try:
            with open(path, "r", encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, ValueError) as e:
            print(f"merge: cannot read trace {path}: {e}", file=sys.stderr)
            skipped += 1
            continue
        evs = doc.get("traceEvents") if isinstance(doc, dict) else None
        if not isinstance(evs, list):
            skipped += 1
            continue
        for ev in evs:
            if not isinstance(ev, dict):
                skipped += 1
                continue
            pid = rank if rank is not None else ev.get("pid", 0)
            if ev.get("ph") == "M":
                # keep ONE process_name metadata event per rank
                if ev.get("name") == "process_name" and \
                        pid not in seen_ranks:
                    seen_ranks[pid] = dict(ev, pid=pid)
                continue
            events.append(dict(ev, pid=pid))
    events.sort(key=lambda ev: (ev.get("ts", 0), ev.get("pid", 0)))
    meta = [seen_ranks[r] for r in sorted(seen_ranks)]
    return {"traceEvents": meta + events,
            "displayTimeUnit": "ms"}, skipped


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.merge",
        description="Merge per-process telemetry JSONL streams into "
                    "one time-ordered, rank-labeled stream.")
    ap.add_argument("paths", nargs="+",
                    help="JSONL files, or directories containing "
                         "telemetry-*.jsonl[.1] (with --trace: "
                         "trace-*.json Chrome exports)")
    ap.add_argument("--output", "-o", default="-",
                    help="output file (default '-': stdout)")
    ap.add_argument("--trace", action="store_true",
                    help="stitch per-rank Chrome trace JSON exports "
                         "into one cluster timeline (pid = rank)")
    args = ap.parse_args(argv)

    if args.trace:
        files = discover_trace_files(args.paths)
        if not files:
            ap.error("no trace-*.json files found under the given paths")
        doc, skipped = merge_traces(files)
        out = (sys.stdout if args.output == "-"
               else open(args.output, "w", encoding="utf-8"))
        try:
            json.dump(doc, out)
            out.write("\n")
        finally:
            if out is not sys.stdout:
                out.close()
        if skipped:
            print(f"merge: skipped {skipped} unreadable "
                  f"event(s)/file(s)", file=sys.stderr)
        return 0

    files = discover_files(args.paths)
    if not files:
        ap.error("no telemetry JSONL files found under the given paths")
    records, skipped = merge_records(files)

    out = (sys.stdout if args.output == "-"
           else open(args.output, "w", encoding="utf-8"))
    try:
        for rec in records:
            out.write(json.dumps(rec, default=str) + "\n")
    finally:
        if out is not sys.stdout:
            out.close()
    if skipped:
        print(f"merge: skipped {skipped} unreadable line(s)/file(s)",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
