"""Runtime observability: metrics, step telemetry, events, health.

The third leg of the reliability stack — tpu-lint catches host-sync
hazards statically, the checkpoint layer makes runs crash-consistent,
and this package answers *"is this run healthy, how fast is each step,
and did something silently recompile?"* at runtime:

 - :mod:`.metrics`    thread-safe label-aware Counter/Gauge/Histogram
                      registry; Prometheus text + JSON snapshots
 - :mod:`.telemetry`  ``TrainingTelemetry``: step wall time and
                      throughput, device-memory gauges, per-callable
                      compile counts and the recompile sentinel
 - :mod:`.events`     per-process, size-rotated JSONL event stream
 - :mod:`.server`     stdlib HTTP endpoint: ``/metrics`` + ``/healthz``
 - :mod:`.aggregator` cluster view: scrape every rank's endpoint
                      (store-discovered), merge series, derive
                      step-time skew / straggler ratio / the
                      cross-rank recompile-storm alarm, re-serve
 - :mod:`.merge`      CLI stitching per-process telemetry JSONL
                      streams into one time-ordered rank-labeled one
                      (``--trace`` stitches per-rank Chrome traces)
 - :mod:`.trace`      ``Tracer``: step-phase span ring buffer, Chrome
                      trace export, analytic MFU, and the crash
                      flight recorder
 - :mod:`.numerics`   device-side numerics sentinels: in-graph
                      non-finite flags over loss/grads read at a
                      cadence, EWMA spike detectors, per-tensor stats,
                      ``pt_numerics_anomalies_total{kind}``
 - :mod:`.sdc`        silent-data-corruption sentry: in-graph replica
                      fingerprints (bit-pattern digests of updated
                      params + optimizer slots) voted on across dp
                      ranks — a minority rank is fingered as corrupt,
                      ``pt_sdc_divergence_total{rank}``
 - :mod:`.goodput`    wall-clock goodput ledger over the tracer's
                      spans: ``pt_goodput_fraction`` +
                      ``pt_badput_seconds{cause}``
 - :mod:`.memory`     device-memory accounting: compile-time
                      ``memory_analysis`` footprints + pre-flight fit
                      checks, ``jax.live_arrays()`` census attributed
                      to parameter paths, watermark timeline
                      (Chrome counter track), OOM postmortems
 - :mod:`.logs`       the library logger that bare ``print`` is banned
                      in favor of (lint rule TPU010)

Everything is inert until asked: importing this package creates no
threads, opens no files, and never initializes a jax backend; with
telemetry disabled (the default) every instrumentation hook in the hot
paths is a single attribute check.  Enable per process with::

    from paddle_tpu.observability import configure
    configure(enabled=True, jsonl_dir="/tmp/tele", http_port=9400)

or via environment: ``PT_TELEMETRY=1`` (+ ``PT_TELEMETRY_DIR``,
``PT_METRICS_PORT``, ``PT_RECOMPILE_THRESHOLD``, ``PT_LOG_LEVEL``).
"""
from __future__ import annotations

from .logs import get_logger
from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,
                      get_registry, reset_registry, log_buckets)
from .events import EventSink
from .telemetry import (TrainingTelemetry, StepTimer, CompileWatcher,
                        RecompileSentinel, get_telemetry, configure,
                        reset)
from .server import MetricsServer, start_http_server

# Aggregator exports resolve lazily: eagerly importing the submodule
# here would shadow `python -m paddle_tpu.observability.aggregator`
# (runpy warns when the module is already in sys.modules) and ranks
# that never aggregate shouldn't pay for the parser.
_AGGREGATOR_EXPORTS = ("ClusterAggregator", "MergeConflict",
                       "parse_prometheus_text", "merge_scrapes",
                       "render_exposition", "cluster_snapshot")

# Trace exports resolve lazily for the same runpy-shadowing reason —
# and because get_tracer() consults PT_TRACE/PT_FLIGHT_RECORDER, which
# plain `import paddle_tpu.observability` must never do.
_TRACE_EXPORTS = ("Tracer", "Span", "PHASES", "PEAK_FLOPS",
                  "peak_flops", "program_flops", "get_tracer",
                  "current_tracer", "reset_tracer")

# Numerics/goodput resolve lazily too: get_monitor()/get_goodput()
# consult PT_NUMERICS/PT_GOODPUT on first call, which a plain package
# import must never trigger.
_NUMERICS_EXPORTS = ("NumericsMonitor", "NumericsHaltError",
                     "health_outputs", "get_monitor", "current_monitor",
                     "reset_monitor")

_GOODPUT_EXPORTS = ("GoodputLedger", "decompose_spans", "get_goodput",
                    "current_ledger", "reset_goodput")

# SDC resolves lazily (get_monitor() consults PT_SDC on first call);
# only the names that don't collide with numerics' are re-exported —
# the monitor accessors live on paddle_tpu.observability.sdc itself.
_SDC_EXPORTS = ("SdcMonitor", "SdcHaltError", "fingerprint_outputs",
                "store_exchange")

# Memory resolves lazily for the same reason: get_memory_monitor()
# consults PT_MEMORY on first call, and the guarded allocator reads
# must stay importable without dragging in a jax backend.
_MEMORY_EXPORTS = ("MemoryMonitor", "device_memory_stats",
                   "device_memory_stat", "program_memory_analysis",
                   "is_oom_error", "oom_postmortem",
                   "get_memory_monitor", "current_memory_monitor",
                   "reset_memory_monitor")


def __getattr__(name):
    if name in _AGGREGATOR_EXPORTS:
        from . import aggregator
        return getattr(aggregator, name)
    if name in _TRACE_EXPORTS:
        from . import trace
        return getattr(trace, name)
    if name in _NUMERICS_EXPORTS:
        from . import numerics
        return getattr(numerics, name)
    if name in _GOODPUT_EXPORTS:
        from . import goodput
        return getattr(goodput, name)
    if name in _SDC_EXPORTS:
        from . import sdc
        return getattr(sdc, name)
    if name in _MEMORY_EXPORTS:
        from . import memory
        return getattr(memory, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "get_logger",
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "get_registry", "reset_registry", "log_buckets",
    "EventSink",
    "TrainingTelemetry", "StepTimer", "CompileWatcher",
    "RecompileSentinel", "get_telemetry", "configure", "reset",
    "MetricsServer", "start_http_server",
    "ClusterAggregator", "MergeConflict", "parse_prometheus_text",
    "merge_scrapes", "render_exposition", "cluster_snapshot",
    "Tracer", "Span", "PHASES", "PEAK_FLOPS", "peak_flops",
    "program_flops", "get_tracer", "current_tracer", "reset_tracer",
    "NumericsMonitor", "NumericsHaltError", "health_outputs",
    "get_monitor", "current_monitor", "reset_monitor",
    "GoodputLedger", "decompose_spans", "get_goodput",
    "current_ledger", "reset_goodput",
    "SdcMonitor", "SdcHaltError", "fingerprint_outputs",
    "store_exchange",
    "MemoryMonitor", "device_memory_stats", "device_memory_stat",
    "program_memory_analysis", "is_oom_error", "oom_postmortem",
    "get_memory_monitor", "current_memory_monitor",
    "reset_memory_monitor",
]
