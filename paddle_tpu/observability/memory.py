"""Device-memory accounting: compile-time footprints, live-buffer
attribution, watermark timelines, and OOM postmortems.

The failure mode this module exists for: a run dies at step 12k with a
raw ``RESOURCE_EXHAUSTED`` naming nothing — no record of which program
grew, which buffer owned the bytes, or how close to the limit the run
had been cruising. HBM was the last instrumentation blind spot (steps,
compiles, numerics and goodput are all observed; memory was three
scattered ``memory_stats()`` reads).

Four instruments, one monitor:

1. **Compile-time footprint** — ``jit/capture`` harvests each compiled
   program's ``memory_analysis()`` beside the FLOPs harvest and feeds
   :meth:`MemoryMonitor.record_program_memory`; the per-kind bytes are
   exported as ``pt_program_memory_bytes{program,kind}`` and a
   pre-flight **fit check** against ``memory_stats()["bytes_limit"]``
   warns once, naming the program and the shortfall, *before* the
   first replay can OOM.
2. **Live-buffer census** — :meth:`MemoryMonitor.live_buffer_census`
   walks ``jax.live_arrays()`` and attributes bytes to parameter paths
   (``param::model::1.weight`` — the same path naming the numerics
   sentinels trip on), capture-private donated buffers, optimizer
   state, or ``unattributed``, with a top-K table.
3. **Watermark timeline** — ``bytes_in_use`` / ``peak_bytes_in_use`` /
   fragmentation (``bytes_reserved − bytes_in_use``) sampled at step
   boundaries (:meth:`on_step`, fed from ``telemetry.observe_step``
   and the capture replay) into a bounded history, exported as
   ``pt_memory_watermark_bytes{stat}`` gauges and Chrome-trace counter
   events (``ph:"C"``) through the tracer, so ``observability.merge
   --trace`` stitches a per-rank memory track into the cluster
   timeline.
4. **OOM postmortem** — the capture replay and hapi ``Model`` steps
   intercept ``RESOURCE_EXHAUSTED``, call :func:`oom_postmortem`
   (census + per-program footprints + watermark history pinned into a
   flight-recorder dump, reason ``oom:<program>:<top buffer>``), then
   re-raise — mirroring the numerics non-finite trip path.

Contract (shared with the rest of ``observability``): zero cost while
disabled, never sync the device, never initialize a jax backend just
to read allocator stats, never raise into the run, side-effect-free
import. :func:`device_memory_stats` is the ONE guarded read every
other call site (telemetry gauges, ``device.cuda`` parity shims)
routes through.

Environment:
  - ``PT_MEMORY=1``       enable on first ``get_memory_monitor()``
  - ``PT_MEMORY_TOPK=n``  census table size (default 10)
"""
from __future__ import annotations

import logging
import os
import sys
import threading
import time
import weakref
from collections import deque

logger = logging.getLogger("paddle_tpu.observability.memory")

__all__ = [
    "MemoryMonitor",
    "device_memory_stats",
    "device_memory_stat",
    "program_memory_analysis",
    "is_oom_error",
    "oom_postmortem",
    "get_memory_monitor",
    "current_memory_monitor",
    "reset_memory_monitor",
]

# the per-program footprint kinds exported through
# pt_program_memory_bytes{program,kind}
KINDS = ("argument", "output", "temp", "generated_code")

# memory_analysis() attribute per kind ("alias" rides along so the fit
# check can credit donation: donated outputs reuse argument buffers)
_ANALYSIS_ATTRS = {
    "argument": "argument_size_in_bytes",
    "output": "output_size_in_bytes",
    "temp": "temp_size_in_bytes",
    "generated_code": "generated_code_size_in_bytes",
    "alias": "alias_size_in_bytes",
}

# allocator stats summed by device_memory_stats (bytes_reserved feeds
# the fragmentation series where the allocator reports it)
_STAT_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
              "bytes_reserved")

# substrings that identify an allocator-exhaustion failure across jax /
# jaxlib / XLA versions (string match: the concrete exception class
# moved between releases, the message text did not)
OOM_NEEDLES = (
    "RESOURCE_EXHAUSTED", "Resource exhausted", "out of memory",
    "Out of memory", "OOM", "Allocation failure",
    "exceeds the memory capacity", "exceeds available memory",
)


def _truthy(v):
    return str(v).lower() not in ("", "0", "false", "no", "off", "none")


# -- the one guarded allocator read ----------------------------------------

def device_memory_stats(per_device=False):
    """Allocator stats over local devices; ``{}`` (or ``[]``) when no
    jax backend exists yet — NEVER initializes one just to ask (same
    rule as ``trace._device_kind``). Default is one dict summed over
    devices; ``per_device=True`` returns a list of raw per-device
    dicts. Backends without allocator stats (cpu) contribute nothing.
    """
    xb = sys.modules.get("jax._src.xla_bridge")
    jax = sys.modules.get("jax")
    empty = [] if per_device else {}
    if jax is None or xb is None or not getattr(xb, "_backends", None):
        return empty
    try:
        devices = jax.local_devices()
    except Exception:
        return empty
    per = []
    out = {}
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        per.append(dict(stats))
        for k in _STAT_KEYS:
            if k in stats:
                out[k] = out.get(k, 0) + int(stats[k])
    return per if per_device else out


def device_memory_stat(which, device_index=0):
    """One allocator stat of one local device as an int (0 when the
    backend/stat is absent) — the ``device.cuda`` parity-shim read."""
    per = device_memory_stats(per_device=True)
    try:
        return int(per[device_index].get(which, 0))
    except (IndexError, AttributeError, TypeError, ValueError):
        return 0


# -- compile-time footprint -------------------------------------------------

def program_memory_analysis(jitted, *args, **kwargs):
    """Per-kind byte footprint of one jitted program from XLA's
    ``memory_analysis()`` (None when the backend can't say). Lowers +
    compiles AOT — call at compile time (the XLA compile is
    cache-shared with the first real call), never per step."""
    try:
        ma = jitted.lower(*args, **kwargs).compile().memory_analysis()
        if ma is None:
            return None
        if isinstance(ma, (list, tuple)):
            ma = ma[0] if ma else None
            if ma is None:
                return None
        out = {k: int(getattr(ma, attr, 0) or 0)
               for k, attr in _ANALYSIS_ATTRS.items()}
        return out if any(out.values()) else None
    except Exception:
        return None


def is_oom_error(exc):
    """True when an exception (or message string) is an allocator
    exhaustion — the intercept predicate for the postmortem path."""
    if exc is None:
        return False
    msg = exc if isinstance(exc, str) else \
        f"{type(exc).__name__}: {exc}"
    return any(n in msg for n in OOM_NEEDLES)


class MemoryMonitor:
    """Host-side device-memory accountant (see module docstring)."""

    def __init__(self, topk=10, history=512):
        self._lock = threading.RLock()
        self.enabled = False
        self.topk = int(topk)
        self.sample_every = 1
        self._metrics = None
        self._history = deque(maxlen=int(history))
        self._reset_state()

    def _reset_state(self):
        self._programs = {}        # name -> {kind: bytes}
        self._fit = {}             # name -> fit verdict dict
        self._fit_warned = set()
        self._providers = []       # weak/strong attribution callables
        self._steps = 0
        self._oom_events = 0
        self._last_oom = None
        self._history.clear()

    # -- lifecycle ---------------------------------------------------

    def enable(self, topk=None, sample_every=None):
        with self._lock:
            self.enabled = True
            if topk is not None:
                self.topk = max(1, int(topk))
            if sample_every is not None:
                self.sample_every = max(1, int(sample_every))
            self._make_metrics()
        return self

    def disable(self):
        with self._lock:
            self.enabled = False
        return self

    def _make_metrics(self):
        if self._metrics is not None:
            return
        try:
            from .metrics import get_registry
            r = get_registry()
            self._metrics = {
                "program": r.gauge(
                    "pt_program_memory_bytes",
                    "per-compiled-program byte footprint from XLA "
                    "memory_analysis, by kind", ("program", "kind")),
                "watermark": r.gauge(
                    "pt_memory_watermark_bytes",
                    "device allocator watermark sampled at step "
                    "boundaries", ("stat",)),
                "oom": r.counter(
                    "pt_oom_events_total",
                    "RESOURCE_EXHAUSTED failures intercepted by the "
                    "postmortem path"),
            }
        except Exception:  # metrics are optional plumbing
            self._metrics = None

    # -- compile-time footprint --------------------------------------

    def harvest_program(self, name, jitted, *args, **kwargs):
        """AOT-harvest one program's footprint and book it (compile
        time only). Returns the per-kind dict or None."""
        mem = program_memory_analysis(jitted, *args, **kwargs)
        if mem is not None:
            self.record_program_memory(name, mem)
        return mem

    def record_program_memory(self, name, mem):
        """Book one program's per-kind footprint (dict or a raw
        ``memory_analysis()`` object) and run the pre-flight fit
        check. Never raises."""
        try:
            if not isinstance(mem, dict):
                mem = {k: int(getattr(mem, attr, 0) or 0)
                       for k, attr in _ANALYSIS_ATTRS.items()}
            name = str(name)
            with self._lock:
                self._programs[name] = dict(mem)
                metrics = self._metrics if self.enabled else None
            if metrics is not None:
                for kind in KINDS:
                    metrics["program"].set(
                        int(mem.get(kind, 0)), program=name, kind=kind)
            self._fit_check(name, mem)
        except Exception:
            logger.debug("record_program_memory failed", exc_info=True)

    @staticmethod
    def required_bytes(mem):
        """Peak device bytes one program needs: arguments + outputs +
        temps + generated code, minus donation aliasing (aliased
        outputs reuse argument buffers)."""
        req = sum(int(mem.get(k, 0)) for k in KINDS)
        return max(req - int(mem.get("alias", 0)), 0)

    def _fit_check(self, name, mem):
        """Pre-flight verdict for one program against the device
        limit; warns ONCE per program when it cannot fit — before the
        first replay would discover it as a raw RESOURCE_EXHAUSTED."""
        limit = device_memory_stats().get("bytes_limit")
        required = self.required_bytes(mem)
        fits = None if not limit else required <= int(limit)
        verdict = {
            "fits": fits,
            "required_bytes": required,
            "limit_bytes": int(limit) if limit else None,
            "shortfall_bytes": (max(required - int(limit), 0)
                                if limit else None),
        }
        with self._lock:
            self._fit[name] = verdict
            warn = fits is False and name not in self._fit_warned
            if warn:
                self._fit_warned.add(name)
        if warn:
            logger.warning(
                "memory fit check: program %r needs %d bytes but the "
                "device limit is %d — short by %d bytes; the first "
                "replay will OOM unless buffers shrink (reduce batch/"
                "model size or shard the state)",
                name, required, verdict["limit_bytes"],
                verdict["shortfall_bytes"])
        return verdict

    # -- live-buffer census ------------------------------------------

    def register_provider(self, fn):
        """Register an attribution source: a callable returning
        ``{qualified_name: array}`` (names like
        ``param::model::1.weight``, ``opt0::velocity::...``,
        ``buffer::...``). Bound methods are held weakly so the census
        never keeps a training step alive."""
        try:
            ref = weakref.WeakMethod(fn)
        except TypeError:
            ref = None
        with self._lock:
            self._providers.append(ref if ref is not None else fn)

    def _named_arrays(self, extra=None):
        named = {}
        with self._lock:
            providers = list(self._providers)
        dead = []
        for p in providers:
            fn = p() if isinstance(p, weakref.WeakMethod) else p
            if fn is None:
                dead.append(p)
                continue
            try:
                named.update(fn() or {})
            except Exception:
                continue
        if dead:
            with self._lock:
                self._providers = [p for p in self._providers
                                   if p not in dead]
        if extra:
            named.update(extra)
        return named

    def live_buffer_census(self, extra_named=None, topk=None):
        """Walk ``jax.live_arrays()`` and attribute bytes.

        Attribution is by array identity against the registered
        providers (+ ``extra_named``): each qualified name's prefix
        (``param`` / ``buffer`` / ``opt*`` / ...) becomes its
        category; live arrays nobody claims are ``unattributed``.
        Returns ``{total_bytes, count, by_category, top}`` where
        ``top`` is the top-K table (name, bytes, shape, dtype).
        Host-side only: identity + nbytes, never a device sync."""
        k = int(topk or self.topk)
        out = {"total_bytes": 0, "count": 0, "by_category": {},
               "top": []}
        jax = sys.modules.get("jax")
        if jax is None:
            return out
        try:
            live = jax.live_arrays()
        except Exception:
            return out
        named = self._named_arrays(extra_named)
        by_id = {}
        for name, arr in named.items():
            try:
                by_id[id(arr)] = name
            except Exception:
                continue
        rows = []
        for arr in live:
            try:
                nbytes = int(arr.nbytes)
                shape = tuple(arr.shape)
                dtype = str(arr.dtype)
            except Exception:
                continue
            name = by_id.get(id(arr), "unattributed")
            cat = name.split("::", 1)[0] if name != "unattributed" \
                else "unattributed"
            out["total_bytes"] += nbytes
            out["count"] += 1
            out["by_category"][cat] = \
                out["by_category"].get(cat, 0) + nbytes
            rows.append((nbytes, name, shape, dtype))
        rows.sort(key=lambda r: (-r[0], r[1]))
        out["top"] = [
            {"name": n, "bytes": b, "shape": list(s), "dtype": d}
            for b, n, s, d in rows[:k]]
        return out

    # -- watermark timeline ------------------------------------------

    def on_step(self, step=None):
        """Step-boundary hook (telemetry.observe_step / capture
        replay): samples the allocator watermark at the configured
        cadence. Plain host reads, never a device sync."""
        if not self.enabled:
            return
        with self._lock:
            self._steps += 1
            due = self._steps % self.sample_every == 0
        if due:
            self.sample_watermark()

    def sample_watermark(self):
        """Read the allocator once and book the sample (no-op when no
        backend / no allocator stats — cpu)."""
        stats = device_memory_stats()
        if stats:
            self.observe_sample(stats)

    def observe_sample(self, stats, t_ns=None):
        """Book one watermark sample. Public so drills/tests (and
        backends without allocator stats) can inject synthetic
        readings through the same pipeline: history + gauges + a
        Chrome-trace counter event per rank."""
        try:
            in_use = int(stats.get("bytes_in_use", 0))
            peak = int(stats.get("peak_bytes_in_use", 0))
            reserved = stats.get("bytes_reserved")
            frag = (max(int(reserved) - in_use, 0)
                    if reserved is not None else 0)
            if t_ns is None:
                t_ns = time.perf_counter_ns()
            sample = {"t_ns": int(t_ns), "bytes_in_use": in_use,
                      "peak_bytes_in_use": peak,
                      "fragmentation_bytes": frag}
            with self._lock:
                self._history.append(sample)
                metrics = self._metrics if self.enabled else None
            if metrics is not None:
                g = metrics["watermark"]
                g.set(in_use, stat="bytes_in_use")
                g.set(peak, stat="peak_bytes_in_use")
                g.set(frag, stat="fragmentation")
            tr_mod = sys.modules.get("paddle_tpu.observability.trace")
            if tr_mod is not None:
                tr = tr_mod.current_tracer()
                if tr is not None and tr.enabled:
                    tr.record_counter(
                        "device_memory", t_ns,
                        {"bytes_in_use": in_use,
                         "peak_bytes_in_use": peak,
                         "fragmentation": frag})
        except Exception:
            logger.debug("watermark sample failed", exc_info=True)

    def watermarks(self):
        """Snapshot of the watermark history (oldest first)."""
        with self._lock:
            return [dict(s) for s in self._history]

    # -- OOM postmortem ----------------------------------------------

    def record_oom(self, program=None, exc=None, extra_named=None):
        """Book one allocator-exhaustion failure: census + per-program
        footprints + watermark history, pinned into a flight-recorder
        dump (reason ``oom:<program>:<top buffer>``). Runs even while
        disabled — an OOM is terminal, the cost argument is over.
        Never raises; the caller re-raises the original error."""
        try:
            census = self.live_buffer_census(extra_named=extra_named)
            top = census["top"][0]["name"] if census["top"] \
                else "unattributed"
            with self._lock:
                self._oom_events += 1
                doc = {
                    "program": str(program) if program else None,
                    "error": (f"{type(exc).__name__}: {str(exc)[:500]}"
                              if exc is not None else None),
                    "top_buffer": top,
                    "census": census,
                    "programs": {n: dict(m)
                                 for n, m in self._programs.items()},
                    "fit": {n: dict(v) for n, v in self._fit.items()},
                    "watermarks": [dict(s) for s in self._history],
                }
                self._last_oom = doc
                metrics = self._metrics
            if metrics is not None:
                try:
                    metrics["oom"].inc()
                except Exception:
                    pass
            logger.error(
                "OOM postmortem: program=%s top_buffer=%s "
                "live_bytes=%d across %d arrays",
                doc["program"], top, census["total_bytes"],
                census["count"])
            reason = "oom:%s:%s" % (doc["program"] or "", top)
            tr_mod = sys.modules.get("paddle_tpu.observability.trace")
            if tr_mod is not None:
                try:
                    tr = tr_mod.current_tracer()
                    if tr is not None and tr.enabled:
                        tr.flight_dump(reason=reason,
                                       extra={"memory": doc})
                except Exception:
                    pass
            return doc
        except Exception:
            logger.debug("oom postmortem failed", exc_info=True)
            return None

    # -- reporting ---------------------------------------------------

    def snapshot(self):
        """Compact JSON-ready summary (attached to bench records and
        the telemetry snapshot)."""
        stats = device_memory_stats()
        with self._lock:
            last = self._history[-1] if self._history else None
            fit = {n: dict(v) for n, v in self._fit.items()}
            programs = {n: dict(m) for n, m in self._programs.items()}
            oom_events = self._oom_events
            last_oom = self._last_oom
        verdicts = [v["fits"] for v in fit.values()]
        fit_ok = (False if any(v is False for v in verdicts)
                  else True if verdicts
                  and all(v is True for v in verdicts) else None)
        return {
            "enabled": self.enabled,
            "topk": self.topk,
            "bytes_in_use": stats.get(
                "bytes_in_use",
                last["bytes_in_use"] if last else None),
            "peak_bytes_in_use": stats.get(
                "peak_bytes_in_use",
                last["peak_bytes_in_use"] if last else None),
            "bytes_limit": stats.get("bytes_limit"),
            "fragmentation_bytes": (
                last["fragmentation_bytes"] if last else
                (max(stats.get("bytes_reserved", 0)
                     - stats.get("bytes_in_use", 0), 0)
                 if "bytes_reserved" in stats else None)),
            "fit_ok": fit_ok,
            "fit": fit,
            "programs": programs,
            "samples": len(self._history),
            "oom_events": oom_events,
            "last_oom": ({"program": last_oom["program"],
                          "top_buffer": last_oom["top_buffer"],
                          "error": last_oom["error"]}
                         if last_oom else None),
        }


# -- module-level postmortem entry point ------------------------------------

def oom_postmortem(program=None, exc=None, extra_named=None):
    """Book an OOM through the singleton (created if needed — the
    error path is cold and terminal, env laziness no longer matters).
    Never raises; callers re-raise the original exception."""
    try:
        return get_memory_monitor().record_oom(
            program=program, exc=exc, extra_named=extra_named)
    except Exception:
        return None


# -- process singleton ------------------------------------------------------

_monitor = None
_monitor_lock = threading.Lock()


def get_memory_monitor():
    """Process singleton; first call applies PT_MEMORY_* env config."""
    global _monitor
    with _monitor_lock:
        if _monitor is None:
            _monitor = MemoryMonitor()
            if _truthy(os.environ.get("PT_MEMORY", "")):
                _monitor.enable(
                    topk=os.environ.get("PT_MEMORY_TOPK") or None)
        return _monitor


def current_memory_monitor():
    """The singleton if it exists, else None — read-only accessor that
    never triggers env-based enablement (hot paths use this)."""
    return _monitor


def reset_memory_monitor():
    """Drop the singleton (tests)."""
    global _monitor
    with _monitor_lock:
        _monitor = None
