"""Library logging entry point.

Every ``paddle_tpu`` module that wants to talk to a human goes through
``get_logger`` instead of ``print`` (enforced by lint rule TPU010):
stdlib logging can be rate-limited, filtered per subsystem, and
collected per process, none of which a bare ``print`` allows.

Import-time contract (shared by the whole observability package): this
module configures NOTHING — no handlers, no levels, no files.  The
hosting application owns the logging tree; we only namespace under
``paddle_tpu``.  ``PT_LOG_LEVEL`` is applied lazily on the first
``get_logger`` call so a bare script still gets output when it asks
for it, without us touching the root logger.
"""
from __future__ import annotations

import logging
import os

__all__ = ["get_logger", "ROOT_LOGGER_NAME"]

ROOT_LOGGER_NAME = "paddle_tpu"

_level_applied = False


def _apply_env_level():
    global _level_applied
    if _level_applied:
        return
    _level_applied = True
    level = os.environ.get("PT_LOG_LEVEL", "").strip().upper()
    if not level:
        return
    root = logging.getLogger(ROOT_LOGGER_NAME)
    try:
        root.setLevel(level)
    except ValueError:
        return
    # only attach our own handler when nothing upstream would show the
    # records anyway — never stomp on an app-configured logging tree
    if not root.handlers and not logging.getLogger().handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s: %(message)s"))
        root.addHandler(h)


def get_logger(name: str | None = None) -> logging.Logger:
    """A logger under the ``paddle_tpu`` namespace.

    ``name`` may be a module's ``__name__`` (kept as-is when it already
    lives under the namespace) or a short suffix.
    """
    _apply_env_level()
    if not name:
        return logging.getLogger(ROOT_LOGGER_NAME)
    if name == ROOT_LOGGER_NAME or name.startswith(ROOT_LOGGER_NAME + "."):
        return logging.getLogger(name)
    return logging.getLogger(ROOT_LOGGER_NAME + "." + name)
