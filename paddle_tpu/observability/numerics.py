"""Device-side numerics health sentinels.

The failure mode this module exists for: an AMP run diverges at step
40k and the only artifact is a loss curve that went to NaN — nobody can
say *which tensor* went non-finite first, and by the time a human adds
``print(float(loss))`` probes the run is gone (and the probes add a
host sync per step, which is its own regression — tpu-lint TPU017
flags exactly that spelling).

Instead the monitor folds a tiny health program *inside* the jitted /
captured step — per-tensor ``isfinite`` flags over loss and every
gradient, a global squared grad-norm, and (opt-in) per-tensor
statistics — and reads the resulting scalar outputs on the host
**asynchronously at a cadence**: at every ``PT_NUMERICS_CADENCE``-th
step the packet from the *previous* step is materialized, by which
point the device finished it long ago, so steady-state steps never
gain a host sync. On a trip the offending tensor is named by parameter
path, ``pt_numerics_anomalies_total{kind}`` is bumped, the flight
recorder dumps (reason ``numerics:<kind>:<tensor>``), and with
``PT_NUMERICS_HALT=1`` the step raises :class:`NumericsHaltError` so
the train loop can stop burning accelerator hours on NaN.

Contract (shared with the rest of ``observability``): zero cost while
disabled, never sync the device on the hot path, never take down the
run unless halting was explicitly requested, side-effect-free import.

Environment:
  - ``PT_NUMERICS=1``          enable on first ``get_monitor()``
  - ``PT_NUMERICS_CADENCE=n``  host read cadence in steps (default 16)
  - ``PT_NUMERICS_STATS=1``    opt-in per-tensor mean/std/max-abs/
                               underflow-fraction sampling
  - ``PT_NUMERICS_HALT=1``     raise ``NumericsHaltError`` on a
                               non-finite trip
"""
from __future__ import annotations

import logging
import math
import os
import sys
import threading

logger = logging.getLogger("paddle_tpu.observability.numerics")

__all__ = [
    "NumericsMonitor",
    "NumericsHaltError",
    "health_outputs",
    "get_monitor",
    "current_monitor",
    "reset_monitor",
]

# kinds emitted through pt_numerics_anomalies_total{kind}
KINDS = ("nonfinite", "loss_spike", "grad_explosion", "scaler_skip")

# |x| below the smallest f32/bf16 normal (2**-126) but not exactly zero
# counts as underflowed: in bf16 those values flush to zero and the
# underflow fraction is the early-warning signal for vanishing grads.
_TINY_NORMAL = 2.0 ** -126


class NumericsHaltError(RuntimeError):
    """Raised from a monitored step when PT_NUMERICS_HALT=1 and a
    non-finite loss/grad tripped the sentinel."""


def health_outputs(named, loss=None, with_stats=False, norm_over=None):
    """Build the device-side health program over a dict of named arrays.

    Called at *trace time* from inside a jitted step (capture's
    ``pure`` or hapi's ``train_step``); the returned arrays become
    extra program outputs, so the health check compiles into the same
    executable — no second program, no extra compile.

    Returns ``(names, health)`` where ``names`` is the host-side tuple
    naming each row of ``health["flags"]`` (sorted parameter paths,
    plus ``"loss"`` last when a loss is given) and ``health`` is a dict
    of small device arrays:

      - ``flags``:        bool[n] — per-tensor any-non-finite
      - ``grad_norm_sq``: f32 scalar — global squared norm, over
                          ``norm_over`` when given, else over ``named``
      - ``loss``:         f32 scalar (only when ``loss`` is given)
      - ``stats``:        f32[n, 4] — mean, std, max-abs, underflow
                          fraction per tensor (only ``with_stats``)

    Each per-tensor flag is derived from the tensor's squared sum —
    any NaN/Inf propagates through ``sum(x*x)`` — so the health
    program costs ONE reduction per tensor, shared with the norm,
    instead of a separate ``isfinite`` sweep (the reduction count, not
    the element pass, is what shows up as per-step overhead). The one
    false-positive mode is f32 overflow of the squared sum, i.e.
    magnitudes past ~1e19 — firing on those is the sentinel doing its
    job.

    ``norm_over`` exists so a caller can flag one set of tensors while
    taking the norm of another: capture flags the UPDATED parameters —
    already-materialized program outputs, so their reductions extend no
    intermediate lifetimes — while the EWMA explosion detector still
    watches the squared norm of the raw gradients.
    """
    import jax.numpy as jnp

    names = tuple(sorted(named))
    flags = []
    stats = []
    norm_sq = jnp.zeros((), jnp.float32)
    for name in names:
        x = named[name]
        if not jnp.issubdtype(x.dtype, jnp.inexact):
            # integer/bool tensors are finite by construction
            flags.append(jnp.zeros((), jnp.bool_))
            if with_stats:
                stats.append(jnp.zeros((4,), jnp.float32))
            continue
        xf = x.astype(jnp.float32)
        sq = jnp.sum(xf * xf)
        flags.append(~jnp.isfinite(sq))
        if norm_over is None:
            norm_sq = norm_sq + sq
        if with_stats:
            ax = jnp.abs(xf)
            under = jnp.mean(
                ((ax > 0) & (ax < _TINY_NORMAL)).astype(jnp.float32))
            stats.append(jnp.stack(
                [jnp.mean(xf), jnp.std(xf), jnp.max(ax), under]))
    if norm_over is not None:
        for x in norm_over.values():
            if jnp.issubdtype(x.dtype, jnp.inexact):
                xf = x.astype(jnp.float32)
                norm_sq = norm_sq + jnp.sum(xf * xf)
    loss_f = None
    if loss is not None:
        loss_f = jnp.mean(jnp.asarray(loss).astype(jnp.float32))
        names = names + ("loss",)
        flags.append(~jnp.isfinite(loss_f))
        if with_stats:
            stats.append(jnp.stack(
                [loss_f, jnp.zeros(()), jnp.abs(loss_f), jnp.zeros(())]))
    health = {
        "flags": (jnp.stack(flags) if flags
                  else jnp.zeros((0,), jnp.bool_)),
        "grad_norm_sq": norm_sq,
    }
    if loss_f is not None:
        health["loss"] = loss_f
    if with_stats:
        health["stats"] = (jnp.stack(stats) if stats
                           else jnp.zeros((0, 4), jnp.float32))
    return names, health


class NumericsMonitor:
    """Host-side half of the sentinel: holds the latest health packet,
    materializes the previous one at cadence boundaries, runs the
    detectors, and books anomalies."""

    def __init__(self):
        self._lock = threading.RLock()
        self.enabled = False
        self.cadence = 16
        self.stats_on = False
        self.halt = False
        self.ewma_alpha = 0.9
        self.spike_factor = 10.0
        self.warmup_reads = 3
        self._metrics = None
        self._reset_state()

    def _reset_state(self):
        # host counters work even while disabled (the scaler-skip path
        # books through here unconditionally); metrics only if enabled
        self._anomalies = {}
        self._last_anomaly = None
        self._pending = None          # (step, names, health) latest packet
        self._last_read_step = None
        self._steps_observed = 0
        self._reads = 0
        self._loss_ewma = None
        self._gnorm_ewma = None
        self._finite_reads = 0
        self._last_loss = None
        self._last_grad_norm = None
        self._last_stats = None       # {tensor: {mean, std, max_abs, ...}}
        self._tripped = set()         # tensor paths already reported

    # -- lifecycle ---------------------------------------------------

    def enable(self, cadence=None, stats=None, halt=None,
               ewma_alpha=None, spike_factor=None):
        with self._lock:
            self.enabled = True
            if cadence is not None:
                self.cadence = max(1, int(cadence))
            if stats is not None:
                self.stats_on = bool(stats)
            if halt is not None:
                self.halt = bool(halt)
            if ewma_alpha is not None:
                self.ewma_alpha = float(ewma_alpha)
            if spike_factor is not None:
                self.spike_factor = float(spike_factor)
            self._make_metrics()
        return self

    def disable(self):
        with self._lock:
            self.enabled = False
        return self

    def _make_metrics(self):
        if self._metrics is not None:
            return
        try:
            from .metrics import get_registry
            r = get_registry()
            self._metrics = {
                "anomalies": r.counter(
                    "pt_numerics_anomalies_total",
                    "Numerics anomalies tripped, by kind",
                    ("kind",)),
                "grad_norm": r.gauge(
                    "pt_numerics_grad_norm",
                    "Last grad norm read by the numerics monitor"),
            }
        except Exception:  # metrics are optional plumbing
            self._metrics = None

    # -- hot path ----------------------------------------------------

    def watch(self, step, names, health):
        """Per-step hook from the captured/jitted step's replay path.

        Holds a reference to the (tiny) health arrays; at every
        cadence boundary the packet from the *previous* step is
        inspected — one full step of dispatch separates enqueue from
        read, so ``np.asarray`` finds the buffers already materialized
        and the read never blocks the step. Detection latency is at
        most one cadence window.
        """
        if not self.enabled:
            return
        with self._lock:
            prev = self._pending
            self._pending = (int(step), names, health)
            self._steps_observed += 1
            due = (prev is not None
                   and (self._last_read_step is None
                        or prev[0] - self._last_read_step >= self.cadence))
        if due:
            self._inspect(*prev)

    def flush(self):
        """Materialize and inspect the held packet now (end of run,
        drills, tests). The one place a blocking read is acceptable."""
        with self._lock:
            pending, self._pending = self._pending, None
        if pending is not None:
            self._inspect(*pending)
        return self

    # -- detectors ---------------------------------------------------

    def _inspect(self, step, names, health):
        import numpy as np

        try:
            flags = np.asarray(health["flags"])
            norm_sq = float(np.asarray(health["grad_norm_sq"]))
            loss = (float(np.asarray(health["loss"]))
                    if "loss" in health else None)
            stats = (np.asarray(health["stats"])
                     if "stats" in health else None)
        except Exception:
            # a failed read must never take down the run
            logger.debug("numerics read failed", exc_info=True)
            return
        with self._lock:
            self._last_read_step = step
            self._reads += 1
        bad = [names[i] for i in range(len(flags)) if bool(flags[i])]
        for tensor in bad:
            if tensor in self._tripped:
                continue
            self._tripped.add(tensor)
            self.record_anomaly(
                "nonfinite", tensor=tensor, step=step,
                detail="non-finite values detected")
        if stats is not None and len(names) == len(stats):
            self._last_stats = {
                names[i]: {
                    "mean": float(stats[i][0]),
                    "std": float(stats[i][1]),
                    "max_abs": float(stats[i][2]),
                    "underflow_frac": float(stats[i][3]),
                }
                for i in range(len(names))
            }
        if bad:
            return  # EWMA baselines stay clean of non-finite reads
        grad_norm = math.sqrt(norm_sq) if norm_sq >= 0 else float("nan")
        with self._lock:
            self._last_loss = loss
            self._last_grad_norm = grad_norm
            self._finite_reads += 1
            warm = self._finite_reads > self.warmup_reads
            loss_spike = (
                loss is not None and warm and self._loss_ewma is not None
                and abs(loss) > self.spike_factor
                * max(abs(self._loss_ewma), 1e-8))
            grad_spike = (
                math.isfinite(grad_norm) and warm
                and self._gnorm_ewma is not None
                and grad_norm > self.spike_factor
                * max(self._gnorm_ewma, 1e-8))
            a = self.ewma_alpha
            if loss is not None and not loss_spike:
                self._loss_ewma = (loss if self._loss_ewma is None
                                   else a * self._loss_ewma + (1 - a) * loss)
            if math.isfinite(grad_norm) and not grad_spike:
                self._gnorm_ewma = (
                    grad_norm if self._gnorm_ewma is None
                    else a * self._gnorm_ewma + (1 - a) * grad_norm)
            if self._metrics is not None:
                try:
                    self._metrics["grad_norm"].set(grad_norm)
                except Exception:
                    pass
        if loss_spike:
            self.record_anomaly(
                "loss_spike", tensor="loss", step=step,
                detail="loss=%.6g ewma=%.6g" % (loss, self._loss_ewma),
                halt_ok=False)
        if grad_spike:
            self.record_anomaly(
                "grad_explosion", tensor="grad_norm", step=step,
                detail="norm=%.6g ewma=%.6g" % (grad_norm,
                                                self._gnorm_ewma),
                halt_ok=False)

    # -- anomaly sink ------------------------------------------------

    def record_anomaly(self, kind, tensor=None, step=None, detail=None,
                       halt_ok=True):
        """Book one anomaly: host counter (always), metric counter
        (when enabled), a warning naming the tensor, a flight-recorder
        dump, and — for hard non-finite trips with halting armed — a
        :class:`NumericsHaltError`."""
        with self._lock:
            self._anomalies[kind] = self._anomalies.get(kind, 0) + 1
            self._last_anomaly = {
                "kind": kind, "tensor": tensor, "step": step,
                "detail": detail,
            }
            metrics = self._metrics if self.enabled else None
        if metrics is not None:
            try:
                metrics["anomalies"].inc(kind=kind)
            except Exception:
                pass
        logger.warning("numerics anomaly: kind=%s tensor=%s step=%s %s",
                       kind, tensor, step, detail or "")
        # the flight dump pins the FIRST non-finite trip: one bad step
        # usually flags several tensors at once (params before the
        # aggregate "loss" in inspection order), and the most specific
        # name — the first parameter path — is the one worth debugging
        reason = "numerics:%s:%s" % (kind, tensor or "")
        dump = kind != "nonfinite" or self._anomalies[kind] == 1
        tr_mod = (sys.modules.get("paddle_tpu.observability.trace")
                  if dump else None)
        if tr_mod is not None:
            try:
                tr = tr_mod.current_tracer()
                if tr is not None and tr.enabled:
                    tr.flight_dump(reason=reason)
            except Exception:
                pass
        if self.halt and halt_ok and kind == "nonfinite":
            raise NumericsHaltError(
                "numerics sentinel tripped: %s in %r at step %s "
                "(PT_NUMERICS_HALT=1)" % (kind, tensor, step))

    # -- reporting ---------------------------------------------------

    def anomaly_count(self, kind=None):
        with self._lock:
            if kind is not None:
                return self._anomalies.get(kind, 0)
            return sum(self._anomalies.values())

    def snapshot(self):
        with self._lock:
            snap = {
                "enabled": self.enabled,
                "cadence": self.cadence,
                "stats": self.stats_on,
                "halt": self.halt,
                "steps_observed": self._steps_observed,
                "reads": self._reads,
                "anomalies": dict(self._anomalies),
                "anomalies_total": sum(self._anomalies.values()),
                "tripped": sorted(self._tripped),
                "last_anomaly": (dict(self._last_anomaly)
                                 if self._last_anomaly else None),
                "loss_ewma": self._loss_ewma,
                "grad_norm_ewma": self._gnorm_ewma,
                "last_loss": self._last_loss,
                "last_grad_norm": self._last_grad_norm,
            }
            if self._last_stats is not None:
                snap["tensor_stats"] = {
                    k: dict(v) for k, v in self._last_stats.items()}
            return snap


_monitor = None
_monitor_lock = threading.Lock()


def _truthy(v):
    return str(v).lower() not in ("", "0", "false", "no", "off", "none")


def get_monitor():
    """Process singleton; first call applies PT_NUMERICS_* env config."""
    global _monitor
    with _monitor_lock:
        if _monitor is None:
            _monitor = NumericsMonitor()
            if _truthy(os.environ.get("PT_NUMERICS", "")):
                _monitor.enable(
                    cadence=os.environ.get("PT_NUMERICS_CADENCE") or None,
                    stats=_truthy(os.environ.get("PT_NUMERICS_STATS", "")),
                    halt=_truthy(os.environ.get("PT_NUMERICS_HALT", "")),
                )
        return _monitor


def current_monitor():
    """The singleton if it exists, else None — read-only accessor that
    never triggers env-based enablement (hot paths use this)."""
    return _monitor


def reset_monitor():
    """Drop the singleton (tests)."""
    global _monitor
    with _monitor_lock:
        _monitor = None
