"""JSONL event sink: append-only structured telemetry stream.

One file per process (``<prefix>-<pid>.jsonl``) so concurrent hosts or
data workers never interleave half-lines; size-rotated by renaming the
current file to ``.1`` (single generation — the aggregation story is
"ship/merge per-process files", see ROADMAP multi-host drills).  Each
record is one JSON object with an ISO-8601 UTC timestamp:

    {"ts": "2026-08-05T12:00:00.123+00:00", "pid": 4242,
     "event": "step", "step": 17, "duration_sec": 0.0123, ...}

Lazy by construction: the directory and file are only created on the
first ``emit`` — constructing a sink does no I/O, so telemetry setup
stays import/enable-safe.  A failing write never raises into the
training loop; it is counted in ``dropped`` and retried on the next
emit.
"""
from __future__ import annotations

import json
import os
import threading
from datetime import datetime, timezone

__all__ = ["EventSink"]

DEFAULT_MAX_BYTES = 32 << 20


class EventSink:
    def __init__(self, directory, prefix="telemetry",
                 max_bytes=DEFAULT_MAX_BYTES):
        self.directory = directory
        self.prefix = prefix
        self.max_bytes = int(max_bytes)
        self.dropped = 0
        self._lock = threading.Lock()
        self._fh = None
        self._size = 0

    @property
    def path(self):
        return os.path.join(self.directory,
                            f"{self.prefix}-{os.getpid()}.jsonl")

    def _open(self):
        os.makedirs(self.directory, exist_ok=True)
        self._fh = open(self.path, "a", encoding="utf-8")
        self._size = self._fh.tell()

    def _rotate(self):
        self._fh.close()
        self._fh = None
        os.replace(self.path, self.path + ".1")
        self._open()

    def emit(self, event, **fields):
        """Append one record. Returns True if it reached the file."""
        rec = {"ts": datetime.now(timezone.utc).isoformat(
                   timespec="milliseconds"),
               "pid": os.getpid(), "event": event}
        rec.update(fields)
        line = json.dumps(rec, default=str) + "\n"
        with self._lock:
            try:
                if self._fh is None:
                    self._open()
                elif self._size + len(line) > self.max_bytes:
                    self._rotate()
                self._fh.write(line)
                self._fh.flush()
                self._size += len(line)
                return True
            except (OSError, ValueError):
                # telemetry must never take down the run it watches
                # (ValueError: write to a file closed under us, e.g.
                # interpreter shutdown or a fork closing descriptors)
                self.dropped += 1
                self._fh = None
                return False

    def close(self):
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None
