"""JSONL event sink: append-only structured telemetry stream.

One file per process so concurrent hosts or data workers never
interleave half-lines; size-rotated by renaming the current file to
``.1`` (single generation — cross-process aggregation is
``python -m paddle_tpu.observability.merge`` over the per-process
files).  With a cluster identity (``run_id`` + ``process_index``,
resolved by :mod:`.telemetry` from ``PT_RUN_ID`` /
``PT_PROCESS_INDEX`` / ``PADDLE_TRAINER_ID``) the file is
``<prefix>-<run_id>-<rank>.jsonl`` — pids are NOT stable across
elastic restarts, so the rank-keyed name is what survives a relaunch;
without one it stays the legacy ``<prefix>-<pid>.jsonl``, which the
merge CLI still reads.  Each record is one JSON object with an
ISO-8601 UTC timestamp:

    {"ts": "2026-08-05T12:00:00.123+00:00", "pid": 4242,
     "run_id": "r7", "process_index": 1,
     "event": "step", "step": 17, "duration_sec": 0.0123, ...}

Lazy by construction: the directory and file are only created on the
first ``emit`` — constructing a sink does no I/O, so telemetry setup
stays import/enable-safe.  A failing write never raises into the
training loop; it is counted in ``dropped`` and retried on the next
emit.
"""
from __future__ import annotations

import json
import os
import re
import threading
from datetime import datetime, timezone

__all__ = ["EventSink"]

DEFAULT_MAX_BYTES = 32 << 20

# run_id appears in the filename; keep it shell/fs-safe there (records
# carry the raw value)
_UNSAFE = re.compile(r"[^A-Za-z0-9._-]")


class EventSink:
    def __init__(self, directory, prefix="telemetry",
                 max_bytes=DEFAULT_MAX_BYTES, run_id=None,
                 process_index=None):
        self.directory = directory
        self.prefix = prefix
        self.max_bytes = int(max_bytes)
        self.run_id = run_id
        self.process_index = (int(process_index)
                              if process_index is not None else None)
        self.dropped = 0
        self._lock = threading.Lock()
        self._fh = None
        self._size = 0

    @property
    def path(self):
        if self.run_id is not None and self.process_index is not None:
            rid = _UNSAFE.sub("_", str(self.run_id))
            return os.path.join(
                self.directory,
                f"{self.prefix}-{rid}-{self.process_index}.jsonl")
        return os.path.join(self.directory,
                            f"{self.prefix}-{os.getpid()}.jsonl")

    def _open(self):
        os.makedirs(self.directory, exist_ok=True)
        self._fh = open(self.path, "a", encoding="utf-8")
        self._size = self._fh.tell()

    def _rotate(self):
        self._fh.close()
        self._fh = None
        os.replace(self.path, self.path + ".1")
        self._open()

    def emit(self, event, **fields):
        """Append one record. Returns True if it reached the file."""
        rec = {"ts": datetime.now(timezone.utc).isoformat(
                   timespec="milliseconds"),
               "pid": os.getpid(), "event": event}
        if self.run_id is not None:
            rec["run_id"] = self.run_id
        if self.process_index is not None:
            rec["process_index"] = self.process_index
        rec.update(fields)
        line = json.dumps(rec, default=str) + "\n"
        with self._lock:
            try:
                if self._fh is None:
                    self._open()
                elif self._size + len(line) > self.max_bytes:
                    self._rotate()
                self._fh.write(line)
                self._fh.flush()
                self._size += len(line)
                return True
            except (OSError, ValueError):
                # telemetry must never take down the run it watches
                # (ValueError: write to a file closed under us, e.g.
                # interpreter shutdown or a fork closing descriptors)
                self.dropped += 1
                self._fh = None
                return False

    def close(self):
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None
