"""Sharded train-step builder: the hybrid-parallel fast path.

This is the TPU-native replacement for the reference's entire hybrid
training machinery (SURVEY §3.3): where the reference composes
Fleet + HybridCommunicateGroup + PipelineParallel.train_batch +
EagerReducer + HybridParallelOptimizer at runtime, here ONE function
builds ONE jitted XLA program:

 - parameters/optimizer state placed by their ``PartitionSpec``
   annotations (mp from the TP layers, sharding from fsdp annotation)
 - batch sharded over dp (× sep for long sequences)
 - gradient psums over dp/sharding, TP collectives over mp, all compiled
   and overlapped by XLA over ICI

Used by fleet users, ``__graft_entry__.dryrun_multichip`` and the bench.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..tensor import Tensor
from ..nn.layer.layers import Layer
from ..jit.api import functional_call
from ..framework import random as _random
from . import mesh as _mesh_mod

__all__ = ["param_shardings", "shard_model_state", "build_train_step"]


def _spec_for(p, mesh):
    spec = getattr(p, "_spec", None)
    if spec is None:
        return P()
    # drop axis names the mesh doesn't have (e.g. model built with TP
    # annotations but run on a dp-only mesh)
    axes = []
    for entry in spec:
        if entry is None:
            axes.append(None)
        elif isinstance(entry, tuple):
            kept = tuple(a for a in entry if a in mesh.shape
                         and mesh.shape[a] > 1)
            axes.append(kept if kept else None)
        else:
            axes.append(entry if entry in mesh.shape and
                        mesh.shape[entry] > 1 else None)
    # verify divisibility; fall back to replicated otherwise
    for d, a in enumerate(axes):
        names = (a,) if isinstance(a, str) else (a or ())
        size = int(np.prod([mesh.shape[n] for n in names])) if names else 1
        if size > 1 and p.shape[d] % size:
            return P()
    return P(*axes)


def param_shardings(layer: Layer, mesh=None):
    """{name: NamedSharding} honoring per-parameter specs."""
    mesh = mesh or _mesh_mod.get_mesh()
    return {k: NamedSharding(mesh, _spec_for(p, mesh))
            for k, p in layer.named_parameters()}


def shard_model_state(layer: Layer, mesh=None):
    """Extract + place (params, buffers) arrays onto the mesh."""
    mesh = mesh or _mesh_mod.get_mesh()
    shardings = param_shardings(layer, mesh)
    # copy via jnp.copy: the step donates its state buffers, and the layer
    # must keep owning its original (undonated) arrays
    params = {k: jax.device_put(jnp.copy(p._data), shardings[k])
              for k, p in layer.named_parameters()}
    repl = NamedSharding(mesh, P())
    buffers = {k: jax.device_put(jnp.copy(b._data), repl)
               for k, b in layer.named_buffers()}
    return params, buffers, shardings


def build_train_step(model: Layer, loss_fn, optimizer, mesh=None,
                     donate=True):
    """Returns (step_fn, state) where
    ``state = {"params", "buffers", "opt"}`` is mesh-placed and
    ``step_fn(state, *batch) -> (loss, state)`` is one compiled program.

    ``loss_fn(outputs, *labels) -> scalar Tensor-or-array``.
    The batch's leading axis is sharded over ``dp`` (and the second axis
    over ``sep`` when that axis is >1, for sequence parallelism).
    """
    mesh = mesh or _mesh_mod.get_mesh()
    params, buffers, shardings = shard_model_state(model, mesh)
    opt_state = optimizer.init_state_tree(params)
    # optimizer slots/master inherit each param's sharding (the ZeRO win:
    # an fsdp-annotated param stores adam moments sharded the same way)
    slots_sh = {s: {k: shardings[k] for k in opt_state["slots"][s]}
                for s in opt_state["slots"]}
    master_sh = {k: shardings[k] for k in opt_state["master"]}
    repl = NamedSharding(mesh, P())
    opt_state = {
        "slots": {s: {k: jax.device_put(v, slots_sh[s][k])
                      for k, v in sv.items()}
                  for s, sv in opt_state["slots"].items()},
        "master": {k: jax.device_put(v, master_sh[k])
                   for k, v in opt_state["master"].items()},
        "step": jax.device_put(opt_state["step"], repl),
    }
    state = {"params": params, "buffers": buffers, "opt": opt_state}

    sep = mesh.shape.get("sep", 1)
    data_spec = P("dp", "sep") if sep > 1 else P("dp")
    data_sharding = NamedSharding(mesh, data_spec)
    fwd = getattr(model, "_orig_forward", model.forward)

    def step(state, x, *labels):
        def loss_of(p):
            out, new_buffers = functional_call(
                model, p, state["buffers"], (Tensor(x),), training=True,
                forward_fn=fwd)
            loss = loss_fn(out, *[Tensor(l) for l in labels])
            loss_arr = loss._data if isinstance(loss, Tensor) else loss
            return loss_arr.astype(jnp.float32), new_buffers

        (loss, new_buffers), grads = jax.value_and_grad(
            loss_of, has_aux=True)(state["params"])
        new_params, new_opt = optimizer.apply_gradients_tree(
            state["params"], grads, state["opt"])
        return loss, {"params": new_params, "buffers": new_buffers,
                      "opt": new_opt}

    def rng_step(state, key, x, *labels):
        with _random.trace_key_scope(key):
            return step(state, x, *labels)

    jitted = jax.jit(rng_step, donate_argnums=(0,) if donate else ())

    def run(state, x, *labels):
        x = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        labels = [l._data if isinstance(l, Tensor) else jnp.asarray(l)
                  for l in labels]
        x = jax.device_put(x, data_sharding)
        labels = [jax.device_put(l, data_sharding) for l in labels]
        key = _random.next_key()
        with jax.set_mesh(mesh):
            return jitted(state, key, x, *labels)

    return run, state
