"""Sharded train-step builder: the hybrid-parallel fast path.

This is the TPU-native replacement for the reference's entire hybrid
training machinery (SURVEY §3.3): where the reference composes
Fleet + HybridCommunicateGroup + PipelineParallel.train_batch +
EagerReducer + HybridParallelOptimizer at runtime, here ONE function
builds ONE jitted XLA program:

 - parameters/optimizer state placed by their ``PartitionSpec``
   annotations (mp from the TP layers, sharding from fsdp annotation)
 - batch sharded over dp (× sep for long sequences)
 - gradient psums over dp/sharding, TP collectives over mp, all compiled
   and overlapped by XLA over ICI

Used by fleet users, ``__graft_entry__.dryrun_multichip`` and the bench.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..tensor import Tensor
from ..nn.layer.layers import Layer
from ..jit.api import functional_call
from ..framework import random as _random
from . import mesh as _mesh_mod

__all__ = ["param_shardings", "shard_model_state", "build_train_step"]


def _spec_for(p, mesh):
    spec = getattr(p, "_spec", None)
    if spec is None:
        return P()
    # drop axis names the mesh doesn't have (e.g. model built with TP
    # annotations but run on a dp-only mesh)
    axes = []
    for entry in spec:
        if entry is None:
            axes.append(None)
        elif isinstance(entry, tuple):
            kept = tuple(a for a in entry if a in mesh.shape
                         and mesh.shape[a] > 1)
            axes.append(kept if kept else None)
        else:
            axes.append(entry if entry in mesh.shape and
                        mesh.shape[entry] > 1 else None)
    # verify divisibility; fall back to replicated otherwise
    for d, a in enumerate(axes):
        names = (a,) if isinstance(a, str) else (a or ())
        size = int(np.prod([mesh.shape[n] for n in names])) if names else 1
        if size > 1 and p.shape[d] % size:
            return P()
    return P(*axes)


def param_shardings(layer: Layer, mesh=None):
    """{name: NamedSharding} honoring per-parameter specs."""
    mesh = mesh or _mesh_mod.get_mesh()
    return {k: NamedSharding(mesh, _spec_for(p, mesh))
            for k, p in layer.named_parameters()}


def shard_model_state(layer: Layer, mesh=None):
    """Extract + place (params, buffers) arrays onto the mesh."""
    mesh = mesh or _mesh_mod.get_mesh()
    shardings = param_shardings(layer, mesh)
    # copy via jnp.copy: the step donates its state buffers, and the layer
    # must keep owning its original (undonated) arrays
    params = {k: jax.device_put(jnp.copy(p._data), shardings[k])
              for k, p in layer.named_parameters()}
    repl = NamedSharding(mesh, P())
    buffers = {k: jax.device_put(jnp.copy(b._data), repl)
               for k, b in layer.named_buffers()}
    return params, buffers, shardings


def build_train_step(model: Layer, loss_fn, optimizer, mesh=None,
                     donate=True, pipeline_microbatches=None):
    """Returns (step_fn, state) where
    ``state = {"params", "buffers", "opt"}`` is mesh-placed and
    ``step_fn(state, *batch) -> (loss, state)`` is one compiled program.

    ``loss_fn(outputs, *labels) -> scalar Tensor-or-array``.
    The batch's leading axis is sharded over ``dp`` (and the second axis
    over ``sep`` when that axis is >1, for sequence parallelism).

    When the mesh has a ``pp`` axis >1 and the model implements
    ``pipeline_blocks()``, the homogeneous block stack is *stacked* into
    ``__ppstack__.*`` leaves sharded over ``pp`` and executed as a compiled
    1F1B schedule (``meta_parallel.pp_spmd``) — each chip stores only its
    stage's blocks. ``pipeline_microbatches`` defaults to the pp degree.
    """
    mesh = mesh or _mesh_mod.get_mesh()
    pp = mesh.shape.get("pp", 1)
    if pp > 1 and pipeline_compatible(model, pp):
        return _build_pipelined_train_step(
            model, loss_fn, optimizer, mesh, donate,
            pipeline_microbatches or pp)
    params, buffers, shardings = shard_model_state(model, mesh)
    opt_state = optimizer.init_state_tree(params)
    # optimizer slots/master inherit each param's sharding (the ZeRO win:
    # an fsdp-annotated param stores adam moments sharded the same way)
    slots_sh = {s: {k: shardings[k] for k in opt_state["slots"][s]}
                for s in opt_state["slots"]}
    master_sh = {k: shardings[k] for k in opt_state["master"]}
    repl = NamedSharding(mesh, P())
    opt_state = {
        "slots": {s: {k: jax.device_put(v, slots_sh[s][k])
                      for k, v in sv.items()}
                  for s, sv in opt_state["slots"].items()},
        "master": {k: jax.device_put(v, master_sh[k])
                   for k, v in opt_state["master"].items()},
        "step": jax.device_put(opt_state["step"], repl),
    }
    state = {"params": params, "buffers": buffers, "opt": opt_state}

    sep = mesh.shape.get("sep", 1)
    data_spec = P("dp", "sep") if sep > 1 else P("dp")
    data_sharding = NamedSharding(mesh, data_spec)
    fwd = getattr(model, "_orig_forward", model.forward)

    def step(state, lr, x, *labels):
        def loss_of(p):
            out, new_buffers = functional_call(
                model, p, state["buffers"], (Tensor(x),), training=True,
                forward_fn=fwd)
            loss = loss_fn(out, *[Tensor(l) for l in labels])
            loss_arr = loss._data if isinstance(loss, Tensor) else loss
            return loss_arr.astype(jnp.float32), new_buffers

        (loss, new_buffers), grads = jax.value_and_grad(
            loss_of, has_aux=True)(state["params"])
        new_params, new_opt = optimizer.apply_gradients_tree(
            state["params"], grads, state["opt"], lr=lr)
        return loss, {"params": new_params, "buffers": new_buffers,
                      "opt": new_opt}

    def rng_step(state, key, lr, x, *labels):
        with _random.trace_key_scope(key):
            return step(state, lr, x, *labels)

    jitted = jax.jit(rng_step, donate_argnums=(0,) if donate else ())

    def run(state, x, *labels):
        x = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        labels = [l._data if isinstance(l, Tensor) else jnp.asarray(l)
                  for l in labels]
        x = jax.device_put(x, data_sharding)
        labels = [jax.device_put(l, data_sharding) for l in labels]
        key = _random.next_key()
        # LR threaded as a runtime arg: schedulers advance between compiled
        # steps without retracing
        lr = jnp.asarray(optimizer.get_lr(), jnp.float32)
        with jax.set_mesh(mesh):
            return jitted(state, key, lr, x, *labels)

    return run, state


def pipeline_compatible(model, pp):
    """True when the model's block stack can run the compiled pipeline:
    a pipeline_blocks() adapter, block count divisible by pp, and
    identical param sets/shapes across blocks (jnp.stack-able)."""
    if not hasattr(model, "pipeline_blocks"):
        return False
    try:
        prefixes, block_layer = model.pipeline_blocks()
    except ValueError:
        return False
    if not prefixes or len(prefixes) % pp:
        return False
    if dict(block_layer.named_buffers()):
        return False  # stage calls are buffer-free pure functions
    named = dict(model.named_parameters())
    locals0 = sorted(k[len(prefixes[0]):] for k in named
                     if k.startswith(prefixes[0]))
    if not locals0:
        return False
    for pfx in prefixes[1:]:
        locs = sorted(k[len(pfx):] for k in named if k.startswith(pfx))
        if locs != locals0:
            return False
        for loc in locs:
            if tuple(named[pfx + loc].shape) != \
                    tuple(named[prefixes[0] + loc].shape):
                return False
    return True


def _build_pipelined_train_step(model, loss_fn, optimizer, mesh, donate,
                                num_microbatches):
    """Pipeline-parallel variant of :func:`build_train_step`.

    State layout: the homogeneous blocks' parameters are stacked into
    ``__ppstack__.<local>`` leaves of shape ``[n_blocks, ...]`` sharded
    ``P("pp", *block_spec)`` — stage ``s`` physically stores blocks
    ``[s*L, (s+1)*L)`` only. The forward routes the model's block loop
    through ``pp_spmd.pipeline_spmd`` via the pipeline-executor scope.
    """
    from .fleet.meta_parallel.pp_spmd import (
        PP_STACK_PREFIX, pipeline_spmd, pipeline_executor_scope)

    pp = mesh.shape["pp"]
    prefixes, block_layer = model.pipeline_blocks()
    n_blocks = len(prefixes)
    if n_blocks % pp:
        raise ValueError(
            f"{n_blocks} pipeline blocks not divisible by pp={pp}")
    if dict(block_layer.named_buffers()):
        raise ValueError("pipelined blocks must be buffer-free")
    n_local = n_blocks // pp

    named = dict(model.named_parameters())
    block_locals = [k[len(prefixes[0]):] for k in named
                    if k.startswith(prefixes[0])]
    # stack [n_blocks, ...] per block-local param, shard over pp
    stacked, stacked_sh = {}, {}
    for loc in block_locals:
        p0 = named[prefixes[0] + loc]
        spec = _spec_for(p0, mesh)
        stacked[PP_STACK_PREFIX + loc] = jnp.stack(
            [jnp.copy(named[pfx + loc]._data) for pfx in prefixes])
        stacked_sh[PP_STACK_PREFIX + loc] = NamedSharding(
            mesh, P(*(("pp",) + tuple(spec))))
    block_names = {pfx + loc for pfx in prefixes for loc in block_locals}

    rest_sh = {k: NamedSharding(mesh, _spec_for(p, mesh))
               for k, p in named.items() if k not in block_names}
    params = {k: jax.device_put(jnp.copy(named[k]._data), rest_sh[k])
              for k in rest_sh}
    params.update({k: jax.device_put(v, stacked_sh[k])
                   for k, v in stacked.items()})
    shardings = {**rest_sh, **stacked_sh}

    repl = NamedSharding(mesh, P())
    buffers = {k: jax.device_put(jnp.copy(b._data), repl)
               for k, b in model.named_buffers()}

    opt_state = optimizer.init_state_tree(params)
    opt_state = {
        "slots": {s: {k: jax.device_put(v, shardings[k])
                      for k, v in sv.items()}
                  for s, sv in opt_state["slots"].items()},
        "master": {k: jax.device_put(v, shardings[k])
                   for k, v in opt_state["master"].items()},
        "step": jax.device_put(opt_state["step"], repl),
    }
    state = {"params": params, "buffers": buffers, "opt": opt_state}

    sep = mesh.shape.get("sep", 1)
    data_spec = P("dp", "sep") if sep > 1 else P("dp")
    data_sharding = NamedSharding(mesh, data_spec)
    fwd = getattr(model, "_orig_forward", model.forward)

    def step(state, lr, x, *labels):
        def loss_of(p):
            sp = {k[len(PP_STACK_PREFIX):]: v for k, v in p.items()
                  if k.startswith(PP_STACK_PREFIX)}
            rest = {k: v for k, v in p.items()
                    if not k.startswith(PP_STACK_PREFIX)}

            def executor(h, *extras):
                # extras (e.g. attention masks) ride as arrays so the
                # schedule can split per-micro-batch ones
                e_arrs = tuple(e._data if isinstance(e, Tensor) else e
                               for e in extras if e is not None)
                e_none = tuple(e is None for e in extras)

                def stage_fn(sp_local, harr, *earrs):
                    t = Tensor(harr)
                    it = iter(earrs)
                    eargs = tuple(None if none else Tensor(next(it))
                                  for none in e_none)
                    for j in range(n_local):
                        pj = {kk: vv[j] for kk, vv in sp_local.items()}
                        out, _ = functional_call(block_layer, pj, {},
                                                 (t,) + eargs)
                        t = out
                    return t._data
                y = pipeline_spmd(stage_fn, sp, h._data, num_microbatches,
                                  mesh=mesh, extras=e_arrs)
                return Tensor(y)

            with pipeline_executor_scope(executor):
                out, new_buffers = functional_call(
                    model, rest, state["buffers"], (Tensor(x),),
                    training=True, forward_fn=fwd)
            loss = loss_fn(out, *[Tensor(l) for l in labels])
            loss_arr = loss._data if isinstance(loss, Tensor) else loss
            return loss_arr.astype(jnp.float32), new_buffers

        (loss, new_buffers), grads = jax.value_and_grad(
            loss_of, has_aux=True)(state["params"])
        new_params, new_opt = optimizer.apply_gradients_tree(
            state["params"], grads, state["opt"], lr=lr)
        return loss, {"params": new_params, "buffers": new_buffers,
                      "opt": new_opt}

    def rng_step(state, key, lr, x, *labels):
        with _random.trace_key_scope(key):
            return step(state, lr, x, *labels)

    jitted = jax.jit(rng_step, donate_argnums=(0,) if donate else ())

    def run(state, x, *labels):
        x = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        labels = [l._data if isinstance(l, Tensor) else jnp.asarray(l)
                  for l in labels]
        x = jax.device_put(x, data_sharding)
        labels = [jax.device_put(l, data_sharding) for l in labels]
        key = _random.next_key()
        lr = jnp.asarray(optimizer.get_lr(), jnp.float32)
        with jax.set_mesh(mesh):
            return jitted(state, key, lr, x, *labels)

    return run, state
