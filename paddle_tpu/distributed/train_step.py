"""Sharded train-step builder: the hybrid-parallel fast path.

This is the TPU-native replacement for the reference's entire hybrid
training machinery (SURVEY §3.3): where the reference composes
Fleet + HybridCommunicateGroup + PipelineParallel.train_batch +
EagerReducer + HybridParallelOptimizer at runtime, here ONE function
builds ONE jitted XLA program:

 - parameters/optimizer state placed by their ``PartitionSpec``
   annotations (mp from the TP layers, sharding from fsdp annotation)
 - batch sharded over dp (× sep for long sequences)
 - gradient psums over dp/sharding, TP collectives over mp, all compiled
   and overlapped by XLA over ICI

Used by fleet users, ``__graft_entry__.dryrun_multichip`` and the bench.
"""
from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..tensor import Tensor
from ..nn.layer.layers import Layer
from ..jit.api import functional_call
from ..framework import random as _random
from . import mesh as _mesh_mod

__all__ = ["param_shardings", "shard_model_state", "build_train_step"]


from ._jax_compat import use_mesh as _use_mesh  # noqa: E402


def _spec_for(p, mesh):
    """Canonicalize a parameter's annotation against the mesh — the
    layout engine's :func:`resolve_spec` (drop absent/size-1 axes,
    e.g. a model built with TP annotations run on a dp-only mesh;
    replicate on indivisibility)."""
    from .auto_parallel.spec_layout import resolve_spec
    return resolve_spec(getattr(p, "_spec", None), tuple(p.shape), mesh)


def param_shardings(layer: Layer, mesh=None):
    """{name: NamedSharding} honoring per-parameter specs."""
    mesh = mesh or _mesh_mod.get_mesh()
    return {k: NamedSharding(mesh, _spec_for(p, mesh))
            for k, p in layer.named_parameters()}


def zero_spec(spec, shape, mesh, axis="sharding"):
    """ZeRO partition spec for an optimizer-state leaf: the param's spec
    with the ``sharding`` axis additionally placed on the largest dim it
    divides (ref ``dygraph_sharding_optimizer.py:29`` partitions the param
    LIST per rank; sharding each state tensor over the same mesh axis is
    the SPMD equivalent — per-device state bytes shrink ~1/N and XLA runs
    the update shard-local)."""
    from .auto_parallel.spec_layout import place_axis
    return place_axis(spec, tuple(shape), mesh.shape.get(axis, 1), axis)


def _zero_level(optimizer):
    """'os' | 'os_g' | None — set by group_sharded_parallel/strategy."""
    lvl = getattr(optimizer, "_group_sharded_level", None)
    return lvl if lvl in ("os", "os_g") else None


def shard_model_state(layer: Layer, mesh=None):
    """Extract + place (params, buffers) arrays onto the mesh."""
    mesh = mesh or _mesh_mod.get_mesh()
    shardings = param_shardings(layer, mesh)
    # copy via jnp.copy: the step donates its state buffers, and the layer
    # must keep owning its original (undonated) arrays
    params = {k: jax.device_put(jnp.copy(p._data), shardings[k])
              for k, p in layer.named_parameters()}
    repl = NamedSharding(mesh, P())
    buffers = {k: jax.device_put(jnp.copy(b._data), repl)
               for k, b in layer.named_buffers()}
    return params, buffers, shardings


def _place_opt_state(optimizer, params, shardings, mesh, zero):
    """Init + mesh-place the optimizer state tree. Slots/master inherit
    each param's sharding; with a ZeRO level they are additionally
    partitioned over the ``sharding`` axis (:func:`zero_spec`)."""
    opt_state = optimizer.init_state_tree(params)
    if zero:
        opt_sh = {k: NamedSharding(mesh, zero_spec(
            shardings[k].spec, params[k].shape, mesh))
            for k in params}
    else:
        opt_sh = dict(shardings)
    repl = NamedSharding(mesh, P())
    placed = {
        "slots": {s: {k: jax.device_put(v, opt_sh[k])
                      for k, v in sv.items()}
                  for s, sv in opt_state["slots"].items()},
        "master": {k: jax.device_put(v, opt_sh[k])
                   for k, v in opt_state["master"].items()},
        "step": jax.device_put(opt_state["step"], repl),
    }
    return placed, opt_sh


def _constrain_opt_state(opt_state, opt_sh):
    """Pin updated slot/master leaves to their shardings inside the trace
    (donation aliases buffers but does not force output shardings)."""
    return {
        "slots": {s: {k: jax.lax.with_sharding_constraint(v, opt_sh[k])
                      for k, v in sv.items()}
                  for s, sv in opt_state["slots"].items()},
        "master": {k: jax.lax.with_sharding_constraint(v, opt_sh[k])
                   for k, v in opt_state["master"].items()},
        "step": opt_state["step"],
    }


def _scaler_init_state(scaler):
    """Loss-scaling state as device scalars so the whole dynamic-scaling
    protocol (ref ``amp/grad_scaler.py:576`` + the pipeline's
    ``hybrid_parallel_gradscaler.py``) compiles into the train step: scale
    the loss, unscale grads, all-reduce-free finite check, skip the update
    on overflow, grow/shrink the scale — zero host round-trips."""
    return {"scale": jnp.float32(scaler.get_loss_scaling()),
            "good": jnp.int32(scaler._good_steps),
            "bad": jnp.int32(scaler._bad_steps),
            "found_inf": jnp.bool_(False)}


def _scaler_finish(scaler, grads, scale, old_state):
    """Unscale grads, detect non-finite, advance the scaler counters.
    Returns (unscaled grads, select(new, old) choosing old on overflow,
    new scaler state)."""
    inv = 1.0 / scale
    grads = {k: (g.astype(jnp.float32) * inv).astype(g.dtype)
             for k, g in grads.items()}
    finite = jnp.array(True)
    for g in grads.values():
        finite &= jnp.all(jnp.isfinite(g.astype(jnp.float32)))

    def select(new, old):
        return jax.tree.map(lambda a, b: jnp.where(finite, a, b), new, old)

    good = jnp.where(finite, old_state["good"] + 1, 0)
    bad = jnp.where(finite, 0, old_state["bad"] + 1)
    if scaler.is_use_dynamic_loss_scaling():
        grow = finite & (good >= scaler._incr_every_n_steps)
        shrink = (~finite) & (bad >= scaler._decr_every_n_nan_or_inf)
        new_scale = jnp.where(
            grow, scale * scaler._incr_ratio,
            jnp.where(shrink, jnp.maximum(scale * scaler._decr_ratio, 1.0),
                      scale))
        good = jnp.where(grow, 0, good)
        bad = jnp.where(shrink, 0, bad)
    else:
        new_scale = scale
    sstate = {"scale": new_scale, "good": good, "bad": bad,
              "found_inf": ~finite}
    return grads, select, sstate


def _bucket_plan_for(params, mesh, zero, grad_bucket_mb, shardings=None,
                     collective_schedule=None):
    """A :class:`grad_buckets.BucketPlan` when the bucketed-reduction
    path applies, else None.

    Bucketed reduction is the gradient fusion of the reference's
    ``EagerReducer``/``fuse_grad_size_in_MB``: it replaces the implicit
    GSPMD grad reduction with explicit per-bucket fused collectives
    placed mid-backward. Two eligible mesh families:

    - **pure dp, no ZeRO** (PR 10): every non-dp axis size 1; each
      bucket is one fused pmean over dp.
    - **dp × sharding with ZeRO** (stages 1–3): the collective-schedule
      planner (:mod:`collective_schedule`) plans each bucket as
      reduce_scatter(sharding) → all_reduce(dp) → all_gather, the
      per-rank scatter windows being the params' ``zero_spec`` windows
      (``shardings`` supplies the base specs).  Params the placement
      rule can't scatter (already fsdp-sharded, or no divisible dim)
      ride in plain all_reduce buckets.  ``PT_COLLECTIVE_SCHEDULE=0``
      (or a falsy ``collective_schedule`` strategy flag) disables this
      family only, restoring the pre-PR-11 GSPMD behavior.

    With mp/sep/ep/pp in play the reduction is GSPMD's to schedule —
    ineligible. ``PT_GRAD_BUCKETS=0`` disables all bucketing;
    ``grad_bucket_mb=0`` disables per call site.
    """
    import os
    from . import grad_buckets as _gb
    if grad_bucket_mb is not None and not grad_bucket_mb:
        return None
    if os.environ.get("PT_GRAD_BUCKETS", "1") in ("0", "false", "off"):
        return None
    if any(n > 1 for ax, n in mesh.shape.items()
           if ax not in ("dp", "sharding")):
        return None
    n_dp = mesh.shape.get("dp", 1)
    n_sh = mesh.shape.get("sharding", 1)
    if zero is None:
        if n_dp <= 1 or n_sh > 1:
            return None  # sharded mesh without ZeRO: GSPMD owns layout
        plan = _gb.partition_buckets(
            params, _gb.default_bucket_bytes(grad_bucket_mb))
        plan.record_metrics()
        return plan
    from . import collective_schedule as _cs
    from .auto_parallel.spec_layout import spec_axes
    sched = _cs.plan_grad_reduction(dict(mesh.shape), zero,
                                    enabled=collective_schedule)
    if sched is None or not sched.scatters:
        return None
    # per-param scatter dim: where zero_spec places the sharding axis
    # (None when the param is already fsdp-sharded or nothing divides —
    # those reduce as plain dp pmeans and re-slice outside)
    scatter_dims = {}
    for k, p in params.items():
        base = shardings[k].spec if shardings is not None else P()
        zs = zero_spec(base, p.shape, mesh)
        dim = None
        if zs is not base:
            base_e = list(base) + [None] * (len(zs) - len(base))
            for d, e in enumerate(zs):
                if "sharding" in spec_axes(e) \
                        and "sharding" not in spec_axes(base_e[d]):
                    dim = d
                    break
        scatter_dims[k] = dim
    plan = _gb.partition_buckets(
        params, _gb.default_bucket_bytes(grad_bucket_mb),
        scatter_dims=scatter_dims)
    plan.schedule = sched
    plan.record_metrics()
    return plan


def _bucketed_value_and_grad(model, fwd, loss_fn, autocast, plan, mesh,
                             state, scale, x, labels):
    """Loss + grads with per-bucket fused reductions, as one
    ``shard_map`` manual over the plan's mapped axes (``dp``, plus
    ``sharding`` for ZeRO reduce-scatter plans): the batch arrives as
    the local dp shard, the loss is the local mean, and each bucket's
    grads are reduced by its marker's backward — emitted exactly where
    that bucket's last cotangent forms, so the reductions interleave
    with (and can hide behind) the remaining backward.  Along
    ``sharding`` the batch is replicated, every rank computes identical
    grads, and the markers' psum_scatter hands each rank its zero_spec
    window."""
    from .grad_buckets import apply_bucketed_reduction
    from ._jax_compat import shard_map

    axes = tuple(plan.mapped_axes)

    def body(params, buffers, key, scale, x, *labels):
        # per-shard dropout stream: fold the dp coordinate so shards
        # draw independent masks (the global-batch analog). The
        # sharding coordinate is NOT folded: sharding ranks must draw
        # identical masks so their grads stay replica-identical (what
        # makes the scatter exact).
        key = jax.random.fold_in(key, jax.lax.axis_index("dp"))

        def loss_of(p):
            p = apply_bucketed_reduction(p, plan, "dp")
            with _random.trace_key_scope(key), \
                    (autocast() if autocast is not None
                     else contextlib.nullcontext()):
                out, new_buffers = functional_call(
                    model, p, buffers, (Tensor(x),), training=True,
                    forward_fn=fwd)
                loss = loss_fn(out, *[Tensor(l) for l in labels])
            loss_arr = loss._data if isinstance(loss, Tensor) else loss
            loss_arr = loss_arr.astype(jnp.float32)
            return loss_arr * scale, (loss_arr, new_buffers)

        (_, (loss, new_buffers)), grads = jax.value_and_grad(
            loss_of, has_aux=True)(params)
        loss = jax.lax.pmean(loss, "dp")
        # float buffers (running stats) merge as the dp mean; others are
        # deterministic/replicated and pass through from the local shard
        new_buffers = {
            k: (jax.lax.pmean(b, "dp")
                if jnp.issubdtype(b.dtype, jnp.floating) else b)
            for k, b in new_buffers.items()}
        return loss, grads, new_buffers

    key = _random.next_key()
    n_lab = len(labels)
    mapped = shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(), P(), P(), P("dp")) + tuple(
            P("dp") for _ in range(n_lab)),
        out_specs=(P(), P(), P()), axis_names=set(axes), check_vma=False)
    return mapped(state["params"], state["buffers"], key, scale, x,
                  *labels)


def build_train_step(model: Layer, loss_fn, optimizer, mesh=None,
                     donate=True, pipeline_microbatches=None, scaler=None,
                     pipeline_virtual_stages=1, autocast=None,
                     grad_bucket_mb=None, pipeline_overlap=None,
                     collective_schedule=None):
    """Returns (step_fn, state) where
    ``state = {"params", "buffers", "opt"}`` is mesh-placed and
    ``step_fn(state, *batch) -> (loss, state)`` is one compiled program.

    ``loss_fn(outputs, *labels) -> scalar Tensor-or-array``.
    The batch's leading axis is sharded over ``dp`` (and the second axis
    over ``sep`` when that axis is >1, for sequence parallelism).

    When the mesh has a ``pp`` axis >1 and the model implements
    ``pipeline_blocks()``, the homogeneous block stack is *stacked* into
    ``__ppstack__.*`` leaves sharded over ``pp`` and executed as a compiled
    1F1B schedule (``meta_parallel.pp_spmd``) — each chip stores only its
    stage's blocks. ``pipeline_microbatches`` defaults to the pp degree.

    ``scaler``: an ``amp.GradScaler`` — dynamic loss scaling runs INSIDE
    the compiled step (state gains a ``"scaler"`` entry; the update is
    skipped on overflow with no host round-trip).

    ``pipeline_virtual_stages``: interleaved-pipeline virtual stage count
    ``v`` (ref ``pipeline_parallel.py:807``): each chip holds ``v``
    non-adjacent block groups, shrinking the bubble by ``v``.

    ``autocast``: optional zero-arg callable returning a context manager
    (e.g. ``lambda: amp.auto_cast(level="O1", dtype="float16")``) entered
    around the forward at trace time — O1 white-list casts compile into
    the step.

    ``collective_schedule``: strategy-level enable flag for the
    mesh-aware collective-schedule pass (ZeRO reduce-scatter bucketing;
    ``sharding_configs.comm_overlap``). ``None`` defers to the
    ``PT_COLLECTIVE_SCHEDULE`` env default (on).
    """
    mesh = mesh or _mesh_mod.get_mesh()
    if scaler is not None and not scaler.is_enable():
        scaler = None
    pp = mesh.shape.get("pp", 1)
    if pp > 1 and pipeline_compatible(model, pp):
        # an explicit-but-indivisible virtual-stage request must fail
        # loudly, not silently build a NON-pipelined (fully replicated)
        # step on a pp mesh
        if pipeline_virtual_stages > 1 and not pipeline_compatible(
                model, pp * pipeline_virtual_stages):
            raise ValueError(
                f"pipeline blocks not divisible by pp*v = "
                f"{pp}*{pipeline_virtual_stages}; drop "
                f"pipeline_virtual_stages or change the block count")
        return _build_pipelined_train_step(
            model, loss_fn, optimizer, mesh, donate,
            pipeline_microbatches or pp, scaler,
            pipeline_virtual_stages, autocast, pipeline_overlap)
    params, buffers, shardings = shard_model_state(model, mesh)
    zero = _zero_level(optimizer)
    bucket_plan = _bucket_plan_for(params, mesh, zero, grad_bucket_mb,
                                   shardings=shardings,
                                   collective_schedule=collective_schedule)
    opt_state, opt_sh = _place_opt_state(optimizer, params, shardings,
                                         mesh, zero)
    state = {"params": params, "buffers": buffers, "opt": opt_state}
    if scaler is not None:
        repl = NamedSharding(mesh, P())
        state["scaler"] = jax.device_put(_scaler_init_state(scaler), repl)

    sep = mesh.shape.get("sep", 1)
    data_spec = P("dp", "sep") if sep > 1 else P("dp")
    data_sharding = NamedSharding(mesh, data_spec)
    fwd = getattr(model, "_orig_forward", model.forward)

    def step(state, lr, x, *labels):
        scale = (state["scaler"]["scale"] if scaler is not None
                 else jnp.float32(1.0))

        def loss_of(p):
            with (autocast() if autocast is not None
                  else contextlib.nullcontext()):
                out, new_buffers = functional_call(
                    model, p, state["buffers"], (Tensor(x),), training=True,
                    forward_fn=fwd)
                loss = loss_fn(out, *[Tensor(l) for l in labels])
            loss_arr = loss._data if isinstance(loss, Tensor) else loss
            loss_arr = loss_arr.astype(jnp.float32)
            return loss_arr * scale, (loss_arr, new_buffers)

        if bucket_plan is not None:
            loss, grads, new_buffers = _bucketed_value_and_grad(
                model, fwd, loss_fn, autocast, bucket_plan, mesh,
                state, scale, x, labels)
        else:
            (_, (loss, new_buffers)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(state["params"])
        if zero == "os_g":
            # ZeRO-2: constrain grads to the optimizer-state partition —
            # GSPMD turns the dp grad all-reduce into reduce-scatter and
            # the update runs shard-local (params re-gather on output)
            grads = {k: jax.lax.with_sharding_constraint(g, opt_sh[k])
                     for k, g in grads.items()}
        if scaler is not None:
            grads, select, sstate = _scaler_finish(
                scaler, grads, scale, state["scaler"])
        new_params, new_opt = optimizer.apply_gradients_tree(
            state["params"], grads, state["opt"], lr=lr)
        new_opt = _constrain_opt_state(new_opt, opt_sh)
        out_state = {"params": new_params, "buffers": new_buffers,
                     "opt": new_opt}
        if scaler is not None:
            out_state["params"] = select(out_state["params"],
                                         state["params"])
            out_state["opt"] = select(out_state["opt"], state["opt"])
            out_state["scaler"] = sstate
        return loss, out_state

    def rng_step(state, key, lr, x, *labels):
        with _random.trace_key_scope(key):
            return step(state, lr, x, *labels)

    jitted = jax.jit(rng_step, donate_argnums=(0,) if donate else ())

    def run(state, x, *labels):
        x = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        labels = [l._data if isinstance(l, Tensor) else jnp.asarray(l)
                  for l in labels]
        x = jax.device_put(x, data_sharding)
        labels = [jax.device_put(l, data_sharding) for l in labels]
        key = _random.next_key()
        # LR threaded as a runtime arg: schedulers advance between compiled
        # steps without retracing
        lr = jnp.asarray(optimizer.get_lr(), jnp.float32)
        with _use_mesh(mesh):
            return jitted(state, key, lr, x, *labels)

    # expose internals for AOT inspection (bench/memory tests lower the
    # jitted step to read XLA cost/memory analysis)
    run.jitted = jitted
    run.mesh = mesh
    run.data_sharding = data_sharding
    return run, state


def pipeline_compatible(model, pp):
    """True when the model's block stack can run the compiled pipeline:
    a pipeline_blocks() adapter, block count divisible by pp, and
    identical param sets/shapes across blocks (jnp.stack-able)."""
    if not hasattr(model, "pipeline_blocks"):
        return False
    try:
        prefixes, block_layer = model.pipeline_blocks()
    except ValueError:
        return False
    if not prefixes or len(prefixes) % pp:
        return False
    if dict(block_layer.named_buffers()):
        return False  # stage calls are buffer-free pure functions
    named = dict(model.named_parameters())
    locals0 = sorted(k[len(prefixes[0]):] for k in named
                     if k.startswith(prefixes[0]))
    if not locals0:
        return False
    for pfx in prefixes[1:]:
        locs = sorted(k[len(pfx):] for k in named if k.startswith(pfx))
        if locs != locals0:
            return False
        for loc in locs:
            if tuple(named[pfx + loc].shape) != \
                    tuple(named[prefixes[0] + loc].shape):
                return False
    return True


def _build_pipelined_train_step(model, loss_fn, optimizer, mesh, donate,
                                num_microbatches, scaler=None,
                                virtual_stages=1, autocast=None,
                                pipeline_overlap=None):
    """Pipeline-parallel variant of :func:`build_train_step`.

    State layout: the homogeneous blocks' parameters are stacked into
    ``__ppstack__.<local>`` leaves — shape ``[n_blocks, ...]`` sharded
    ``P("pp", *block_spec)`` (stage ``s`` physically stores blocks
    ``[s*L, (s+1)*L)`` only), or, with ``virtual_stages = v > 1``, the
    row-major reshape ``[v, pp*Lv, ...]`` sharded ``P(None, "pp", ...)``
    so chip ``s`` owns the interleaved virtual stages ``{g*pp + s}``. The
    forward routes the model's block loop through
    ``pp_spmd.pipeline_spmd`` via the pipeline-executor scope.
    """
    from .fleet.meta_parallel.pp_spmd import (
        PP_STACK_PREFIX, pipeline_spmd, pipeline_executor_scope)

    pp = mesh.shape["pp"]
    vstages = int(virtual_stages)
    prefixes, block_layer = model.pipeline_blocks()
    n_blocks = len(prefixes)
    if n_blocks % (pp * vstages):
        raise ValueError(
            f"{n_blocks} pipeline blocks not divisible by pp*v={pp * vstages}")
    if dict(block_layer.named_buffers()):
        raise ValueError("pipelined blocks must be buffer-free")

    named = dict(model.named_parameters())
    block_locals = [k[len(prefixes[0]):] for k in named
                    if k.startswith(prefixes[0])]
    # stack [n_blocks, ...] per block-local param, shard over pp;
    # interleaved: reshape to [v, pp*Lv, ...] (natural order preserved)
    stacked, stacked_sh = {}, {}
    for loc in block_locals:
        p0 = named[prefixes[0] + loc]
        spec = _spec_for(p0, mesh)
        arr = jnp.stack(
            [jnp.copy(named[pfx + loc]._data) for pfx in prefixes])
        if vstages > 1:
            arr = arr.reshape((vstages, n_blocks // vstages) + arr.shape[1:])
            sh = P(*((None, "pp") + tuple(spec)))
        else:
            sh = P(*(("pp",) + tuple(spec)))
        stacked[PP_STACK_PREFIX + loc] = arr
        stacked_sh[PP_STACK_PREFIX + loc] = NamedSharding(mesh, sh)
    block_names = {pfx + loc for pfx in prefixes for loc in block_locals}

    rest_sh = {k: NamedSharding(mesh, _spec_for(p, mesh))
               for k, p in named.items() if k not in block_names}
    params = {k: jax.device_put(jnp.copy(named[k]._data), rest_sh[k])
              for k in rest_sh}
    params.update({k: jax.device_put(v, stacked_sh[k])
                   for k, v in stacked.items()})
    shardings = {**rest_sh, **stacked_sh}

    repl = NamedSharding(mesh, P())
    buffers = {k: jax.device_put(jnp.copy(b._data), repl)
               for k, b in model.named_buffers()}

    zero = _zero_level(optimizer)
    opt_state, opt_sh = _place_opt_state(optimizer, params, shardings,
                                         mesh, zero)
    state = {"params": params, "buffers": buffers, "opt": opt_state}
    if scaler is not None:
        state["scaler"] = jax.device_put(_scaler_init_state(scaler), repl)

    sep = mesh.shape.get("sep", 1)
    data_spec = P("dp", "sep") if sep > 1 else P("dp")
    data_sharding = NamedSharding(mesh, data_spec)
    fwd = getattr(model, "_orig_forward", model.forward)

    def step(state, lr, x, *labels):
        scale = (state["scaler"]["scale"] if scaler is not None
                 else jnp.float32(1.0))

        def loss_of(p):
            sp = {k[len(PP_STACK_PREFIX):]: v for k, v in p.items()
                  if k.startswith(PP_STACK_PREFIX)}
            rest = {k: v for k, v in p.items()
                    if not k.startswith(PP_STACK_PREFIX)}

            def executor(h, *extras):
                # extras (e.g. attention masks) ride as arrays so the
                # schedule can split per-micro-batch ones
                e_arrs = tuple(e._data if isinstance(e, Tensor) else e
                               for e in extras if e is not None)
                e_none = tuple(e is None for e in extras)

                def stage_fn(sp_local, harr, *earrs):
                    t = Tensor(harr)
                    it = iter(earrs)
                    eargs = tuple(None if none else Tensor(next(it))
                                  for none in e_none)
                    # blocks-per-call = the received leaves' leading dim
                    # (n_blocks/pp plain; n_blocks/(pp*v) interleaved)
                    n_rows = next(iter(sp_local.values())).shape[0]
                    for j in range(n_rows):
                        pj = {kk: vv[j] for kk, vv in sp_local.items()}
                        out, _ = functional_call(block_layer, pj, {},
                                                 (t,) + eargs)
                        t = out
                    return t._data
                y = pipeline_spmd(stage_fn, sp, h._data, num_microbatches,
                                  mesh=mesh, extras=e_arrs,
                                  virtual_stages=vstages,
                                  overlap=pipeline_overlap)
                return Tensor(y)

            with pipeline_executor_scope(executor), \
                    (autocast() if autocast is not None
                     else contextlib.nullcontext()):
                out, new_buffers = functional_call(
                    model, rest, state["buffers"], (Tensor(x),),
                    training=True, forward_fn=fwd)
                loss = loss_fn(out, *[Tensor(l) for l in labels])
            loss_arr = loss._data if isinstance(loss, Tensor) else loss
            loss_arr = loss_arr.astype(jnp.float32)
            return loss_arr * scale, (loss_arr, new_buffers)

        (_, (loss, new_buffers)), grads = jax.value_and_grad(
            loss_of, has_aux=True)(state["params"])
        if zero == "os_g":
            grads = {k: jax.lax.with_sharding_constraint(g, opt_sh[k])
                     for k, g in grads.items()}
        if scaler is not None:
            grads, select, sstate = _scaler_finish(
                scaler, grads, scale, state["scaler"])
        new_params, new_opt = optimizer.apply_gradients_tree(
            state["params"], grads, state["opt"], lr=lr)
        new_opt = _constrain_opt_state(new_opt, opt_sh)
        out_state = {"params": new_params, "buffers": new_buffers,
                     "opt": new_opt}
        if scaler is not None:
            out_state["params"] = select(out_state["params"],
                                         state["params"])
            out_state["opt"] = select(out_state["opt"], state["opt"])
            out_state["scaler"] = sstate
        return loss, out_state

    def rng_step(state, key, lr, x, *labels):
        with _random.trace_key_scope(key):
            return step(state, lr, x, *labels)

    jitted = jax.jit(rng_step, donate_argnums=(0,) if donate else ())

    def run(state, x, *labels):
        x = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        labels = [l._data if isinstance(l, Tensor) else jnp.asarray(l)
                  for l in labels]
        x = jax.device_put(x, data_sharding)
        labels = [jax.device_put(l, data_sharding) for l in labels]
        key = _random.next_key()
        lr = jnp.asarray(optimizer.get_lr(), jnp.float32)
        with _use_mesh(mesh):
            return jitted(state, key, lr, x, *labels)

    # expose internals for AOT inspection (bench/memory tests lower the
    # jitted step to read XLA cost/memory analysis)
    run.jitted = jitted
    run.mesh = mesh
    run.data_sharding = data_sharding
    return run, state
