"""``paddle.distributed.rpc`` (ref: ``python/paddle/distributed/rpc/rpc.py``
over the brpc agent ``paddle/fluid/distributed/rpc/rpc_agent.cc``).

TPU-native design: a lightweight socket RPC agent per worker — the
control-plane companion to the XLA data path. Rendezvous rides the native
:class:`paddle_tpu.core.TCPStore` (the reference uses its TCPStore the same
way, ``rpc.py:73 init_rpc``); requests are pickled callables executed on
the target worker and answered with pickled results (the same
trusted-cluster model as the reference's brpc transport — ranks of one
training job on a private network).
"""
from __future__ import annotations

import concurrent.futures
import pickle
import socket
import socketserver
import struct
import threading
from dataclasses import dataclass

from ...utils.retry import wait_until

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown",
           "get_worker_info", "get_all_worker_infos",
           "get_current_worker_info", "WorkerInfo", "FutureWrapper"]

# reference default: -1 = infinite timeout (rpc.py:28 _DEFAULT_RPC_TIMEOUT)
_DEFAULT_RPC_TIMEOUT = -1


def _dumps(obj):
    """Callables cross the wire with cloudpickle when available (plain
    pickle rejects lambdas/closures; the reference's PythonFunc pickle
    has the same limitation — this is a strict superset)."""
    try:
        import cloudpickle
        return cloudpickle.dumps(obj)
    except ImportError:  # pragma: no cover - cloudpickle is baked in
        return pickle.dumps(obj)


class FutureWrapper:
    """Future returned by :func:`rpc_async` (ref ``rpc.py FutureWrapper``):
    ``wait()`` blocks and returns the result (re-raising remote errors)."""

    def __init__(self, fut):
        self._fut = fut

    def wait(self, timeout=None):
        return self._fut.result(timeout)

    # concurrent.futures-style alias so either idiom works
    def result(self, timeout=None):
        return self._fut.result(timeout)

    def done(self):
        return self._fut.done()

    def __getattr__(self, name):
        # preserve the concurrent.futures surface this API used to
        # return (cancel / exception / add_done_callback ...)
        return getattr(self._fut, name)


@dataclass
class WorkerInfo:
    name: str
    rank: int
    ip: str = "127.0.0.1"
    port: int = 0


_state = {"server": None, "pool": None, "workers": {}, "me": None,
          "store": None}


def _send_msg(sock, payload: bytes):
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv_msg(sock) -> bytes:
    hdr = b""
    while len(hdr) < 8:
        chunk = sock.recv(8 - len(hdr))
        if not chunk:
            raise ConnectionError("rpc peer closed")
        hdr += chunk
    (n,) = struct.unpack("<Q", hdr)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("rpc peer closed")
        buf += chunk
    return bytes(buf)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        try:
            payload = _recv_msg(self.request)
        except ConnectionError:
            return
        try:
            fn, args, kwargs = pickle.loads(payload)
            result = ("ok", fn(*args, **(kwargs or {})))
        except Exception as e:  # errors propagate to the caller
            result = ("err", e)
        try:
            try:
                reply = _dumps(result)
            except Exception as e:  # unpicklable result/exception state
                reply = _dumps(("err", RuntimeError(
                    f"rpc result not serializable: {e!r}")))
            _send_msg(self.request, reply)
        except (ConnectionError, OSError):
            pass


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def init_rpc(name, rank=None, world_size=None, master_endpoint=None):
    """Start this worker's agent and rendezvous with the others
    (ref ``rpc.py:73``). ``master_endpoint`` is "host:port" of the rank-0
    store; single-process usage may omit rank/world_size."""
    server = _Server(("0.0.0.0", 0), _Handler)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    ip = "127.0.0.1"
    me = WorkerInfo(name, 0 if rank is None else rank, ip, port)
    _state.update(server=server, me=me,
                  pool=concurrent.futures.ThreadPoolExecutor(8))

    if world_size is None or world_size <= 1:
        _state["workers"] = {name: me}
        return me

    from ... import core
    host, sport = (master_endpoint or "127.0.0.1:0").split(":")
    store = core.TCPStore(host, int(sport), is_master=(rank == 0),
                          timeout=60.0)
    _state["store"] = store
    store.set(f"rpc/worker/{rank}", pickle.dumps((name, rank, ip, port)))
    workers = {}
    for r in range(world_size):
        info = pickle.loads(store.get(f"rpc/worker/{r}", wait=True))
        workers[info[0]] = WorkerInfo(*info)
    _state["workers"] = workers
    # barrier: nobody proceeds until all have published + read the table
    store.add("rpc/ready", 1)
    wait_until(lambda: store.add("rpc/ready", 0) >= world_size,
               timeout=60.0, base=0.02, max_delay=0.25,
               desc="rpc rendezvous barrier")
    return me


def _target(to) -> WorkerInfo:
    w = _state["workers"].get(to)
    if w is None:
        raise ValueError(f"unknown rpc worker '{to}' "
                         f"(have {list(_state['workers'])})")
    return w


def _invoke(to, fn, args, kwargs, timeout):
    w = _target(to)
    me = _state["me"]
    if me is not None and w.name == me.name:
        return fn(*(args or ()), **(kwargs or {}))  # local fast path
    # reference timeout semantics (rpc.py:141): <= 0 means infinite —
    # including the connect phase (slow cluster start-up must not trip it)
    sock_timeout = None if timeout is None or timeout <= 0 else timeout
    with socket.create_connection((w.ip, w.port),
                                  timeout=sock_timeout) as s:
        s.settimeout(sock_timeout)
        _send_msg(s, _dumps((fn, args or (), kwargs or {})))
        status, value = pickle.loads(_recv_msg(s))
    if status == "err":
        raise value
    return value


def rpc_sync(to, fn, args=None, kwargs=None, timeout=_DEFAULT_RPC_TIMEOUT):
    """Blocking call on worker ``to`` (ref ``rpc.py:141``). ``timeout``
    in seconds; <= 0 (the default) never times out; on expiry a
    ``socket.timeout`` (OSError subclass) is raised."""
    return _invoke(to, fn, args, kwargs, timeout)


def rpc_async(to, fn, args=None, kwargs=None, timeout=_DEFAULT_RPC_TIMEOUT):
    """Non-blocking call; returns a :class:`FutureWrapper` whose
    ``wait()`` yields the result (ref ``rpc.py:179``)."""
    if _state["pool"] is None:
        raise RuntimeError("call init_rpc first")
    return FutureWrapper(
        _state["pool"].submit(_invoke, to, fn, args, kwargs, timeout))


def shutdown():
    if _state["server"] is not None:
        _state["server"].shutdown()
        _state["server"].server_close()
        _state["server"] = None
    if _state["pool"] is not None:
        _state["pool"].shutdown(wait=False)
        _state["pool"] = None
    if _state["store"] is not None:
        _state["store"].close()
        _state["store"] = None
    _state["workers"] = {}
    _state["me"] = None


def get_worker_info(name):
    return _target(name)


def get_all_worker_infos():
    return list(_state["workers"].values())


def get_current_worker_info():
    return _state["me"]
