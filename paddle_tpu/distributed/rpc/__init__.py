"""``paddle.distributed.rpc`` parity (ref: ``python/paddle/distributed/rpc/
rpc.py`` over brpc ``paddle/fluid/distributed/rpc/rpc_agent.cc``).

TPU-native stance: control-plane RPC between training processes is out of
the XLA data path; a minimal in-process/multiprocessing implementation
covers the API (init_rpc, rpc_sync, rpc_async, shutdown) for single-host
use. Cross-host RPC should ride the user's own transport — the reference's
brpc dependency is deliberately not replicated.
"""
from __future__ import annotations

import concurrent.futures

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown", "get_worker_info",
           "get_all_worker_infos", "get_current_worker_info"]

_pool = None
_workers = {}
_me = None


class WorkerInfo:
    def __init__(self, name, rank, ip="127.0.0.1", port=0):
        self.name = name
        self.rank = rank
        self.ip = ip
        self.port = port

    def __repr__(self):
        return f"WorkerInfo(name={self.name}, rank={self.rank})"


def init_rpc(name, rank=0, world_size=1, master_endpoint=None):
    global _pool, _me
    _pool = concurrent.futures.ThreadPoolExecutor(max_workers=4)
    _me = WorkerInfo(name, rank)
    _workers[name] = _me
    return _me


def rpc_sync(to, fn, args=None, kwargs=None, timeout=None):
    return fn(*(args or ()), **(kwargs or {}))


def rpc_async(to, fn, args=None, kwargs=None, timeout=None):
    if _pool is None:
        raise RuntimeError("call init_rpc first")
    return _pool.submit(fn, *(args or ()), **(kwargs or {}))


def shutdown():
    global _pool
    if _pool is not None:
        _pool.shutdown(wait=True)
        _pool = None
    _workers.clear()


def get_worker_info(name):
    return _workers.get(name)


def get_all_worker_infos():
    return list(_workers.values())


def get_current_worker_info():
    return _me
