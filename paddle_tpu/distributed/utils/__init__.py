"""Distributed helpers (ref: ``python/paddle/distributed/utils/``)."""
from __future__ import annotations

__all__ = ["global_scatter", "global_gather"]


def global_scatter(x, local_count, global_count, group=None):
    """MoE dispatch primitive (ref: ``utils/moe_utils.py global_scatter``);
    the TPU path uses dense all_to_all inside the MoE layer instead —
    exposed here for API parity."""
    from ..collective import alltoall_single
    return alltoall_single(x, group=group)


def global_gather(x, local_count, global_count, group=None):
    from ..collective import alltoall_single
    return alltoall_single(x, group=group)
