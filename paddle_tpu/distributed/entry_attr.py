"""Sparse-table entry filters (ref:
``python/paddle/distributed/entry_attr.py``): admission policies for
large-scale sparse embedding tables — a feature id enters the table
only probabilistically / after a show count / weighted by show-click.
Consumed by the parameter-server embedding
(:mod:`paddle_tpu.distributed.ps`)."""
from __future__ import annotations

__all__ = ["ProbabilityEntry", "CountFilterEntry", "ShowClickEntry"]


class EntryAttr:
    def __init__(self):
        self._name = None

    def _to_attr(self):
        raise NotImplementedError("EntryAttr is abstract")


class ProbabilityEntry(EntryAttr):
    """Admit a new feature id with fixed probability (ref
    ``entry_attr.py:57``)."""

    def __init__(self, probability):
        super().__init__()
        if not isinstance(probability, float):
            raise ValueError("probability must be a float in (0,1)")
        if probability <= 0 or probability >= 1:
            raise ValueError("probability must be a float in (0,1)")
        self._name = "probability_entry"
        self._probability = probability

    def _to_attr(self):
        return ":".join([self._name, str(self._probability)])


class CountFilterEntry(EntryAttr):
    """Admit a feature id once it has been seen ``count_filter`` times
    (ref ``entry_attr.py:121``)."""

    def __init__(self, count_filter):
        super().__init__()
        if not isinstance(count_filter, int):
            raise ValueError("count_filter must be a valid integer")
        if count_filter < 0:
            raise ValueError("count_filter must be a integer larger than 0")
        self._name = "count_filter_entry"
        self._count_filter = count_filter

    def _to_attr(self):
        return ":".join([self._name, str(self._count_filter)])


class ShowClickEntry(EntryAttr):
    """Weight feature admission by show/click statistic slots (ref
    ``entry_attr.py:184``)."""

    def __init__(self, show_name, click_name):
        super().__init__()
        if not isinstance(show_name, str) or not isinstance(click_name,
                                                            str):
            raise ValueError("show_name/click_name must be strings")
        self._name = "show_click_entry"
        self._show_name = show_name
        self._click_name = click_name

    def _to_attr(self):
        return ":".join([self._name, self._show_name, self._click_name])
