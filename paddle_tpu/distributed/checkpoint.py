"""Distributed (sharded) checkpoint with resharding on load.

ref: the reference's auto-parallel distributed checkpoint story —
per-rank save + merge-on-load converter
(``python/paddle/distributed/auto_parallel/static/dist_saver.py``,
``converter.py``) and the PP/sharding re-partitioning tool
(``python/paddle/distributed/fleet/utils/pp_parallel_adaptor.py``).

TPU-native re-design (orbax-style, no orbax dependency):

 - ``save_sharded(state, path)`` writes each array's *addressable* shards
   as ``<ckpt>/data/<leaf>/<k>.npy`` (replica 0 only — replicated copies
   are not duplicated) plus a JSON index per host
   (``index.<process>.json``) recording global shape/dtype/PartitionSpec
   and each shard file's index window. A 1.3B-param sharded state never
   materializes on one host.
 - ``load_sharded(path, template)`` builds arrays on the CURRENT mesh /
   target shardings via ``jax.make_array_from_callback``: each requested
   device slice is assembled from whichever saved shard files overlap it
   (``np.load(mmap_mode="r")`` so only the needed windows are read).
   The saved mesh and the loading mesh can differ arbitrarily — this IS
   the reference's "converter" resharding, done by index arithmetic.

Crash consistency (this framework's equivalent of the reference's
elastic fault tolerance — SURVEY §5): a preemption SIGKILL can land at
ANY instant of a save, so durability is enforced by construction:

 - every payload write is fsynced, then a ``COMMIT.<proc>`` marker — a
   manifest of per-file CRC32s and sizes — is written LAST;
 - single-host saves stage everything in ``<path>.tmp.<nonce>`` and
   commit via one atomic ``os.rename``; multi-host saves with a
   coordination ``store`` stage into one shared ``<path>.tmp.<nonce>``
   (nonce published by rank 0), barrier on all ``COMMIT.<proc>``
   markers (:func:`store_barrier` — a timeout names exactly the ranks
   that never arrived), then rank 0 promotes with one atomic rename —
   so a whole-process SIGKILL at any phase leaves only staging debris,
   never a half-committed final directory.  Store-less multi-host saves
   (shared fs, no rendezvous) fall back to in-place per-marker commit;
 - ``load_sharded`` verifies marker presence, shard existence, size,
   CRC and full window coverage of each leaf BEFORE constructing
   arrays, raising :class:`CheckpointCorruptError` naming the offending
   leaf/file instead of mmap-ing garbage weights;
 - elastic resume: ``load_sharded(..., elastic=True)`` re-shards a
   checkpoint written by ``world_size=M`` into a run with a different
   process count, stitching each leaf from whichever committed ranks'
   shard windows cover it; an uncoverable leaf raises
   :class:`ReshardError` (never a silent zero-fill);
 - :func:`sweep_staging` is the startup janitor for crash debris:
   age-gated removal of orphaned ``*.tmp.<nonce>`` staging dirs and
   partial-marker directories, never touching the newest in-flight
   nonce.

Works for any pytree of jax.Arrays (params / optimizer slots / stacked
``__ppstack__.*`` pipeline leaves alike); :class:`HostLocalShard`
leaves let a multi-process job without a global jax mesh save
host-partitioned numpy state through the same protocol.
"""
from __future__ import annotations

import io as _io
import json
import logging
import os
import re
import shutil
import time
import uuid
import zlib

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from . import mesh as _mesh_mod
from ..utils.retry import retry_call, wait_until

__all__ = ["save_sharded", "load_sharded", "save_state", "load_state",
           "CheckpointCorruptError", "ReshardError", "HostLocalShard",
           "is_committed", "verify_checkpoint", "store_barrier",
           "sweep_staging", "read_leaf"]

logger = logging.getLogger(__name__)

_COMMIT_RE = re.compile(r"^COMMIT\.(\d+)$")
_STAGING_RE = re.compile(r"\.(tmp|old)\.[0-9a-fA-F]+$")


class CheckpointCorruptError(RuntimeError):
    """A checkpoint directory failed commit/integrity verification:
    missing COMMIT markers, a missing/truncated/bit-flipped shard file,
    or shard windows that do not cover a leaf's full shape."""


class ReshardError(CheckpointCorruptError):
    """An elastic resume could not re-shard the checkpoint: the shard
    windows of the committed ranks leave a hole in some leaf, so the
    state cannot be reconstructed at the new world size.  Subclasses
    :class:`CheckpointCorruptError` so resume-from-latest fallback
    logic treats it as "this step is unusable", never as fatal."""


class HostLocalShard:
    """This process's window of a logically-global array.

    For multi-process jobs that do NOT run a global jax mesh (each
    process holds a host-local numpy block — drill workers, data-loader
    state, CPU-side optimizer tails): ``save_sharded`` records the
    declared ``global_shape``/``window`` instead of deriving them from
    device sharding, so N processes jointly write one resharding-capable
    checkpoint through the ordinary commit protocol.  ``window`` is
    ``[[start, stop], ...]`` per dimension into the global array and
    defaults to the full shape (a replicated leaf — every process
    writes it, windows overlap, any one covers it on elastic resume).
    """

    __slots__ = ("data", "window", "global_shape")

    def __init__(self, data, window=None, global_shape=None):
        self.data = np.asarray(data)
        self.global_shape = tuple(
            int(d) for d in (self.data.shape if global_shape is None
                             else global_shape))
        if window is None:
            window = [[0, d] for d in self.data.shape]
        self.window = [[int(a), int(b)] for a, b in window]
        if len(self.window) != len(self.global_shape):
            raise ValueError(
                f"window rank {len(self.window)} != global rank "
                f"{len(self.global_shape)}")
        for (a, b), dim in zip(self.window, self.global_shape):
            if not (0 <= a <= b <= dim):
                raise ValueError(f"window {self.window} out of bounds "
                                 f"for global shape {self.global_shape}")
        want = tuple(b - a for a, b in self.window)
        if want != tuple(self.data.shape):
            raise ValueError(f"data shape {self.data.shape} does not "
                             f"fill window {self.window}")

_SEP = "."  # flattened-tree key separator


def _unflatten(flat):
    """Rebuild the nested dict; keys were escaped (see _esc) so splitting
    on the separator is exact even though param names contain dots."""
    tree = {}
    for k, v in flat.items():
        parts = [_unesc(p) for p in k.split(_SEP)]
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return tree


def _esc(key):
    return key.replace("\\", "\\\\").replace(_SEP, "\\u002e")


def _unesc(key):
    return key.replace("\\u002e", _SEP).replace("\\\\", "\\")


def _flat_items(tree, path=()):
    if isinstance(tree, dict):
        for k, v in tree.items():
            yield from _flat_items(v, path + (str(k),))
    else:
        yield path, tree


def _leaf_name(path):
    return _SEP.join(_esc(p) for p in path)


def _spec_to_json(spec):
    if spec is None:
        return None
    out = []
    for e in spec:
        if e is None:
            out.append(None)
        elif isinstance(e, (tuple, list)):
            out.append(list(e))
        else:
            out.append(e)
    return out


def _json_to_spec(entries):
    if entries is None:
        return P()
    return P(*[tuple(e) if isinstance(e, list) else e for e in entries])


def _fs_name(leaf):
    """Filesystem-safe directory name for a leaf key."""
    return re.sub(r"[^A-Za-z0-9_.\-]", "_", leaf)


# -- durable write plumbing -------------------------------------------------
# Every byte that must survive a SIGKILL funnels through _write_file /
# _replace_dir; the fault-injection harness (tests/fault_injection.py)
# patches exactly these two to kill a save after the Nth write.

def _write_file(path, data, durable=True):
    """Write ``data`` bytes to ``path`` and fsync before returning."""
    with open(path, "wb") as f:
        f.write(data)
        if durable:
            f.flush()
            os.fsync(f.fileno())


def _fsync_dir(path):
    """fsync a directory so freshly-created entries survive a crash."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # not supported (e.g. some network fs) — best effort
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _replace_dir(tmp, final):
    """Atomically promote ``tmp`` to ``final`` via os.rename; an existing
    ``final`` is swapped out and removed after the new one is in place."""
    if os.path.isdir(final):
        old = f"{final}.old.{os.path.basename(tmp).rsplit('.', 1)[-1]}"
        shutil.rmtree(old, ignore_errors=True)
        os.rename(final, old)
        os.rename(tmp, final)
        shutil.rmtree(old, ignore_errors=True)
    else:
        os.rename(tmp, final)
    _fsync_dir(os.path.dirname(os.path.abspath(final)))


def _npy_bytes(arr):
    buf = _io.BytesIO()
    np.save(buf, arr)
    return buf.getvalue()


def _content_digest(arr):
    """CRC32 over the LOGICAL element bytes of one shard, taken from the
    live in-memory array at save time — before any serialization.

    Distinct from the COMMIT manifest's per-file CRC on purpose: the
    manifest CRC is computed over the .npy write buffer, so corruption
    that lands between device memory and serialization is sealed INTO
    the manifest and passes file verification forever.  The content
    digest is the end-to-end witness: it can only be reproduced by the
    same element bytes that were alive in the tree at save."""
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def _shard_records(state, proc):
    """Yield ``(relpath, bytes)`` for every durable file of this
    process's part of the checkpoint: each addressable replica-0 shard as
    ``data/<leaf>/<proc>_<k>.npy``, then ``index.<proc>.json`` LAST (an
    index must never land before the shards it points at)."""
    index = {}
    for p, arr in _flat_items(state):
        leaf = _leaf_name(p)
        if isinstance(arr, HostLocalShard):
            # host-declared window: no device sharding to consult
            fs = _fs_name(leaf)
            fname = f"{proc}_0.npy"
            index[leaf] = {"shape": list(arr.global_shape),
                           "dtype": str(arr.data.dtype),
                           "spec": None,
                           "shards": [{"file": f"{fs}/{fname}",
                                       "index": [list(w)
                                                 for w in arr.window],
                                       "digest": _content_digest(
                                           arr.data)}]}
            yield (f"data/{fs}/{fname}", _npy_bytes(arr.data))
            continue
        arr = arr if isinstance(arr, jax.Array) else jnp.asarray(arr)
        spec = None
        if isinstance(arr.sharding, NamedSharding):
            spec = _spec_to_json(arr.sharding.spec)
        entry = {
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
            "spec": spec,
            "shards": [],
        }
        fs = _fs_name(leaf)
        for k, shard in enumerate(arr.addressable_shards):
            if shard.replica_id != 0:
                continue  # replicated copy — one writer is enough
            fname = f"{proc}_{k}.npy"
            window = [[int(sl.start or 0),
                       int(sl.stop if sl.stop is not None else dim)]
                      for sl, dim in zip(shard.index, arr.shape)]
            # 0-d arrays: shard.index is (), window is []
            data = np.asarray(shard.data)
            entry["shards"].append({"file": f"{fs}/{fname}",
                                    "index": window,
                                    "digest": _content_digest(data)})
            yield (f"data/{fs}/{fname}", _npy_bytes(data))
        index[leaf] = entry
    yield (f"index.{proc}.json", json.dumps(index).encode())


def _write_records(root, records, durable=True):
    """Write ``(relpath, bytes)`` records under ``root``; returns the
    integrity manifest {relpath: {"crc32": ..., "size": ...}}."""
    manifest = {}
    made = set()
    for rel, data in records:
        dst = os.path.join(root, rel)
        d = os.path.dirname(dst)
        if d not in made:
            os.makedirs(d, exist_ok=True)
            made.add(d)
        _write_file(dst, data, durable=durable)
        manifest[rel] = {"crc32": zlib.crc32(data) & 0xFFFFFFFF,
                         "size": len(data)}
    return manifest


def _write_commit_marker(root, proc, world, manifest, durable=True,
                         nonce=None):
    marker = {"format": 1, "proc": proc, "world": world, "files": manifest}
    if nonce:
        marker["nonce"] = nonce
    _write_file(os.path.join(root, f"COMMIT.{proc}"),
                json.dumps(marker).encode(), durable=durable)
    _fsync_dir(root)


def _committed_nonce(path):
    """The staging nonce recorded in ``path``'s COMMIT markers, or None
    when the directory is absent / not fully committed / pre-nonce."""
    try:
        markers = _read_markers(path)
    except (FileNotFoundError, CheckpointCorruptError):
        return None
    return next(iter(markers.values())).get("nonce")


def _save_records(records, path, proc, world, store=None, durable=True,
                  nonce=None, run_id=None, barrier_timeout=300.0):
    """The commit protocol over pre-serialized records (shared by
    :func:`save_sharded` and the CheckpointManager async writer)."""
    if world <= 1:
        # single-writer: stage in <path>.tmp.<nonce>, commit by rename —
        # the checkpoint appears at `path` fully formed or not at all
        nonce = nonce or uuid.uuid4().hex[:8]
        tmp = f"{path}.tmp.{nonce}"
        shutil.rmtree(tmp, ignore_errors=True)
        manifest = _write_records(tmp, records, durable=durable)
        _write_commit_marker(tmp, proc, world, manifest, durable=durable,
                             nonce=nonce)
        _replace_dir(tmp, path)
    elif store is not None:
        # multi-host staged commit: all procs write into ONE shared
        # staging dir (nonce published by rank 0 — a relaunch after a
        # crashed save gets a fresh nonce, so stale attempts can never
        # mix into this one), barrier on all COMMIT markers, then rank 0
        # promotes with a single atomic rename.  A SIGKILL at any phase
        # leaves only `.tmp.<nonce>` debris for the janitor.
        base = os.path.basename(path)
        tag = f"ckpt/{run_id or '0'}/{base}"
        if proc == 0:
            nonce = nonce or uuid.uuid4().hex[:8]
            store.set(f"{tag}/nonce", nonce)
        else:
            got = store.get(f"{tag}/nonce", wait=True,
                            timeout=barrier_timeout)
            nonce = got.decode() if isinstance(got, bytes) else str(got)
        tmp = f"{path}.tmp.{nonce}"
        manifest = _write_records(tmp, records, durable=durable)
        _write_commit_marker(tmp, proc, world, manifest, durable=durable,
                             nonce=nonce)
        store_barrier(store, f"{tag}/{nonce}/commit", world, rank=proc,
                      timeout=barrier_timeout)
        if proc == 0:
            _replace_dir(tmp, path)
            store.set(f"{tag}/{nonce}/promoted", b"1")
        else:
            # rank 0 may die between rename and flag: the marker nonce
            # in the final dir is the authoritative promote signal
            wait_until(
                lambda: (store.get(f"{tag}/{nonce}/promoted", wait=False)
                         is not None
                         or _committed_nonce(path) == nonce),
                barrier_timeout,
                desc=f"checkpoint promote of {base} (nonce {nonce})")
    else:
        # store-less multi-host shared fs: every proc writes its own
        # files in place; the checkpoint is committed only once ALL
        # COMMIT.<proc> markers exist, so a partial save is detectable,
        # never loadable — but a crashed attempt leaves a partial marker
        # set in the FINAL dir (see sweep_staging), which the staged
        # path above avoids entirely
        os.makedirs(path, exist_ok=True)
        manifest = _write_records(path, records, durable=durable)
        _write_commit_marker(path, proc, world, manifest, durable=durable)


def save_sharded(state, path, process_index=None, *, world_size=None,
                 store=None, durable=True, run_id=None,
                 barrier_timeout=300.0):
    """Save a pytree of jax.Arrays as a crash-consistent sharded
    checkpoint directory.

    Each host writes only its addressable, replica-0 shards; call on every
    process of a multi-host job (single-controller semantics preserved:
    identical code path everywhere).  Single-process saves are atomic
    (stage + rename).  Multi-process saves with ``store`` (a
    :class:`paddle_tpu.core.TCPStore`) use the staged protocol: shared
    ``<path>.tmp.<nonce>`` staging, a COMMIT barrier over all
    ``world_size`` processes, one atomic promote by rank 0 — ``run_id``
    (defaults to ``$PT_RUN_ID``) isolates barrier keys across
    relaunches of the same job.  Without a store, multi-process saves
    commit in place via per-process markers.  ``durable=False`` skips
    fsyncs (tests / throwaway dirs).
    """
    proc = jax.process_index() if process_index is None else process_index
    world = jax.process_count() if world_size is None else world_size
    _save_records(_shard_records(state, proc), path, proc, world,
                  store=store, durable=durable,
                  run_id=run_id or os.environ.get("PT_RUN_ID"),
                  barrier_timeout=barrier_timeout)


def _barrier_arrive(store, key, rank=None):
    """Announce this process at the barrier (the per-rank key makes a
    hung barrier diagnosable: the waiters can name who never arrived)."""
    if rank is not None:
        store.set(f"{key}/rank/{rank}", b"1")
    return store.add(key, 1)


class _StoreGone(Exception):
    """Internal carrier: a StoreUnavailableError inside a retried
    barrier step.  StoreUnavailableError subclasses ConnectionError, so
    retry_call's transient filter would keep retrying it — this wrapper
    pierces the filter (terminal: the client already exhausted ITS
    deadline / was generation-fenced) and the original is re-raised at
    the barrier boundary via ``__cause__``."""


def store_barrier(store, key, world, rank=None, timeout=300.0):
    """Block until ``world`` processes have entered this barrier — the
    multi-host commit seal: after it returns, every process's COMMIT
    marker is on the shared filesystem.

    Pass ``rank`` so a timeout names exactly which ranks are missing
    (diff of arrived per-rank keys vs the expected set) instead of only
    a count — one log line locates the dead process in a hung drill.

    Fault semantics: a transient ``ConnectionError``/``TimeoutError``
    while arriving or polling (store master restarting) is retried
    within ``timeout`` instead of failing the commit instantly; a
    :class:`~paddle_tpu.distributed.resilient_store.StoreUnavailableError`
    (the client's own deadline already spent, or an amnesiac master
    fenced) is terminal and propagates at once.  With ``rank`` the seal
    is the set of idempotent per-rank arrival keys, so a retried
    arrival that double-bumps the shared counter can never release the
    barrier early; ``rank=None`` keeps the legacy counter-only contract
    (stores that only implement ``add``).
    """
    from ..observability import get_telemetry
    from .resilient_store import StoreUnavailableError

    _transient = (ConnectionError, TimeoutError, OSError)

    def _missing_ranks():
        try:
            arrived = sorted(
                p for p in range(world)
                if store.get(f"{key}/rank/{p}", wait=False) is not None)
        except _transient as e:
            return (f"store unreachable while probing arrivals "
                    f"({type(e).__name__}: {e})")
        missing = sorted(set(range(world)) - set(arrived))
        return (f"{len(arrived)}/{world} ranks arrived; missing ranks "
                f"{missing} (arrived: {arrived})")

    def _arrive_once():
        try:
            return _barrier_arrive(store, key, rank)
        except StoreUnavailableError as e:
            raise _StoreGone() from e

    arrived_cache: set[int] = set()

    def _sealed():
        try:
            if rank is not None:
                # idempotent seal: per-rank keys, monotonic accumulate
                for p in range(world):
                    if p not in arrived_cache and store.get(
                            f"{key}/rank/{p}", wait=False) is not None:
                        arrived_cache.add(p)
                return len(arrived_cache) >= world
            return store.add(key, 0) >= world
        except StoreUnavailableError:
            raise  # client deadline spent / fenced: terminal
        except _transient as e:
            logger.warning(
                "checkpoint barrier %r: transient store error while "
                "polling (%s: %s); retrying within deadline",
                key, type(e).__name__, e)
            return False

    t0 = time.monotonic()
    ok = False
    try:
        try:
            retry_call(_arrive_once, retry_on=_transient,
                       deadline=timeout, base=0.05, max_delay=1.0)
        except _StoreGone as e:
            raise e.__cause__
        remaining = max(0.0, timeout - (time.monotonic() - t0))
        wait_until(_sealed, remaining,
                   desc=f"checkpoint barrier {key!r} ({world} procs)",
                   diag=_missing_ranks if rank is not None else None)
        ok = True
    finally:
        get_telemetry().record_barrier_wait(time.monotonic() - t0, ok=ok)


# -- commit / integrity verification ----------------------------------------

def _read_markers(path, elastic=False):
    """Parse every COMMIT.<proc> marker under ``path``; raises
    CheckpointCorruptError when none exist, any is unreadable, or —
    unless ``elastic`` — the set is short of the recorded world size
    (``elastic=True`` accepts a partial set and lets coverage stitching
    decide whether the committed ranks' windows suffice)."""
    if not os.path.isdir(path):
        raise FileNotFoundError(f"no checkpoint directory at {path}")
    markers = {}
    for n in os.listdir(path):
        m = _COMMIT_RE.match(n)
        if not m:
            continue
        try:
            with open(os.path.join(path, n)) as f:
                markers[int(m.group(1))] = json.load(f)
        except (OSError, ValueError) as e:
            if elastic:
                logger.warning("%s: skipping unreadable commit marker "
                               "%s for elastic resume: %s", path, n, e)
                continue
            raise CheckpointCorruptError(
                f"{path}: unreadable commit marker {n}: {e}")
    if not markers:
        raise CheckpointCorruptError(
            f"{path}: no COMMIT marker — checkpoint was never committed "
            f"(save crashed mid-write?)")
    world = max(mk.get("world", 1) for mk in markers.values())
    missing = [p for p in range(world) if p not in markers]
    if missing:
        if not elastic:
            raise CheckpointCorruptError(
                f"{path}: partially committed checkpoint: COMMIT markers "
                f"present for ranks {sorted(markers)} but the recorded "
                f"world_size={world} expects ranks "
                f"{list(range(world))}; missing ranks {missing}. If the "
                f"fleet changed size or lost hosts, resume elastically "
                f"(load_sharded(..., elastic=True) / "
                f"CheckpointManager(..., elastic=True)) to re-shard from "
                f"the committed ranks' shard windows")
        logger.warning(
            "%s: elastic resume from a partial commit — using ranks %s "
            "of world_size=%d (missing %s); leaf coverage will be "
            "verified before any array is built",
            path, sorted(markers), world, missing)
    return markers


def _verify_manifest(path, markers, integrity="full", elastic=False):
    """Check every manifested file for existence/size (and CRC32 when
    ``integrity='full'``); stray index files outside any manifest are
    corruption too (debris of an aborted multi-host save) — except under
    ``elastic``, where files of non-committed ranks are expected debris
    and simply ignored."""
    manifest = {}
    for mk in markers.values():
        manifest.update(mk.get("files", {}))
    for rel, want in manifest.items():
        fp = os.path.join(path, rel)
        if not os.path.exists(fp):
            raise CheckpointCorruptError(
                f"{path}: manifested file {rel} is missing")
        size = os.path.getsize(fp)
        if size != want["size"]:
            raise CheckpointCorruptError(
                f"{path}: {rel} truncated/resized: {size} bytes on disk, "
                f"{want['size']} in manifest")
        if integrity == "full":
            crc = 0
            with open(fp, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    crc = zlib.crc32(chunk, crc)
            if (crc & 0xFFFFFFFF) != want["crc32"]:
                raise CheckpointCorruptError(
                    f"{path}: {rel} failed CRC32 check "
                    f"(bit rot or partial write)")
    if not elastic:
        for n in os.listdir(path):
            if n.startswith("index.") and n.endswith(".json") \
                    and n not in manifest:
                raise CheckpointCorruptError(
                    f"{path}: index file {n} is not covered by any COMMIT "
                    f"manifest (debris of an aborted save?)")
    return manifest


def _verify_coverage(path, leaf, entry, elastic=False, committed=None):
    """Every shard window in bounds + windows jointly covering the full
    shape.  The volume test is exact for the save path (windows of one
    world never overlap) and conservative under elastic stitching
    (replicated leaves overlap, making ``covered > total`` — a deficit
    therefore always means a real hole a load would otherwise fill with
    mmap garbage).  Under ``elastic`` a hole raises :class:`ReshardError`
    naming the committed ranks so the operator can see whose windows are
    gone."""
    shape = tuple(entry["shape"])
    total = int(np.prod(shape)) if shape else 1
    exc = ReshardError if elastic else CheckpointCorruptError
    if not entry["shards"]:
        raise exc(f"{path}: leaf '{leaf}' has no shard files")
    covered = 0
    for sh in entry["shards"]:
        win = sh["index"]
        if len(win) != len(shape):
            raise CheckpointCorruptError(
                f"{path}: leaf '{leaf}' shard {sh['file']} window rank "
                f"{len(win)} != array rank {len(shape)}")
        vol = 1
        for (a, b), dim in zip(win, shape):
            if not (0 <= a < b <= dim):
                raise CheckpointCorruptError(
                    f"{path}: leaf '{leaf}' shard {sh['file']} window "
                    f"{win} out of bounds for shape {list(shape)}")
            vol *= b - a
        covered += vol
    if covered < total:
        if elastic:
            raise ReshardError(
                f"{path}: cannot re-shard leaf '{leaf}': the windows of "
                f"committed ranks {committed} cover only {covered} of "
                f"{total} elements of shape {list(shape)} — the missing "
                f"ranks' shard files are required and a zero-fill would "
                f"silently corrupt the state")
        raise CheckpointCorruptError(
            f"{path}: leaf '{leaf}' shards cover {covered} of {total} "
            f"elements — missing shard files for shape {list(shape)}")


def _verify_leaf_digests(path, leaf, entry):
    """Recompute each shard's content digest from the reconstructed
    element bytes and compare against the value recorded from the live
    array at save.  Per shard file, so it holds under elastic M→N
    restitch (the saved windows are verified regardless of the target
    partitioning).  Shards without a recorded digest — checkpoints
    written before digests existed — are skipped, keeping old
    checkpoints loadable."""
    for sh in entry.get("shards", ()):
        want = sh.get("digest")
        if want is None:
            continue
        fp = os.path.join(path, "data", sh["file"])
        try:
            src = np.load(fp, mmap_mode="r")
        except Exception as e:
            raise CheckpointCorruptError(
                f"{path}: leaf '{leaf}' shard {sh['file']} is "
                f"unreadable: {e}") from e
        got = _content_digest(src)
        if got != int(want):
            raise CheckpointCorruptError(
                f"{path}: leaf '{leaf}' shard {sh['file']} failed its "
                f"content digest check (recorded {int(want):#010x} from "
                f"the live array at save, reconstructed {got:#010x}) — "
                f"silent corruption between device memory and restore")


def is_committed(path):
    """True iff ``path`` holds a fully committed checkpoint (all
    ``COMMIT.<proc>`` markers present and parseable). Cheap: no CRC."""
    try:
        _read_markers(path)
        return True
    except (FileNotFoundError, CheckpointCorruptError):
        return False


def verify_checkpoint(path, integrity="full", elastic=False):
    """Full integrity audit of a checkpoint directory; raises
    :class:`CheckpointCorruptError` (or FileNotFoundError) naming the
    offending file/leaf. ``integrity``: "full" checks CRC32s, "size"
    only existence+size (cheap scan), "off" checks markers only.
    ``elastic=True`` accepts a partially-committed checkpoint as long as
    the committed ranks' windows still cover every leaf (raising
    :class:`ReshardError` otherwise).  Returns the merged leaf index on
    success."""
    markers = _read_markers(path, elastic=elastic)
    if integrity in ("full", "size"):
        _verify_manifest(path, markers, integrity=integrity,
                         elastic=elastic)
    merged = _merge_index(path, procs=sorted(markers))
    if integrity in ("full", "size"):
        for leaf, entry in merged.items():
            _verify_coverage(path, leaf, entry, elastic=elastic,
                             committed=sorted(markers))
            if integrity == "full":
                _verify_leaf_digests(path, leaf, entry)
    return merged


def _merge_index(path, procs=None):
    """Merge ``index.<proc>.json`` files into one leaf index.  ``procs``
    restricts the merge to the given (committed) ranks — the elastic
    stitching rule: never read a window a dead rank may have torn."""
    merged = {}
    names = sorted(n for n in os.listdir(path)
                   if n.startswith("index.") and n.endswith(".json"))
    if procs is not None:
        want = {f"index.{p}.json" for p in procs}
        names = [n for n in names if n in want]
    if not names:
        raise FileNotFoundError(f"no index.*.json under {path}")
    for n in names:
        with open(os.path.join(path, n)) as f:
            idx = json.load(f)
        for leaf, entry in idx.items():
            if leaf in merged:
                merged[leaf]["shards"].extend(entry["shards"])
            else:
                merged[leaf] = entry
    return merged


def _read_index(path, verify=True, integrity="full", elastic=False):
    if verify:
        return verify_checkpoint(path, integrity=integrity,
                                 elastic=elastic)
    return _merge_index(path)


def sweep_staging(root, max_age=3600.0, now=None):
    """Startup janitor: remove crash debris under checkpoint root
    ``root``.

    Sweeps two kinds of orphans a SIGKILL mid-save leaves behind:

     - staging/backup directories (``*.tmp.<nonce>`` / ``*.old.<nonce>``)
       — except the NEWEST staging dir, which may belong to a
       still-running save on a shared filesystem (the "never touch the
       newest in-flight nonce" rule), and
     - partially-committed checkpoint directories (a marker/index/data
       set short of its recorded world size — debris of a store-less
       in-place multi-host save; the staged protocol never creates
       these) — a later in-place re-save could otherwise mix stale
       markers of a dead generation into a new commit.

    Both are age-gated: only entries whose mtime is older than
    ``max_age`` seconds are touched, so a concurrently-starting peer's
    fresh files survive.  Fully committed checkpoints are never removed
    here (retention is the CheckpointManager GC's job).  Returns the
    number of directories removed; filesystem races are swallowed — a
    janitor must never take down a starting run.
    """
    try:
        names = os.listdir(root)
    except OSError:
        return 0
    now = time.time() if now is None else now
    staging, partial = [], []
    for n in names:
        p = os.path.join(root, n)
        if not os.path.isdir(p):
            continue
        try:
            age = now - os.path.getmtime(p)
        except OSError:
            continue
        if _STAGING_RE.search(n):
            staging.append((age, p))
        elif age > max_age and _looks_like_checkpoint(p) \
                and not is_committed(p):
            partial.append(p)
    if staging:
        # newest in-flight nonce is spared unconditionally
        staging.sort()
        partial.extend(p for age, p in staging[1:] if age > max_age)
    swept = 0
    for p in partial:
        logger.info("checkpoint janitor: sweeping orphaned %s", p)
        shutil.rmtree(p, ignore_errors=True)
        swept += 1
    if swept:
        from ..observability import get_telemetry
        get_telemetry().record_staging_sweep(swept)
    return swept


def _looks_like_checkpoint(path):
    """Only directories bearing checkpoint artifacts are janitor
    candidates — never an arbitrary user directory under the root."""
    try:
        names = os.listdir(path)
    except OSError:
        return False
    return any(_COMMIT_RE.match(n) or n == "data"
               or (n.startswith("index.") and n.endswith(".json"))
               for n in names)


def read_leaf(path, leaf, window=None, integrity="size", elastic=False):
    """Host-side window read of one saved leaf as a plain numpy array —
    no jax arrays, no mesh (drill workers / inspection tooling).

    ``window``: ``[[start, stop], ...]`` into the global shape (defaults
    to the full array).  The checkpoint is verified first at
    ``integrity`` level — but coverage only for the REQUESTED leaf, so
    an elastic hole elsewhere doesn't block reading an intact leaf;
    ``elastic=True`` stitches from the committed ranks only (raising
    :class:`ReshardError` when this leaf has a hole).
    """
    markers = _read_markers(path, elastic=elastic)
    if integrity in ("full", "size"):
        _verify_manifest(path, markers, integrity=integrity,
                         elastic=elastic)
    index = _merge_index(path, procs=sorted(markers))
    if leaf in index and integrity in ("full", "size"):
        _verify_coverage(path, leaf, index[leaf], elastic=elastic,
                         committed=sorted(markers))
        if integrity == "full":
            _verify_leaf_digests(path, leaf, index[leaf])
    if leaf not in index:
        raise KeyError(f"{path}: no leaf {leaf!r} "
                       f"(have: {sorted(index)[:16]})")
    reader = _LeafReader(path, index[leaf])
    if window is None:
        sel = tuple(slice(0, d) for d in reader.shape)
    else:
        sel = tuple(slice(int(a), int(b)) for a, b in window)
    return reader.read(sel)


class _LeafReader:
    """Assembles arbitrary index windows of one saved array from its
    shard files (mmap'd — only overlapping windows touch disk)."""

    def __init__(self, path, entry):
        self.path = path
        self.entry = entry
        self.shape = tuple(entry["shape"])
        self.dtype = entry["dtype"]

    def read(self, idx):
        """idx: tuple of slices into the global array."""
        want = [(sl.start or 0,
                 sl.stop if sl.stop is not None else dim)
                for sl, dim in zip(idx, self.shape)]
        out_shape = tuple(b - a for a, b in want)
        if self.dtype == "bfloat16":
            import ml_dtypes
            np_dtype = ml_dtypes.bfloat16
        else:
            np_dtype = np.dtype(self.dtype)
        out = np.empty(out_shape, np_dtype)
        filled = 0
        for sh in self.entry["shards"]:
            win = sh["index"] or [[0, 1]] * 0
            inter = []
            ok = True
            for (wa, wb), (sa, sb) in zip(want, win):
                a, b = max(wa, sa), min(wb, sb)
                if a >= b:
                    ok = False
                    break
                inter.append((a, b))
            if not ok and want:
                continue
            src = np.load(os.path.join(self.path, "data", sh["file"]),
                          mmap_mode="r")
            if not want:  # 0-d
                return np.asarray(src)
            src_sel = tuple(slice(a - sa, b - sa)
                            for (a, b), (sa, _sb) in zip(inter, win))
            dst_sel = tuple(slice(a - wa, b - wa)
                            for (a, b), (wa, _wb) in zip(inter, want))
            out[dst_sel] = src[src_sel]
            filled += int(np.prod([b - a for a, b in inter]))
        if filled < int(np.prod(out_shape)):
            raise ValueError(
                f"checkpoint shards do not cover requested window {want}")
        return out


_PP = "__ppstack__."


def _natkey(s):
    """Natural sort key ("layers.10." after "layers.9.")."""
    return [int(t) if t.isdigit() else t
            for t in re.split(r"(\d+)", s)]


class _StackedReader:
    """Presents N per-block saved leaves as one [N, ...] stacked array
    (loading an unstacked checkpoint into a pp-stacked state)."""

    def __init__(self, readers):
        self.readers = readers
        self.shape = (len(readers),) + readers[0].shape
        self.dtype = readers[0].dtype

    def read(self, idx):
        lead, rest = idx[0], idx[1:]
        lo = lead.start or 0
        hi = lead.stop if lead.stop is not None else len(self.readers)
        full = tuple(slice(0, d) for d in self.readers[0].shape)
        rest = tuple(r if r.start is not None or r.stop is not None else f
                     for r, f in zip(rest, full)) if rest else full
        parts = [self.readers[i].read(rest)[None] for i in range(lo, hi)]
        return np.concatenate(parts, 0) if parts else \
            np.empty((0,) + self.readers[0].shape, self.readers[0].dtype)


class _RowReader:
    """Row i of a saved stacked leaf (loading a pp-stacked checkpoint
    into an unstacked state) — the pp_parallel_adaptor direction."""

    def __init__(self, reader, i):
        self.reader = reader
        self.i = i
        self.shape = reader.shape[1:]
        self.dtype = reader.dtype

    def read(self, idx):
        idx = tuple(idx) if idx else tuple(slice(0, d) for d in self.shape)
        out = self.reader.read((slice(self.i, self.i + 1),) + idx)
        return out[0]


class _LeadLayoutReader:
    """Present a saved ``__ppstack__`` leaf under a different leading
    layout: flat ``[N, ...]`` ↔ interleaved ``[v, N/v, ...]``. Both are
    row-major views of the natural block order, so only leading-index
    arithmetic changes."""

    def __init__(self, reader, shape):
        self.reader = reader
        self.shape = tuple(shape)
        self.dtype = reader.dtype
        # leading-dim count per side: 1 (flat) or 2 (interleaved)
        self._src_lead = 2 if len(reader.shape) > len(shape) else 1
        self._tgt_lead = 2 if len(shape) > len(reader.shape) else 1

    def _read_flat_rows(self, lo, hi, rest):
        r = self.reader
        if self._src_lead == 1:
            return r.read((slice(lo, hi),) + rest)
        R = r.shape[1]
        parts = []
        for g in range(lo // R, (hi - 1) // R + 1):
            r0 = max(lo - g * R, 0)
            r1 = min(hi - g * R, R)
            parts.append(r.read((slice(g, g + 1), slice(r0, r1)) + rest)[0])
        return np.concatenate(parts, 0)

    def read(self, idx):
        idx = tuple(idx) if idx else ()
        full = tuple(slice(0, d) for d in self.shape)
        idx = tuple(s if (s.start is not None or s.stop is not None) else f
                    for s, f in zip(idx, full)) + full[len(idx):]
        if self._tgt_lead == 1:
            lo = idx[0].start or 0
            hi = idx[0].stop if idx[0].stop is not None else self.shape[0]
            return self._read_flat_rows(lo, hi, idx[1:])
        R = self.shape[1]
        g0 = idx[0].start or 0
        g1 = idx[0].stop if idx[0].stop is not None else self.shape[0]
        r0 = idx[1].start or 0
        r1 = idx[1].stop if idx[1].stop is not None else R
        rows = [self._read_flat_rows(g * R + r0, g * R + r1, idx[2:])[None]
                for g in range(g0, g1)]
        return np.concatenate(rows, 0) if rows else np.empty(
            (0, r1 - r0) + tuple(
                (s.stop or d) - (s.start or 0)
                for s, d in zip(idx[2:], self.shape[2:])), self.dtype)


def _adapt_pp_layout(readers, tmpl_flat):
    """Bridge flat vs interleaved pp-stack layouts (same total blocks,
    different leading split) between checkpoint and template."""
    for tk, tmpl in tmpl_flat.items():
        r = readers.get(tk)
        if r is None:
            continue
        name = _unesc(tk.split(_SEP)[-1])
        tshape = tuple(getattr(tmpl, "shape", ()) or ())
        if (name.startswith(_PP) and tshape and
                tuple(r.shape) != tshape and
                int(np.prod(r.shape)) == int(np.prod(tshape)) and
                abs(len(r.shape) - len(tshape)) == 1):
            readers[tk] = _LeadLayoutReader(r, tshape)
    return readers


def _translate_pp(readers, tmpl_flat):
    """Reconcile __ppstack__ stacked leaves between checkpoint and
    template: synthesize missing readers in either direction (the
    reference's PP re-partitioning on load,
    fleet/utils/pp_parallel_adaptor.py)."""
    ck = set(readers)

    def parent_and_name(key):
        comps = key.split(_SEP)
        return _SEP.join(comps[:-1]), _unesc(comps[-1])

    def sibling_blocks(keys, parent, loc):
        """Keys under `parent` whose unescaped last component ends with
        '.'+loc but is not itself a stacked key, natural-sorted."""
        out = []
        for k in keys:
            par, name = parent_and_name(k)
            if par == parent and not name.startswith(_PP) and \
                    name.endswith("." + loc):
                out.append((k, name))
        out.sort(key=lambda kn: _natkey(kn[1]))
        return [k for k, _ in out]

    for tk in tmpl_flat:
        if tk in ck:
            continue
        parent, name = parent_and_name(tk)
        if name.startswith(_PP):
            # template wants stacked; checkpoint saved per-block
            loc = name[len(_PP):]
            blocks = sibling_blocks(ck, parent, loc)
            if blocks:
                readers[tk] = _StackedReader([readers[b] for b in blocks])
        else:
            # template wants per-block; checkpoint saved stacked
            for sk in list(ck):
                spar, sname = parent_and_name(sk)
                if spar == parent and sname.startswith(_PP) and \
                        name.endswith("." + sname[len(_PP):]):
                    loc = sname[len(_PP):]
                    order = sibling_blocks(tmpl_flat, parent, loc)
                    if tk in order:
                        base = readers[sk]
                        if base.shape[0] != len(order):
                            # interleaved [v, pp*Lv, ...] saved layout:
                            # view it flat before slicing block rows
                            base = _LeadLayoutReader(
                                base,
                                (len(order),) + tuple(base.shape[2:]))
                        readers[tk] = _RowReader(base, order.index(tk))
                    break
    return readers


def _target_spec(saved_spec, shape, mesh):
    """Adapt the SAVED PartitionSpec to the LOADING mesh: drop axes the
    new mesh lacks / sizes that no longer divide (the resharding rule,
    same policy as train_step._spec_for)."""
    if saved_spec is None:
        return P()
    axes = []
    for d, e in enumerate(saved_spec):
        names = (e,) if isinstance(e, str) else tuple(e or ())
        kept = tuple(a for a in names if a in mesh.shape
                     and mesh.shape[a] > 1)
        size = int(np.prod([mesh.shape[a] for a in kept])) if kept else 1
        if kept and d < len(shape) and shape[d] % size == 0:
            axes.append(kept if len(kept) > 1 else kept[0])
        else:
            axes.append(None)
    return P(*axes)


def load_sharded(path, mesh=None, shardings=None, template=None,
                 integrity="full", elastic=False):
    """Load a sharded checkpoint onto the current (possibly different)
    mesh.

    shardings: optional flat {leaf_key: NamedSharding} overrides.
    template: optional pytree (same structure as saved) whose arrays'
    shardings are reused — pass a freshly-built train-step ``state`` to
    restore into its exact placement.

    Before any array is constructed the checkpoint is verified
    (``integrity``: "full" = CRC32 + coverage, "size" = existence/size +
    coverage, "off" = COMMIT markers only); an uncommitted or corrupt
    checkpoint raises :class:`CheckpointCorruptError` naming the
    offending leaf/file instead of mmap-ing garbage into weights.

    ``elastic=True`` is the changed-world-size resume path: a checkpoint
    written by ``world_size=M`` (even one whose marker set is partial
    after losing hosts) is re-sharded onto the current run by stitching
    each leaf from the committed ranks' shard windows; an uncoverable
    leaf raises :class:`ReshardError` rather than zero-filling.

    Returns the restored pytree (nested dicts mirroring the saved tree).
    """
    mesh = mesh or _mesh_mod.get_mesh()
    index = _read_index(path, verify=True, integrity=integrity,
                        elastic=elastic)
    tmpl_flat = {}
    if template is not None:
        tmpl_flat = {_leaf_name(p): a for p, a in _flat_items(template)}

    readers = {leaf: _LeafReader(path, entry)
               for leaf, entry in index.items()}
    if template is not None:
        # reconcile pp-stacked vs per-block layouts between checkpoint
        # and template, then restore only what the template asks for
        readers = _translate_pp(readers, tmpl_flat)
        readers = {k: r for k, r in readers.items() if k in tmpl_flat}
        readers = _adapt_pp_layout(readers, tmpl_flat)

    flat_out = {}
    for leaf, reader in readers.items():
        shape = reader.shape
        saved_spec = index[leaf]["spec"] if leaf in index else None
        if shardings and leaf in shardings:
            sharding = shardings[leaf]
        elif leaf in tmpl_flat and isinstance(
                getattr(tmpl_flat[leaf], "sharding", None), NamedSharding):
            sharding = tmpl_flat[leaf].sharding
        else:
            sharding = NamedSharding(
                mesh, _target_spec(saved_spec, shape, mesh))
        arr = jax.make_array_from_callback(
            shape, sharding, lambda idx, r=reader: r.read(idx))
        flat_out[leaf] = arr
    if template is None:
        return _unflatten(flat_out)

    # rebuild following the TEMPLATE structure (preserves empty subtrees
    # like a buffer-less model's {}); checkpoint leaves win, template
    # leaves fill anything the checkpoint lacks
    def rebuild(node, path=()):
        if isinstance(node, dict):
            return {k: rebuild(v, path + (str(k),))
                    for k, v in node.items()}
        return flat_out.get(_leaf_name(path), node)

    return rebuild(template)


# -- whole-train-state convenience (fleet/hapi entry points) ---------------

def save_state(state, path):
    """Save a build_train_step ``state`` ({params, buffers, opt})."""
    save_sharded(state, path)


def load_state(path, state):
    """Restore a checkpoint INTO a freshly built train-step state (exact
    same placements, arbitrary saved mesh). Returns the new state."""
    return load_sharded(path, shardings=None, template=state)
