"""File-streaming datasets for PS-style training (ref:
``python/paddle/distributed/fleet/dataset/dataset.py`` — DatasetBase /
InMemoryDataset:351 / QueueDataset:1275 over the C++ MultiSlot data
feeds).

TPU-native: no C++ DataFeed pipeline — files stream through the
``pipe_command`` as a real subprocess (same contract as the reference:
the command reads raw file bytes on stdin and emits MultiSlot text),
lines parse into per-slot numpy arrays on the host, and the dataset
iterates dict batches ready for ``feed=``. The MultiSlot line format is
the reference's: for each slot in ``use_var`` order,
``<n> v1 ... vn``.
"""
from __future__ import annotations

import random
import subprocess

import numpy as np

__all__ = ["DatasetBase", "InMemoryDataset", "QueueDataset"]


class DatasetBase:
    """ref ``dataset.py:24``. ``init(**kwargs)`` keys mirrored:
    batch_size, thread_num, use_var (names or Variables), pipe_command,
    input_type, fs_name, fs_ugi, download_cmd."""

    def __init__(self):
        self.batch_size = 1
        self.thread_num = 1
        self.use_var = []
        self.pipe_command = "cat"
        self.input_type = 0
        self.fs_name = ""
        self.fs_ugi = ""
        self.download_cmd = "cat"
        self.filelist = []

    def init(self, batch_size=1, thread_num=1, use_var=None,
             pipe_command="cat", input_type=0, fs_name="", fs_ugi="",
             download_cmd="cat", **kwargs):
        self.batch_size = int(batch_size)
        self.thread_num = int(thread_num)
        self.use_var = list(use_var or [])
        self.pipe_command = pipe_command
        self.input_type = input_type
        self.fs_name = fs_name
        self.fs_ugi = fs_ugi
        self.download_cmd = download_cmd
        return self

    def set_filelist(self, filelist):
        self.filelist = list(filelist)

    # -- slot helpers -------------------------------------------------------
    def _slot_meta(self):
        """(name, np_dtype, fixed_len) per slot. A slot batches to a
        stacked (B, n) array iff its use_var DECLARES a static size
        (last dim of a concrete ``shape``); otherwise it is ragged and
        always yields a list — deciding per batch would flip the type
        whenever lengths coincide."""
        meta = []
        for v in self.use_var:
            name = getattr(v, "name", v)
            dt = str(getattr(v, "dtype", "float32"))
            np_dt = np.int64 if "int" in dt else np.float32
            fixed = None
            shape = getattr(v, "shape", None)
            if shape:
                last = shape[-1]
                if isinstance(last, int) and last > 0:
                    fixed = last
            meta.append((str(name), np_dt, fixed))
        return meta

    def _parse_line(self, line, meta):
        toks = line.split()
        rec, i = [], 0
        for name, dt, fixed in meta:
            if i >= len(toks):
                raise ValueError(
                    f"MultiSlot parse error: line ended before slot "
                    f"'{name}' ({line[:80]!r})")
            n = int(toks[i])
            vals = np.asarray(toks[i + 1:i + 1 + n], dtype=dt)
            if len(vals) != n:
                raise ValueError(
                    f"MultiSlot parse error: slot '{name}' declared {n} "
                    f"values, found {len(vals)}")
            if fixed is not None and n != fixed:
                raise ValueError(
                    f"MultiSlot parse error: slot '{name}' declares a "
                    f"static size {fixed} but a record carries {n} values")
            i += 1 + n
            rec.append(vals)
        if i != len(toks):
            raise ValueError(
                f"MultiSlot parse error: {len(toks) - i} trailing tokens "
                f"after the {len(meta)} declared slots — use_var is "
                f"missing a slot or lists slots in the wrong order")
        return rec

    def _stream_records(self):
        meta = self._slot_meta()
        for path in self.filelist:
            with open(path, "rb") as f:
                proc = subprocess.Popen(
                    self.pipe_command, shell=True, stdin=f,
                    stdout=subprocess.PIPE)
                try:
                    for raw in proc.stdout:
                        line = raw.decode().strip()
                        if line:
                            yield self._parse_line(line, meta)
                finally:
                    proc.stdout.close()
                    try:
                        rc = proc.wait(timeout=600.0)
                    except subprocess.TimeoutExpired:
                        # a preprocessor ignoring a closed stdout is
                        # wedged — kill it and fail the stream loudly
                        proc.kill()
                        rc = proc.wait(timeout=10.0)
                # a crashed preprocessor must fail loudly — silently
                # training on a truncated stream is the worst outcome
                if rc != 0:
                    raise RuntimeError(
                        f"pipe_command {self.pipe_command!r} exited with "
                        f"status {rc} on {path!r}")

    def _batches(self, records):
        meta = self._slot_meta()
        buf = []
        for rec in records:
            buf.append(rec)
            if len(buf) == self.batch_size:
                yield self._pack(buf, meta)
                buf = []
        if buf:
            yield self._pack(buf, meta)

    @staticmethod
    def _pack(buf, meta):
        out = {}
        for j, (name, _, fixed) in enumerate(meta):
            cols = [r[j] for r in buf]
            # declared-static slots stack to (B, n); undeclared slots
            # are ragged and ALWAYS a list, even when a batch's lengths
            # happen to coincide (a per-batch decision would flip the
            # yielded type under the consumer's feet)
            out[name] = np.stack(cols) if fixed is not None else cols
        return out

    def get_filelist(self):
        return list(self.filelist)


class QueueDataset(DatasetBase):
    """Streaming dataset: files -> pipe_command -> batches, one pass,
    nothing resident (ref ``dataset.py:1275``)."""

    def __iter__(self):
        return self._batches(self._stream_records())


class InMemoryDataset(DatasetBase):
    """Load-then-shuffle dataset (ref ``dataset.py:351``)."""

    def __init__(self):
        super().__init__()
        self._memory = None
        self._distributed_settings = {}

    def _init_distributed_settings(self, **kwargs):
        """Accepted for API parity (merge_size / parse_ins_id /
        fleet_send_* tune the reference's PS transport; iteration here
        is host-local)."""
        self._distributed_settings.update(kwargs)

    def update_settings(self, **kwargs):
        for k, v in kwargs.items():
            if hasattr(self, k):
                setattr(self, k, v)
            else:
                self._distributed_settings[k] = v

    def load_into_memory(self, is_shuffle=False):
        self._memory = list(self._stream_records())
        if is_shuffle:
            self.local_shuffle()

    def local_shuffle(self):
        if self._memory is None:
            raise RuntimeError("call load_into_memory() first")
        random.shuffle(self._memory)

    def global_shuffle(self, fleet=None, thread_num=12):
        """Single-host build: global == local (the reference's fleet
        send/recv shuffle redistributes across PS trainers)."""
        self.local_shuffle()

    def release_memory(self):
        self._memory = None

    def get_memory_data_size(self, fleet=None):
        return len(self._memory) if self._memory is not None else 0

    def get_shuffle_data_size(self, fleet=None):
        return self.get_memory_data_size(fleet)

    def __iter__(self):
        if self._memory is None:
            raise RuntimeError("call load_into_memory() first")
        return self._batches(iter(self._memory))
