"""The Fleet singleton (ref: ``fleet/fleet.py:99``)."""
from __future__ import annotations

import os

from ..env import get_rank, get_world_size
from ..parallel import init_parallel_env
from ..topology import CommunicateTopology, HybridCommunicateGroup
from .base.distributed_strategy import DistributedStrategy

__all__ = ["Fleet", "fleet", "init", "get_hybrid_communicate_group",
           "distributed_model", "distributed_optimizer"]

_HCG: HybridCommunicateGroup | None = None


class Fleet:
    """ref: ``fleet.py:99``. ``init`` builds the hybrid topology + global
    mesh (``fleet.py:371 _init_hybrid_parallel_env``)."""

    def __init__(self):
        self._is_initialized = False
        self._user_defined_strategy: DistributedStrategy | None = None
        self._hcg: HybridCommunicateGroup | None = None

    def init(self, role_maker=None, is_collective=True, strategy=None,
             log_level="INFO"):
        global _HCG
        if strategy is None:
            strategy = DistributedStrategy()
        self._user_defined_strategy = strategy
        # comm-overlap compiler flags must land before the backend spins
        # up; idempotent, env-gated, no-op off TPU (device/xla_flags.py)
        from ...device import enable_overlap_flags
        enable_overlap_flags()
        init_parallel_env()

        hc = strategy.hybrid_configs
        import jax
        world = get_world_size()
        if world <= 1:
            world = jax.device_count()
        dims = {"dp": hc.get("dp_degree", 1), "pp": hc.get("pp_degree", 1),
                "sharding": hc.get("sharding_degree", 1),
                "sep": hc.get("sep_degree", 1),
                "mp": hc.get("mp_degree", 1)}
        # infer dp if left at 1 and devices remain (ref fleet.py:373-377
        # requires the product to match; we auto-absorb into dp)
        prod = 1
        for v in dims.values():
            prod *= v
        if prod < world and world % prod == 0 and dims["dp"] == 1:
            dims["dp"] = world // prod
        topo = CommunicateTopology(
            hybrid_group_names=("data", "pipe", "sharding", "sep", "model"),
            dims=(dims["dp"], dims["pp"], dims["sharding"], dims["sep"],
                  dims["mp"]))
        self._hcg = HybridCommunicateGroup(topo)
        _HCG = self._hcg
        self._is_initialized = True
        return self

    @property
    def is_initialized(self):
        return self._is_initialized

    def get_hybrid_communicate_group(self) -> HybridCommunicateGroup:
        return self._hcg

    # -- role queries (ref fleet.py worker_* family) ----------------------
    def worker_index(self):
        return get_rank()

    def worker_num(self):
        return get_world_size()

    def is_first_worker(self):
        return get_rank() == 0

    def worker_endpoints(self, to_string=False):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
        eps = [e for e in eps if e]
        return ",".join(eps) if to_string else eps

    def is_worker(self):
        return True

    def is_server(self):
        return False

    def barrier_worker(self):
        from ..collective import barrier
        barrier()

    # -- model / optimizer wrapping ---------------------------------------
    def distributed_model(self, model):
        """ref: ``fleet/model.py:30`` — dispatch on parallel mode
        (``model.py:134-166``). Strategy toggles (amp / recompute) are
        applied here, like the Engine does — they must not be silent
        no-ops."""
        hcg = self._hcg
        if hcg is None:
            raise RuntimeError("call fleet.init() first")
        s = self._user_defined_strategy
        if s is not None:
            from .base.distributed_strategy import strategy_amp_setup
            autocast, _ = strategy_amp_setup(s, model)
            # fp16 O1: compiled paths (PipelineParallel) read this; eager
            # modes follow the user's own amp.auto_cast context like the
            # reference dygraph flow
            s._amp_autocast = autocast
            if getattr(s, "recompute", False):
                mcfg = getattr(model, "config", None)
                if mcfg is not None and hasattr(mcfg, "use_recompute"):
                    mcfg.use_recompute = True
        mode = hcg.get_parallel_mode()
        if mode == "pipeline":
            from .meta_parallel.pipeline_parallel import PipelineParallel
            return PipelineParallel(model, hcg,
                                    strategy=self._user_defined_strategy)
        if mode == "model":
            from .meta_parallel.tensor_parallel import TensorParallel
            return TensorParallel(model, hcg,
                                  strategy=self._user_defined_strategy)
        if mode == "sharding_parallel":
            from .meta_parallel.sharding_parallel import ShardingParallel
            return ShardingParallel(model, hcg,
                                    strategy=self._user_defined_strategy)
        from ..parallel import DataParallel
        return DataParallel(model,
                            group=hcg.get_data_parallel_group())

    def distributed_optimizer(self, optimizer, strategy=None):
        """ref: ``fleet.py:1044`` → HybridParallelOptimizer
        (``dygraph_optimizer/hybrid_parallel_optimizer.py:238``)."""
        if strategy is not None:
            self._user_defined_strategy = strategy
        s = self._user_defined_strategy
        if s is not None and getattr(s, "sharding", False):
            # ZeRO stage from the strategy: compiled train steps built
            # over this optimizer partition state over the sharding axis
            # (train_step._zero_level); stage 3 is applied model-side by
            # ShardingParallel
            stage = int(s.sharding_configs.get("stage", 1))
            level = {1: "os", 2: "os_g"}.get(stage)
            if level is not None:
                setattr(optimizer, "_group_sharded_level", level)
        from .meta_optimizers.hybrid_parallel_optimizer import \
            HybridParallelOptimizer
        return HybridParallelOptimizer(optimizer, self._hcg,
                                       self._user_defined_strategy)

    # -- save/load (ref fleet.py:829-1009) --------------------------------
    def save(self, path, **configs):
        from ...framework.io_state import save as _save
        _save(configs.get("program", {}), path)

    def save_persistables(self, executor, dirname, main_program=None,
                          mode=0):
        from ..io import save_persistables as _sp
        return _sp(executor, dirname, main_program)

    def save_sharded(self, state, path):
        """Distributed checkpoint of a build_train_step state: per-host
        shard files + index, reshardable on load (ref:
        ``auto_parallel/static/dist_saver.py``)."""
        from ..checkpoint import save_state
        save_state(state, path)

    def load_sharded(self, path, state):
        """Restore a sharded checkpoint into a freshly built train-step
        state — the saved mesh may differ (ref: ``converter.py``,
        ``pp_parallel_adaptor.py``)."""
        from ..checkpoint import load_state
        return load_state(path, state)


fleet = Fleet()


def init(role_maker=None, is_collective=True, strategy=None):
    return fleet.init(role_maker, is_collective, strategy)


def get_hybrid_communicate_group():
    return _HCG


def distributed_model(model):
    return fleet.distributed_model(model)


def distributed_optimizer(optimizer, strategy=None):
    return fleet.distributed_optimizer(optimizer, strategy)


def worker_num():
    return fleet.worker_num()


def worker_index():
    return fleet.worker_index()


def is_first_worker():
    return fleet.is_first_worker()


def worker_endpoints(to_string=False):
    return fleet.worker_endpoints(to_string)


def barrier_worker():
    return fleet.barrier_worker()
