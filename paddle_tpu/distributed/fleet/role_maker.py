"""Role makers (ref:
``python/paddle/distributed/fleet/base/role_maker.py``): who am I in
the job — trainer or server, which index, which endpoints. The
reference derives this from PaddleCloud env vars; the same env names
drive this build (``distributed/env.py`` uses them for rank/world)."""
from __future__ import annotations

import os

__all__ = ["Role", "PaddleCloudRoleMaker", "UserDefinedRoleMaker"]


class Role:
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4
    COORDINATOR = 5


class PaddleCloudRoleMaker:
    """Env-derived role (ref ``role_maker.py:546``): PADDLE_TRAINER_ID /
    PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS, plus the PS-era
    TRAINING_ROLE / PADDLE_PSERVERS_IP_PORT_LIST pair."""

    def __init__(self, is_collective=False, **kwargs):
        self._is_collective = is_collective
        self._kwargs = dict(kwargs)
        self._generate()

    def _generate(self):
        env = os.environ
        self._role = {"TRAINER": Role.WORKER, "PSERVER": Role.SERVER,
                      "HETER_TRAINER": Role.HETER_WORKER}.get(
            env.get("TRAINING_ROLE", "TRAINER"), Role.WORKER)
        self._current_id = int(env.get("PADDLE_TRAINER_ID", 0))
        self._worker_num = int(env.get("PADDLE_TRAINERS_NUM", 1))
        eps = env.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._worker_endpoints = [e for e in eps.split(",") if e]
        seps = env.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
        self._server_endpoints = [e for e in seps.split(",") if e]

    # -- reference surface -------------------------------------------------
    def _is_worker(self):
        return self._role == Role.WORKER

    def _is_server(self):
        return self._role == Role.SERVER

    is_worker = _is_worker
    is_server = _is_server

    def is_first_worker(self):
        return self._is_worker() and self._current_id == 0

    def worker_index(self):
        return self._current_id

    def server_index(self):
        return self._current_id if self._is_server() else -1

    def worker_num(self):
        return self._worker_num

    def server_num(self):
        return len(self._server_endpoints)

    def get_trainer_endpoints(self):
        return list(self._worker_endpoints)

    def get_pserver_endpoints(self):
        return list(self._server_endpoints)

    def role_id(self):
        return self._current_id

    def to_string(self):
        return (f"role={self._role} id={self._current_id} "
                f"workers={self._worker_num} "
                f"worker_endpoints={self._worker_endpoints} "
                f"server_endpoints={self._server_endpoints}")


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    """Explicit role description (ref ``role_maker.py:1182``):
    ``current_id`` / ``role`` / ``worker_num`` / ``server_endpoints``
    passed directly instead of read from the environment."""

    def __init__(self, is_collective=False, init_gloo=False, **kwargs):
        self._init_kwargs = dict(kwargs)
        super().__init__(is_collective=is_collective, **kwargs)

    def _generate(self):
        kw = self._init_kwargs
        self._role = kw.get("role", Role.WORKER)
        self._current_id = int(kw.get("current_id", 0))
        self._worker_num = int(kw.get("worker_num", 1))
        self._worker_endpoints = list(kw.get("worker_endpoints", []))
        self._server_endpoints = list(kw.get("server_endpoints", []))
