"""Fleet util (ref:
``python/paddle/distributed/fleet/base/util_factory.py:49 UtilBase``):
job-level helpers — collective reductions over worker scalars, file
sharding across workers, rank-scoped printing."""
from __future__ import annotations

import os

import numpy as np

__all__ = ["UtilBase"]


class UtilBase:
    def __init__(self, role_maker=None):
        self.role_maker = role_maker

    def _worker(self):
        from .fleet import worker_index, worker_num
        if self.role_maker is not None:
            return (self.role_maker.worker_index(),
                    self.role_maker.worker_num())
        return worker_index(), worker_num()

    # -- collectives over host scalars (ref util_factory all_reduce) -------
    def all_reduce(self, input, mode="sum", comm_world="worker"):
        from ..collective import all_reduce as _ar, ReduceOp
        from ...tensor import Tensor
        op = {"sum": ReduceOp.SUM, "max": ReduceOp.MAX,
              "min": ReduceOp.MIN}[mode]
        t = Tensor(np.asarray(input))
        _ar(t, op=op)
        return np.asarray(t._data)

    def all_gather(self, input, comm_world="worker"):
        from ..collective import all_gather_object
        return all_gather_object(input)

    def barrier(self, comm_world="worker"):
        from ..collective import barrier
        barrier()

    # -- file sharding (ref util_factory get_file_shard) -------------------
    def get_file_shard(self, files):
        """Split ``files`` contiguously across workers; earlier workers
        take the remainder (the reference's blocking split)."""
        if not isinstance(files, list):
            raise TypeError("files should be a list of file need to be read.")
        idx, n = self._worker()
        per, rem = divmod(len(files), n)
        begin = idx * per + min(idx, rem)
        return files[begin:begin + per + (1 if idx < rem else 0)]

    def print_on_rank(self, message, rank_id):
        idx, _ = self._worker()
        if idx == rank_id:
            # rank-scoped console printing IS this helper's contract
            print(message)  # tpu-lint: disable=TPU010
