"""HybridParallelOptimizer (ref:
``fleet/meta_parallel/../dygraph_optimizer/hybrid_parallel_optimizer.py:238``
and ``HybridParallelClipGrad :49``).

The reference's job: (a) clip by GLOBAL norm across tp/pp shards — each
rank only holds slices, so the squared norms must be all-reduced across the
mp/pp/sharding groups before clipping; (b) fuse the dp allreduce of shared
params. Under the single-controller mesh both problems vanish: every
parameter is one logical array, so the inner optimizer's
ClipGradByGlobalNorm already IS the hybrid-correct global norm, and grad
reduction is compiled in. What remains is API parity + sharding-aware
state placement.
"""
from __future__ import annotations

from ....optimizer.optimizer import Optimizer

__all__ = ["HybridParallelOptimizer", "HybridParallelClipGrad"]


class HybridParallelClipGrad:
    """Kept for API parity: delegates to the wrapped clip — the global
    norm is already global on a single logical mesh (ref :49 computes it
    with explicit mp/pp/sharding all-reduces)."""

    def __init__(self, clip, hcg=None):
        self._clip = clip
        self._hcg = hcg

    def __call__(self, params_grads):
        return self._clip(params_grads) if self._clip is not None \
            else params_grads


class HybridParallelOptimizer:
    def __init__(self, optimizer: Optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        if optimizer._grad_clip is not None:
            optimizer._grad_clip = HybridParallelClipGrad(
                optimizer._grad_clip, hcg)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, set_to_zero=False):
        self._inner_opt.clear_grad(set_to_zero=set_to_zero) \
            if hasattr(self._inner_opt, "clear_grad") else None

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()
        return [], []

    def set_lr(self, value):
        self._inner_opt.set_lr(value)

    def get_lr(self):
        return self._inner_opt.get_lr()

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, state):
        return self._inner_opt.set_state_dict(state)

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)
