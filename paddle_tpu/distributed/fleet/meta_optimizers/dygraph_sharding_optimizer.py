"""DygraphShardingOptimizer — ZeRO stage-1 (ref:
``meta_optimizers/dygraph_optimizer/dygraph_sharding_optimizer.py:29``).

The reference partitions the param list across the sharding group by
greedy size balancing (``_partition_parameters``), each rank updates its
slice, then broadcasts. TPU-native: optimizer STATE arrays inherit the
parameter's fsdp ``PartitionSpec`` (annotated by
``annotate_fsdp_specs``), so XLA stores each state shard on its owner
and the update runs shard-local — same memory win, no broadcast step.
This class keeps the reference's greedy partition (used by save/load
re-partitioning tools) and delegates the actual step to the inner opt.
"""
from __future__ import annotations

import numpy as np

__all__ = ["DygraphShardingOptimizer"]


class DygraphShardingOptimizer:
    def __init__(self, optimizer=None, hcg=None, user_defined_strategy=None,
                 params=None, inner_optimizer_class=None, **inner_kw):
        # reference signature: (hcg, user_defined_strategy, params,
        # inner_optimizer_class, **kw); also accept a built optimizer
        if optimizer is not None and inner_optimizer_class is None:
            self._inner_opt = optimizer
            self._parameter_list = optimizer._parameter_list
        else:
            self._parameter_list = list(params)
            self._inner_opt = inner_optimizer_class(
                parameters=self._parameter_list, **inner_kw)
        self._hcg = hcg
        n = (hcg.get_sharding_parallel_world_size()
             if hcg is not None else 1)
        self._rank2params = self._partition_parameters(max(n, 1))
        # compiled train steps built over this optimizer partition the
        # state tree over the `sharding` axis (train_step._zero_level)
        setattr(self._inner_opt, "_group_sharded_level", "os")

    def _partition_parameters(self, n):
        """Greedy size-balanced assignment (ref :66)."""
        mapping = {i: [] for i in range(n)}
        sizes = [0.0] * n
        for p in sorted(self._parameter_list, key=lambda p: -p.size):
            i = int(np.argmin(sizes))
            mapping[i].append(p)
            sizes[i] += p.size
        return mapping

    def step(self):
        self._inner_opt.step()

    def clear_grad(self):
        for p in self._parameter_list:
            p.clear_grad()

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        self.clear_grad()
        return [], []

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)
