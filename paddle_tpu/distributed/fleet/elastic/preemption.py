"""Preemption / SIGTERM checkpoint hook.

SURVEY §5 designates TPU preemption handling as the equivalent of the
reference's elastic fault tolerance (``fleet/elastic/manager.py:124``):
cloud TPU VMs receive SIGTERM ahead of maintenance/preemption. This module
installs a handler that saves a (sharded) checkpoint and exits, so the
relaunched job resumes via ``distributed.checkpoint.load_state`` (or
``CheckpointManager.restore_latest``).

Exit codes are the operator's only signal from a preempted worker, so
they are disjoint: ``exit_code`` (default 143 = 128+SIGTERM) means
"checkpoint saved, clean preemption exit"; ``error_exit_code`` (default
75, EX_TEMPFAIL) means "the preemption save FAILED — the relaunch will
resume from an older checkpoint".  A second signal while the save is
still running force-exits immediately via ``os._exit`` (the platform is
about to SIGKILL anyway; a wedged save must not block the exit).
"""
from __future__ import annotations

import logging
import os
import signal
import sys
import threading

from ...exit_codes import EXIT_DRAIN, EXIT_TEMPFAIL

__all__ = ["on_preemption", "clear_preemption_handler",
           "SAVE_FAILED_EXIT_CODE"]

logger = logging.getLogger(__name__)

#: default exit code when save_fn raises (EX_TEMPFAIL: retry-able — the
#: relaunched job falls back to the previous committed checkpoint);
#: canonical taxonomy: distributed/exit_codes.py
SAVE_FAILED_EXIT_CODE = EXIT_TEMPFAIL

_state = threading.local()
_installed: dict[int, object] = {}


def on_preemption(save_fn, signals=(signal.SIGTERM,), exit_code=EXIT_DRAIN,
                  exit=True, error_exit_code=SAVE_FAILED_EXIT_CODE):
    """Install ``save_fn()`` as the preemption handler.

    save_fn runs once, in the main thread, when any of ``signals``
    arrives; the process then exits with ``exit_code`` (Unix convention
    128+SIGTERM) unless ``exit=False`` (then the previous disposition is
    NOT re-raised — the caller owns shutdown).  If ``save_fn`` raises,
    the failure is logged and the process exits with ``error_exit_code``
    instead, so operators can tell "saved then exited" from "save
    failed" without grepping logs.  A repeated signal force-exits with
    ``exit_code`` via ``os._exit`` even mid-save.

    Typical use::

        eng = Engine(model, loss, opt)
        on_preemption(lambda: eng.save(ckpt_dir))
    """
    done = threading.Event()

    def handler(signum, frame):
        if done.is_set():  # double signal: force exit
            os._exit(exit_code)
        done.set()
        try:
            save_fn()
        except BaseException:
            # without this, `finally: sys.exit(exit_code)` would both
            # swallow the save failure and report a clean preemption
            logger.exception(
                "preemption save_fn failed (signal %s); exiting %d "
                "instead of %d — relaunch resumes from the previous "
                "committed checkpoint", signum, error_exit_code, exit_code)
            if exit:
                sys.exit(error_exit_code)
            raise
        if exit:
            sys.exit(exit_code)

    for sig in signals:
        prev = signal.signal(sig, handler)
        # remember only the ORIGINAL disposition: re-installing must not
        # make clear_preemption_handler restore a stale save handler
        _installed.setdefault(sig, prev)
    return handler


def clear_preemption_handler():
    """Restore the dispositions replaced by :func:`on_preemption`."""
    for sig, prev in _installed.items():
        try:
            signal.signal(sig, prev)
        except (ValueError, OSError, TypeError):
            # ValueError: not the main thread / bad signal number;
            # restoring the rest still matters more than raising here
            pass
    _installed.clear()
