"""Preemption / SIGTERM checkpoint hook.

SURVEY §5 designates TPU preemption handling as the equivalent of the
reference's elastic fault tolerance (``fleet/elastic/manager.py:124``):
cloud TPU VMs receive SIGTERM ahead of maintenance/preemption. This module
installs a handler that saves a (sharded) checkpoint and exits, so the
relaunched job resumes via ``distributed.checkpoint.load_state``.
"""
from __future__ import annotations

import os
import signal
import sys
import threading

__all__ = ["on_preemption", "clear_preemption_handler"]

_state = threading.local()
_installed: dict[int, object] = {}


def on_preemption(save_fn, signals=(signal.SIGTERM,), exit_code=143,
                  exit=True):
    """Install ``save_fn()`` as the preemption handler.

    save_fn runs once, in the main thread, when any of ``signals``
    arrives; the process then exits with ``exit_code`` (Unix convention
    128+SIGTERM) unless ``exit=False`` (then the previous disposition is
    NOT re-raised — the caller owns shutdown).

    Typical use::

        eng = Engine(model, loss, opt)
        on_preemption(lambda: eng.save(ckpt_dir))
    """
    done = threading.Event()

    def handler(signum, frame):
        if done.is_set():  # double signal: force exit
            os._exit(exit_code)
        done.set()
        try:
            save_fn()
        finally:
            if exit:
                sys.exit(exit_code)

    for sig in signals:
        prev = signal.signal(sig, handler)
        # remember only the ORIGINAL disposition: re-installing must not
        # make clear_preemption_handler restore a stale save handler
        _installed.setdefault(sig, prev)
    return handler


def clear_preemption_handler():
    """Restore the dispositions replaced by :func:`on_preemption`."""
    for sig, prev in _installed.items():
        try:
            signal.signal(sig, prev)
        except (ValueError, OSError, TypeError):
            # ValueError: not the main thread / bad signal number;
            # restoring the rest still matters more than raising here
            pass
    _installed.clear()
