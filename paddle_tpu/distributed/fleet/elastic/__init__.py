"""``paddle.distributed.fleet.elastic`` — fault tolerance / elastic scaling.

TPU-native re-design of the reference ElasticManager
(``python/paddle/distributed/fleet/elastic/manager.py:124``): nodes
register heartbeats in a coordination store and a watcher detects
join/leave, recomputes the rank map (``_match`` ``manager.py:417``) and
restarts local trainers (``LauncherInterface`` ``manager.py:54``).

Mapping: etcd leases → the native-core :class:`~paddle_tpu.core.TCPStore`
(heartbeat keys with timestamps; rank-0 hosts the store). On TPU pods the
restart story is "rebuild the mesh from the surviving hosts and resume
from the latest checkpoint" — a dead chip kills its jax client, so
in-run self-healing is process-level, exactly like the reference's
NCCL-abort-then-relaunch model.
"""
from .manager import ElasticManager, ElasticStatus, LauncherInterface  # noqa: F401
from .preemption import (  # noqa: F401
    on_preemption, clear_preemption_handler, SAVE_FAILED_EXIT_CODE,
)

__all__ = ["ElasticManager", "ElasticStatus", "LauncherInterface",
           "on_preemption", "clear_preemption_handler",
           "SAVE_FAILED_EXIT_CODE"]
