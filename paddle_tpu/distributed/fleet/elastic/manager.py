"""Elastic manager over the native TCPStore (see package docstring)."""
from __future__ import annotations

import enum
import json
import logging
import os
import signal
import subprocess
import threading
import time

from ....observability import get_telemetry
from ....utils.retry import retry_call, wait_until

__all__ = ["ElasticManager", "ElasticStatus", "LauncherInterface"]

logger = logging.getLogger(__name__)

_PREFIX = "elastic/nodes/"


class ElasticStatus(enum.Enum):
    COMPLETED = 0
    ERROR = 1
    HOLD = 2        # waiting for np in [np_min, np_max]
    RESTART = 3     # membership changed; relaunch
    EXIT = 4


class LauncherInterface:
    """Local trainer process control (ref ``manager.py:54``)."""

    def __init__(self, args):
        self.args = list(args)
        self.proc = None

    def launch(self, extra_env=None):
        env = dict(os.environ)
        env.update(extra_env or {})
        self.proc = subprocess.Popen(self.args, env=env)
        return self.proc

    def stop(self, timeout=10.0):
        if self.proc is None or self.proc.poll() is not None:
            return
        self.proc.send_signal(signal.SIGTERM)
        try:
            self.proc.wait(timeout)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait(timeout)

    def watch(self):
        """Returns exit code or None while running."""
        return None if self.proc is None else self.proc.poll()


class ElasticManager:
    """Heartbeat + membership watcher.

    Args mirror the reference: ``np`` may be "min:max" for elastic range.
    ``store`` is a connected :class:`paddle_tpu.core.TCPStore` (master on
    rank-0's host) — or a
    :class:`~paddle_tpu.distributed.resilient_store.ResilientStore` for
    store-failover tolerance: heartbeats then ride the reconnect path
    across a master SIGKILL/respawn, and a reconnect that lands within
    the lease TTL costs the node nothing (the respawned durable master
    replays the slot keys, and the next ``_beat`` refreshes the lease
    before peers evict it).  Size the client's ``deadline`` BELOW
    ``lease_ttl`` so a beat either lands in time or fails loudly
    (``StoreUnavailableError`` is a ``ConnectionError``, so the
    heartbeat loop's existing error path and ``register``'s retries
    already handle it).
    """

    def __init__(self, store, host, np="1", heartbeat_interval=1.0,
                 lease_ttl=5.0):
        self.store = store
        self.host = host
        if isinstance(np, str) and ":" in np:
            lo, hi = np.split(":")
            self.np_min, self.np_max = int(lo), int(hi)
        else:
            self.np_min = self.np_max = int(np)
        self.interval = heartbeat_interval
        self.ttl = lease_ttl
        self._stop = threading.Event()
        self._membership_changed = threading.Event()
        self._last_members: list[str] = []
        self._hb_thread = None
        self._watch_thread = None

    # -- heartbeats ---------------------------------------------------------
    def _beat(self):
        self.store.set(_PREFIX + self.host,
                       json.dumps({"ts": time.time()}))
        get_telemetry().heartbeat(ok=True, lease_ttl=self.ttl)

    def _heartbeat_loop(self):
        while not self._stop.is_set():
            try:
                self._beat()
            except Exception as e:
                # a silent dead heartbeat gets this node evicted by its
                # peers with nothing in the log to explain why
                get_telemetry().heartbeat(ok=False, lease_ttl=self.ttl)
                logger.warning("elastic heartbeat to store failed "
                               "(node %s): %s", self.host, e)
            self._stop.wait(self.interval)

    def alive_nodes(self):
        """Hosts whose lease has not expired. Membership is enumerated via
        atomically-allocated slot keys (see ``register``) — there is no
        shared read-modify-write, so concurrent joins cannot lose members."""
        now = time.time()
        n = self.store.add("elastic/nslots", 0)
        nodes, seen = [], set()
        for slot in range(1, n + 1):
            h = self.store.get(f"elastic/slot/{slot}", wait=False)
            if not h:
                continue
            h = h.decode()
            if h in seen:
                continue
            seen.add(h)
            v = self.store.get(_PREFIX + h, wait=False)
            if not v:
                continue
            ts = json.loads(v).get("ts", 0)
            if now - ts <= self.ttl:
                nodes.append(h)
        return sorted(nodes)

    def register(self):
        """Join membership (atomic slot allocation) and start
        heartbeating. A rejoining host gets a fresh slot; dead slots age
        out via the heartbeat lease. The registration store ops retry
        with backoff (bounded by one lease TTL): right after a mass
        restart the store may still be coming up, and a node that gives
        up on its first try never rejoins."""
        slot = retry_call(self.store.add, "elastic/nslots", 1,
                          retry_on=(ConnectionError, TimeoutError, OSError),
                          deadline=self.ttl, base=0.05)
        retry_call(self.store.set, f"elastic/slot/{slot}", self.host,
                   retry_on=(ConnectionError, TimeoutError, OSError),
                   deadline=self.ttl, base=0.05)
        self._slot = slot
        retry_call(self._beat,
                   retry_on=(ConnectionError, TimeoutError, OSError),
                   deadline=self.ttl, base=0.05)
        self._hb_thread = threading.Thread(target=self._heartbeat_loop,
                                           daemon=True)
        self._hb_thread.start()

    # -- membership ---------------------------------------------------------
    def match(self):
        """Recompute the rank map (ref ``_match`` ``manager.py:417``):
        returns (ok, hosts, rank_of_self). ok is True when the alive count
        is inside [np_min, np_max]."""
        hosts = self.alive_nodes()
        ok = self.np_min <= len(hosts) <= self.np_max
        rank = hosts.index(self.host) if self.host in hosts else -1
        return ok, hosts, rank

    def _watch_loop(self):
        while not self._stop.is_set():
            hosts = self.alive_nodes()
            if self._last_members and hosts != self._last_members:
                self._membership_changed.set()
            self._last_members = hosts
            self._stop.wait(self.interval)

    def watch(self, timeout=None):
        """Block until membership changes (ref ``watch`` ``manager.py:604``);
        returns ELASTIC status."""
        if self._watch_thread is None:
            self._last_members = self.alive_nodes()
            self._watch_thread = threading.Thread(target=self._watch_loop,
                                                  daemon=True)
            self._watch_thread.start()
        changed = self._membership_changed.wait(timeout)
        if not changed:
            return ElasticStatus.COMPLETED
        self._membership_changed.clear()
        ok, hosts, _ = self.match()
        return ElasticStatus.RESTART if ok else ElasticStatus.HOLD

    def wait_for_np(self, timeout=60.0):
        """Hold until the alive count enters [np_min, np_max] — jittered
        backoff polling so a whole restarted fleet doesn't hammer the
        store in lockstep."""
        def _ready():
            ok, hosts, rank = self.match()
            return (hosts, rank) if ok else None

        try:
            return wait_until(
                _ready, timeout, base=self.interval / 4, factor=1.5,
                max_delay=self.interval,
                desc=f"np in [{self.np_min},{self.np_max}]")
        except TimeoutError:
            raise TimeoutError(
                f"elastic: np stayed outside [{self.np_min},{self.np_max}]"
                f" for {timeout}s (alive={self.alive_nodes()})")

    def supervise(self, make_launcher, max_restarts=5, poll=0.25,
                  hold_timeout=60.0):
        """Drive this node's local trainer under elastic membership
        (ref ``manager.py`` main loop: watch ``:604`` → re-match ``:417``
        → relaunch via ``LauncherInterface :54``).

        make_launcher(hosts, rank) -> LauncherInterface for the CURRENT
        rank map; called again after every membership change or trainer
        death. Returns ElasticStatus.COMPLETED when the trainer exits 0,
        ERROR when the restart budget is exhausted.
        """
        hosts, rank = self.wait_for_np(hold_timeout)
        launcher = make_launcher(hosts, rank)
        launcher.launch()
        restarts = 0
        # arm the membership watcher
        self.watch(timeout=0)
        while True:
            rc = launcher.watch()
            if rc == 0:
                return ElasticStatus.COMPLETED
            relaunch = False
            if rc is not None:
                relaunch = True      # local trainer died
            else:
                status = self.watch(timeout=poll)
                if status in (ElasticStatus.RESTART, ElasticStatus.HOLD):
                    relaunch = True  # peers joined/left: rank map changed
            if relaunch:
                if restarts >= max_restarts:
                    launcher.stop()
                    return ElasticStatus.ERROR
                restarts += 1
                launcher.stop()
                hosts, rank = self.wait_for_np(hold_timeout)
                launcher = make_launcher(hosts, rank)
                launcher.launch()

    def exit(self):
        self._stop.set()
        # deregister: clear own slot + heartbeat (both are per-node keys)
        try:
            if getattr(self, "_slot", None) is not None:
                self.store.delete(f"elastic/slot/{self._slot}")
            self.store.delete(_PREFIX + self.host)
        except Exception as e:
            # best-effort on teardown (the lease expires anyway), but a
            # swallowed store error here would also hide a dead store
            logger.debug("elastic deregister failed for %s: %s",
                         self.host, e)
