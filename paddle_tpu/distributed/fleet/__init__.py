"""Fleet: the distributed-training facade.

ref: ``python/paddle/distributed/fleet/fleet.py:99`` (Fleet), ``fleet.py:167
init``, ``:371 _init_hybrid_parallel_env``, ``model.py:30
distributed_model``, ``fleet.py:1044 distributed_optimizer``.
"""
from .base.distributed_strategy import DistributedStrategy  # noqa: F401
from .fleet import (  # noqa: F401
    Fleet, fleet, init, get_hybrid_communicate_group, distributed_model,
    distributed_optimizer, worker_num, worker_index, is_first_worker,
    worker_endpoints, barrier_worker,
)
from ..topology import HybridCommunicateGroup, CommunicateTopology  # noqa: F401
from . import recompute as _recompute_mod  # noqa: F401
from .recompute import (  # noqa: F401
    recompute, recompute_hybrid, recompute_sequential,
)
from . import utils  # noqa: F401
from .role_maker import (  # noqa: F401
    PaddleCloudRoleMaker, Role, UserDefinedRoleMaker,
)
from .data_generator import (  # noqa: F401
    DataGenerator, MultiSlotDataGenerator, MultiSlotStringDataGenerator,
)
from .util import UtilBase  # noqa: F401
from . import meta_parallel  # noqa: F401
