"""Fleet data generators (ref:
``python/paddle/distributed/fleet/data_generator/data_generator.py``):
the PRODUCER side of the MultiSlot pipe contract — a generator script
reads raw lines on stdin and writes ``<n> v1 ... vn`` slot text on
stdout, which :class:`~paddle_tpu.distributed.fleet.dataset
.QueueDataset`'s ``pipe_command`` consumes."""
from __future__ import annotations

import sys

__all__ = ["DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]


class DataGenerator:
    def __init__(self):
        self.batch_size_ = 32

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    # -- user hooks --------------------------------------------------------
    def generate_sample(self, line):
        """Return a local_iter() yielding (slot_name, values) tuples for
        one raw input line (ref ``data_generator.py:171``)."""
        raise NotImplementedError(
            "Please rewrite this function to return a list or tuple: " +
            "[(name, [feasign, ...]), ...] or ((name, [feasign, ...]), ...)")

    def generate_batch(self, samples):
        """Optional batch-level rewrite (ref ``:205``); defaults to
        yielding each sample unchanged."""
        def local_iter():
            for sample in samples:
                yield sample
        return local_iter

    # -- drivers -----------------------------------------------------------
    def _run(self, lines, out=None):
        out = out or sys.stdout
        batch = []

        def flush(batch):
            for sample in self.generate_batch(batch)():
                out.write(self._gen_str(sample))

        for line in lines:
            it = self.generate_sample(line)
            for parsed in it():
                if parsed is None:
                    continue
                batch.append(parsed)
                if len(batch) == self.batch_size_:
                    flush(batch)
                    batch = []
        if batch:
            flush(batch)

    def run_from_memory(self):
        self._run([None])

    def run_from_stdin(self):
        self._run(sys.stdin)

    def _gen_str(self, line):
        raise NotImplementedError(
            "Please inherit MultiSlotDataGenerator or "
            "MultiSlotStringDataGenerator to implement _gen_str")


class MultiSlotStringDataGenerator(DataGenerator):
    """Values are already strings (ref ``data_generator.py:239``)."""

    def _gen_str(self, line):
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "the output of process() must be in list or tuple type")
        out = ""
        for name, elements in line:
            out += str(len(elements)) + " " + " ".join(elements) + " "
        return out.strip() + "\n"


class MultiSlotDataGenerator(DataGenerator):
    """Values are ints/floats, validated (ref ``:284``)."""

    def _gen_str(self, line):
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "the output of process() must be in list or tuple type")
        out = ""
        for name, elements in line:
            if not elements:
                raise ValueError(
                    f"the elements of slot {name} are empty")
            out += str(len(elements)) + " " + " ".join(
                str(x) for x in elements) + " "
        return out.strip() + "\n"
