"""DistributedStrategy: the feature-toggle config tree.

ref: ``python/paddle/distributed/fleet/base/distributed_strategy.py`` backed
by ``paddle/fluid/framework/distributed_strategy.proto``. The TPU build
replaces the protobuf with a plain typed attribute tree (SURVEY §5 config
stance: one typed config + env overrides); the attribute NAMES match the
reference so user strategy code ports unchanged. Toggles that are NCCL
mechanics with no XLA meaning are accepted and ignored — with two
exceptions made meaningful by the overlap layer (PR 10):
``fuse_all_reduce_ops``/``fuse_grad_size_in_MB`` drive the bucketed
gradient reduction (``distributed/grad_buckets.py``) and
``pipeline_configs["overlap_p2p_comm"]`` the double-buffered 1F1B hop
(``meta_parallel/pp_spmd.py``). :func:`strategy_overlap_setup` is the
one translation point.
"""
from __future__ import annotations

__all__ = ["DistributedStrategy"]

_HYBRID_DEFAULTS = {
    "dp_degree": 1, "mp_degree": 1, "pp_degree": 1, "sharding_degree": 1,
    "sep_degree": 1, "order": ["dp", "pp", "sharding", "sep", "mp"],
}


class DistributedStrategy:
    def __init__(self):
        # collective / hybrid
        self.hybrid_configs = dict(_HYBRID_DEFAULTS)
        # AMP
        self.amp = False
        self.amp_configs = {
            "init_loss_scaling": 32768.0, "incr_every_n_steps": 1000,
            "decr_every_n_nan_or_inf": 2, "incr_ratio": 2.0,
            "decr_ratio": 0.5, "use_dynamic_loss_scaling": True,
            "custom_white_list": [], "custom_black_list": [],
            "use_pure_fp16": False, "use_fp16_guard": True,
            "use_bf16": True,
        }
        # recompute
        self.recompute = False
        self.recompute_configs = {"checkpoints": [], "enable_offload": False}
        # sharding (ZeRO). comm_overlap (ref group_sharded knob of the
        # same name) enables the mesh-aware collective-schedule pass —
        # reduce-scatter bucketing on dp×sharding meshes; the
        # PT_COLLECTIVE_SCHEDULE env kill switch wins over it
        self.sharding = False
        self.sharding_configs = {"stage": 1, "degree": 8,
                                 "offload": False,
                                 "comm_overlap": True}
        # pipeline
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1,
                                 "micro_batch_size": 1,
                                 "schedule_mode": "1F1B",
                                 "virtual_pp_degree": 1,
                                 # double-buffered ring hop (pp_spmd
                                 # overlap); None = PT_PP_OVERLAP env
                                 "overlap_p2p_comm": None}
        # gradient merge
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        # grad-fusion knobs — MEANINGFUL since PR 10: size target of the
        # bucketed dp gradient reduction (PT_GRAD_BUCKET_MB env wins)
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        # misc toggles kept for parity (no-ops under XLA)
        self.nccl_comm_num = 1
        self.sync_nccl_allreduce = False
        self.find_unused_parameters = False
        self.gradient_scale_configs = {"scale_strategy": "avg"}
        self.tensor_parallel = False
        self.tensor_parallel_configs = {"tensor_parallel_degree": 1}
        self.lamb = False
        self.lars = False
        self.dgc = False
        self.a_sync = False
        self.heter_ccl_mode = False
        self.without_graph_optimization = True

    def __setattr__(self, key, value):
        # dict-valued configs merge over defaults like the reference's
        # check_configs_key (unknown keys rejected)
        cur = self.__dict__.get(key)
        if isinstance(cur, dict) and isinstance(value, dict):
            unknown = set(value) - set(cur)
            if unknown:
                raise ValueError(f"unknown {key} keys: {sorted(unknown)}")
            cur.update(value)
        else:
            object.__setattr__(self, key, value)

    def __repr__(self):
        rows = [f"  {k}={v!r}" for k, v in sorted(self.__dict__.items())]
        return "DistributedStrategy(\n" + "\n".join(rows) + "\n)"


def strategy_overlap_setup(strategy):
    """Translate the strategy's comm-overlap knobs for
    ``build_train_step``: returns ``(grad_bucket_mb, pipeline_overlap,
    collective_schedule)``.

    ``grad_bucket_mb``: the bucketed-reduction size target —
    ``fuse_grad_size_in_MB`` when ``fuse_all_reduce_ops`` is on, else 0
    (disabled). ``pipeline_overlap``:
    ``pipeline_configs["overlap_p2p_comm"]`` (None defers to the
    ``PT_PP_OVERLAP`` env default inside ``pp_spmd``).
    ``collective_schedule``: ``sharding_configs["comm_overlap"]`` — the
    mesh-aware collective-schedule pass enable (ZeRO reduce-scatter
    bucketing; the ``PT_COLLECTIVE_SCHEDULE`` env kill switch wins).
    """
    if strategy is None:
        return None, None, None
    bucket_mb = (getattr(strategy, "fuse_grad_size_in_MB", None)
                 if getattr(strategy, "fuse_all_reduce_ops", True) else 0)
    overlap = getattr(strategy, "pipeline_configs",
                      {}).get("overlap_p2p_comm")
    schedule = getattr(strategy, "sharding_configs",
                       {}).get("comm_overlap", True)
    return bucket_mb, overlap, schedule


def strategy_amp_setup(strategy, model=None):
    """Apply ``strategy.amp``/``amp_configs`` and return
    ``(autocast_factory, scaler)`` — the ONE place the strategy's AMP
    semantics live (used by the auto-parallel Engine and the fleet
    facade, so neither can silently no-op a toggle).

    - bf16 or pure fp16 (O2): ``model``'s params are cast in place.
    - fp16 O1: returns an autocast factory for ``build_train_step`` —
      white-list ops cast at trace time.
    - dynamic loss scaling on: returns a GradScaler built from the
      configs.
    """
    if not getattr(strategy, "amp", False):
        return None, None
    from .... import amp as _amp
    cfg = strategy.amp_configs
    dtype = "bfloat16" if cfg.get("use_bf16", True) else "float16"
    autocast = None
    if cfg.get("use_pure_fp16", False) or dtype == "bfloat16":
        if model is not None:
            _amp.decorate(model, level="O2", dtype=dtype)
    else:
        def autocast():
            return _amp.auto_cast(enable=True, level="O1", dtype=dtype)
    scaler = None
    if cfg.get("use_dynamic_loss_scaling", True):
        scaler = _amp.GradScaler(
            init_loss_scaling=cfg.get("init_loss_scaling", 2.0 ** 15),
            incr_ratio=cfg.get("incr_ratio", 2.0),
            decr_ratio=cfg.get("decr_ratio", 0.5),
            incr_every_n_steps=cfg.get("incr_every_n_steps", 1000),
            decr_every_n_nan_or_inf=cfg.get("decr_every_n_nan_or_inf", 2))
    return autocast, scaler
