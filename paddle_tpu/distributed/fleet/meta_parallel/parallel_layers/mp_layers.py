"""Tensor-parallel layers.

ref: ``python/paddle/distributed/fleet/layers/mpu/mp_layers.py``
(``VocabParallelEmbedding :35``, ``ColumnParallelLinear :173``,
``RowParallelLinear :343``, ``ParallelCrossEntropy :524``).

TPU-native design — two execution modes from ONE layer:

 - **GSPMD mode (default)**: the layer holds the FULL logical weight with a
   ``PartitionSpec`` annotation (``Tensor._spec``); forward is plain math
   plus ``with_sharding_constraint`` hints. Under ``jit`` over the global
   mesh, XLA partitions the weight over the ``mp`` axis and inserts the
   same collectives Megatron does by hand — this replaces the reference's
   explicit ``_c_identity/_mp_allreduce`` wiring.
 - **Manual-SPMD mode**: when traced inside ``shard_map`` with the ``mp``
   axis in scope (per-rank weight blocks), forward uses the explicit
   ``mp_ops`` custom-vjp collectives — bit-for-bit the reference's
   comm placement, used by the pipeline schedule and tests.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .....tensor import Tensor
from .....nn.layer.layers import Layer
from .....nn import initializer as I
from .... import mesh as _mesh_mod
from ....collective import _in_axis_scope
from .. import mp_ops

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy"]

_MP = "mp"


def _layout():
    # parameter specs come from the canonical layout table (lazy: the
    # auto_parallel package imports the engine, which imports fleet)
    from ....auto_parallel.spec_layout import default_layout
    return default_layout()


def _mp_degree(mp_group):
    if mp_group is not None:
        return mp_group.nranks
    return _mesh_mod.mesh_axis_size(_MP)


def _constraint(arr, spec):
    """Sharding hint under jit when a global mesh exists; no-op eager.
    Skipped inside an old-jax compat shard_map body: there every mesh
    axis is manual and a named constraint fails at LOWERING time, past
    any trace-time exception guard."""
    from ...._jax_compat import in_compat_manual_region
    mesh = _mesh_mod.get_mesh(create_default=False)
    if mesh is None or not isinstance(arr, jax.core.Tracer) \
            or in_compat_manual_region():
        return arr
    try:
        return lax.with_sharding_constraint(arr, NamedSharding(mesh, spec))
    except Exception:
        return arr


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim split over mp (ref: mp_layers.py:35)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.mp_group = mp_group
        self.world_size = _mp_degree(mp_group)
        if num_embeddings % max(self.world_size, 1):
            raise ValueError(
                f"vocab {num_embeddings} not divisible by mp degree "
                f"{self.world_size}")
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight._spec = _layout().vocab_embedding()
        self.weight.is_distributed = self.world_size > 1

    def forward(self, x):
        ax = self.mp_group.axis_name if self.mp_group else _MP
        idx = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        w = self.weight._data
        if _in_axis_scope(ax):
            # manual mode: w is the local vocab block
            n = self.world_size
            per = w.shape[0]
            i = lax.axis_index(ax)
            start = i * per
            mask = (idx >= start) & (idx < start + per)
            local = jnp.clip(idx - start, 0, per - 1)
            out = jnp.where(mask[..., None], jnp.take(w, local, axis=0), 0.0)
            out_t = Tensor(out, stop_gradient=False)
            return mp_ops._mp_allreduce(out_t, self.mp_group)
        # GSPMD mode: full gather; XLA partitions the table over mp
        from .....nn import functional as F
        out = F.embedding(x if isinstance(x, Tensor) else Tensor(x),
                          self.weight)
        out._data = _constraint(out._data, P())
        return out


class ColumnParallelLinear(Layer):
    """Linear with the OUT dim split over mp (ref: mp_layers.py:173).
    Forward comm: identity (f op); backward: all-reduce of input grad."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.mp_group = mp_group
        self.world_size = _mp_degree(mp_group)
        if out_features % max(self.world_size, 1):
            raise ValueError(
                f"out_features {out_features} not divisible by mp degree "
                f"{self.world_size}")
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr)
        self.weight._spec = _layout().column_weight()
        self.weight.is_distributed = self.world_size > 1
        self.bias = self.create_parameter(
            [out_features], attr=has_bias if has_bias is not True else None,
            is_bias=True) if has_bias else None
        if self.bias is not None:
            self.bias._spec = _layout().column_bias()
            self.bias.is_distributed = self.world_size > 1

    def forward(self, x):
        ax = self.mp_group.axis_name if self.mp_group else _MP
        if _in_axis_scope(ax):
            x = mp_ops._c_identity(x, self.mp_group)
            a = x._data if isinstance(x, Tensor) else x
            y = a @ self.weight._data
            if self.bias is not None:
                y = y + self.bias._data
            out = Tensor(y, stop_gradient=False)
            if self.gather_output:
                out = mp_ops._c_concat(out, self.mp_group)
            return out
        from .....nn import functional as F
        out = F.linear(x if isinstance(x, Tensor) else Tensor(x),
                       self.weight, self.bias)
        out._data = _constraint(
            out._data, P() if self.gather_output
            else P(*([None] * (out.ndim - 1) + [_MP])))
        return out


class RowParallelLinear(Layer):
    """Linear with the IN dim split over mp (ref: mp_layers.py:343).
    Forward comm: all-reduce of partial sums (g op)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.mp_group = mp_group
        self.world_size = _mp_degree(mp_group)
        if in_features % max(self.world_size, 1):
            raise ValueError(
                f"in_features {in_features} not divisible by mp degree "
                f"{self.world_size}")
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr)
        self.weight._spec = _layout().row_weight()
        self.weight.is_distributed = self.world_size > 1
        # bias is replicated, added AFTER the reduce (ref :411)
        self.bias = self.create_parameter(
            [out_features], attr=has_bias if has_bias is not True else None,
            is_bias=True) if has_bias else None

    def forward(self, x):
        ax = self.mp_group.axis_name if self.mp_group else _MP
        if _in_axis_scope(ax):
            if not self.input_is_parallel:
                x = mp_ops._c_split(x, self.mp_group)
            a = x._data if isinstance(x, Tensor) else x
            y = a @ self.weight._data
            out = mp_ops._mp_allreduce(Tensor(y, stop_gradient=False),
                                       self.mp_group)
            if self.bias is not None:
                out = Tensor(out._data + self.bias._data,
                             stop_gradient=False)
            return out
        from .....nn import functional as F
        xt = x if isinstance(x, Tensor) else Tensor(x)
        xt._data = _constraint(xt._data,
                               P(*([None] * (xt.ndim - 1) + [_MP])))
        out = F.linear(xt, self.weight, self.bias)
        out._data = _constraint(out._data, P())
        return out


class ParallelCrossEntropy(Layer):
    """Softmax cross-entropy over vocab-sharded logits (ref:
    mp_layers.py:524 → ``c_softmax_with_cross_entropy`` op). Never
    materializes the gathered [tokens, vocab] logits — max and sum-exp are
    reduced across mp with ``pmax``/``psum``; the target logit is fetched
    with a masked psum."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.mp_group = mp_group
        self.world_size = _mp_degree(mp_group)
        self.ignore_index = ignore_index

    def forward(self, input, label):
        ax = self.mp_group.axis_name if self.mp_group else _MP
        logits = input._data if isinstance(input, Tensor) else input
        y = label._data if isinstance(label, Tensor) else jnp.asarray(label)
        if y.ndim == logits.ndim:  # [.., 1] form like the reference
            y = y.squeeze(-1)
        valid = y != self.ignore_index
        y_safe = jnp.where(valid, y, 0)
        if _in_axis_scope(ax):
            n_local = logits.shape[-1]
            i = lax.axis_index(ax)
            start = i * n_local
            m = lax.pmax(jnp.max(logits, axis=-1), ax)
            shifted = logits - m[..., None]
            sumexp = lax.psum(jnp.sum(jnp.exp(shifted), axis=-1), ax)
            in_range = (y_safe >= start) & (y_safe < start + n_local)
            local_y = jnp.clip(y_safe - start, 0, n_local - 1)
            tgt = jnp.take_along_axis(shifted, local_y[..., None],
                                      axis=-1)[..., 0]
            tgt = lax.psum(jnp.where(in_range, tgt, 0.0), ax)
            loss = jnp.where(valid, jnp.log(sumexp) - tgt, 0.0)
            return Tensor(loss[..., None], stop_gradient=False)
        # GSPMD mode: plain CE on the tape; XLA keeps the logits sharded
        from .....ops.op_utils import nary

        ignore = self.ignore_index

        def ce(lg, yy):
            ok = yy != ignore
            yy_safe = jnp.where(ok, yy, 0)
            m = jnp.max(lg, axis=-1, keepdims=True)
            shifted = lg - jax.lax.stop_gradient(m)
            lse = jnp.log(jnp.sum(jnp.exp(shifted), axis=-1))
            tgt = jnp.take_along_axis(shifted, yy_safe[..., None],
                                      axis=-1)[..., 0]
            return jnp.where(ok, lse - tgt, 0.0)[..., None]

        return nary(ce, [input if isinstance(input, Tensor)
                         else Tensor(input), Tensor(y)],
                    name="parallel_cross_entropy")
