"""Pipeline layer partitioning.

ref: ``python/paddle/distributed/fleet/meta_parallel/parallel_layers/
pp_layers.py`` (``PipelineLayer :239``, ``LayerDesc``, ``SharedLayerDesc``,
virtual stages :249).

TPU-native stance: the reference materializes ONLY this rank's stage
layers (each process owns a stage); in single-controller JAX ALL stages are
built, and the pipeline schedule (``pipeline_parallel.py``) places each
stage's parameters on its ``pp`` mesh slice — stacking homogeneous stage
blocks so the 1F1B loop runs as ONE ``shard_map`` program with
``ppermute`` hops instead of NCCL p2p.
"""
from __future__ import annotations

import math

from .....nn.layer.layers import Layer
from .....nn.layer.container import Sequential, LayerList

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer"]


class LayerDesc:
    """Deferred layer constructor (ref: pp_layers.py LayerDesc)."""

    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, Layer):
            raise TypeError("LayerDesc expects a Layer subclass")

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    """Layer shared between stages, e.g. tied embeddings (ref:
    pp_layers.py SharedLayerDesc). Single-controller: sharing is literal
    object identity — no grad-sync group needed (the compiled backward sums
    both uses' gradients naturally)."""

    def __init__(self, key, layer_func, forward_func=None,
                 shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """ref: pp_layers.py:239. Accepts a list of Layer / LayerDesc, a
    partition policy, and exposes per-stage segments.

    seg_method: "uniform" or "layer:<ClassName>" (balance by count of that
    layer class, the reference's transformer-block policy).
    """

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 recompute_ctx=None, num_virtual_pipeline_stages=None):
        super().__init__()
        self._loss_fn = loss_fn
        self._topo = topology
        self._recompute_interval = recompute_interval
        if num_stages is None:
            if topology is not None:
                num_stages = topology.get_dim("pipe")
            else:
                from .... import mesh as _mesh_mod
                num_stages = _mesh_mod.mesh_axis_size("pp")
        self._num_stages = max(int(num_stages), 1)

        self.descs = list(layers)
        built = []
        self._shared = {}
        for d in self.descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    layer = self._shared[d.layer_name]
                else:
                    layer = d.build_layer()
                    self._shared[d.layer_name] = layer
                built.append((layer, d.forward_func))
            elif isinstance(d, LayerDesc):
                built.append((d.build_layer(), None))
            elif isinstance(d, Layer):
                built.append((d, None))
            elif callable(d):
                built.append((d, None))
            else:
                raise TypeError(f"cannot build pipeline item {d!r}")
        self._items = built
        self.run_function = [l for l, _ in built]
        # register as sublayers for state_dict
        self._layer_list = LayerList([l for l, _ in built
                                      if isinstance(l, Layer)])
        self._segment(seg_method)

    # -- partitioning (ref pp_layers.py _segment_network) ------------------
    def _segment(self, seg_method):
        n = len(self._items)
        stages = self._num_stages
        if seg_method.startswith("layer:"):
            cls_name = seg_method.split(":", 1)[1]
            marks = [i for i, (l, _) in enumerate(self._items)
                     if type(l).__name__ == cls_name]
            if not marks:
                raise ValueError(f"no layer of class {cls_name} found")
            per = math.ceil(len(marks) / stages)
            bounds = [0]
            for s in range(1, stages):
                k = s * per
                bounds.append(marks[k] if k < len(marks) else n)
            bounds.append(n)
        else:
            per = math.ceil(n / stages)
            bounds = [min(i * per, n) for i in range(stages)] + [n]
        self.segment_parts = bounds

    def get_stage_from_index(self, layer_idx):
        for s in range(self._num_stages):
            if self.segment_parts[s] <= layer_idx < self.segment_parts[s + 1]:
                return s
        return self._num_stages - 1

    def stage_layers(self, stage_id):
        lo, hi = self.segment_parts[stage_id], self.segment_parts[stage_id + 1]
        return self._items[lo:hi]

    @property
    def num_stages(self):
        return self._num_stages

    # -- compiled-pipeline adapter (consumed by build_train_step) ----------
    def _homogeneous_run(self):
        """Longest run of same-class Layer items (the pipelineable block
        stack); returns (start, end) item indices or None."""
        best = None
        i, n = 0, len(self._items)
        while i < n:
            l0, f0 = self._items[i]
            if not isinstance(l0, Layer) or f0 is not None:
                i += 1
                continue
            j = i + 1
            while j < n:
                lj, fj = self._items[j]
                if not (isinstance(lj, Layer) and fj is None and
                        type(lj) is type(l0)):
                    break
                j += 1
            if best is None or j - i > best[1] - best[0]:
                best = (i, j)
            i = j
        if best is not None and best[1] - best[0] >= 2:
            return best
        return None

    def _layerlist_index(self, item_idx):
        """Item index -> index within _layer_list (Layers only)."""
        return sum(1 for l, _ in self._items[:item_idx]
                   if isinstance(l, Layer))

    def pipeline_blocks(self):
        """build_train_step adapter: the homogeneous block run's parameter
        prefixes + a representative block layer."""
        run = self._homogeneous_run()
        if run is None:
            raise ValueError("no homogeneous block run to pipeline")
        lo, hi = run
        j0 = self._layerlist_index(lo)
        prefixes = [f"_layer_list.{j0 + k}." for k in range(hi - lo)]
        return prefixes, self._items[lo][0]

    def forward(self, x):
        """Run ALL stages sequentially (the semantics oracle). When a
        pipeline executor scope is active (compiled train step on a pp
        mesh), the homogeneous block run executes as the compiled SPMD
        schedule instead."""
        from ...recompute import recompute as _recompute
        from ..pp_spmd import current_pipeline_executor
        pexec = current_pipeline_executor()
        run = self._homogeneous_run() if pexec is not None else None

        def call_item(v, layer, fwd_fn):
            if fwd_fn is not None:
                return fwd_fn(layer, v)
            return layer(v)

        out = x
        i, n = 0, len(self._items)
        while i < n:
            if run is not None and i == run[0]:
                out = pexec(out)
                i = run[1]
                continue
            layer, fwd_fn = self._items[i]
            if self._recompute_interval and \
                    i % self._recompute_interval == 0 and \
                    isinstance(layer, Layer):
                out = _recompute(lambda v, _l=layer, _f=fwd_fn:
                                 call_item(v, _l, _f), out)
            else:
                out = call_item(out, layer, fwd_fn)
            i += 1
        return out
