"""ShardingParallel wrapper (ref:
``fleet/meta_parallel/sharding_parallel.py``): ZeRO-style parameter /
optimizer-state sharding. Under XLA this is an axis annotation, not a
runtime protocol — parameters get ``PartitionSpec`` specs over the
``sharding`` mesh axis on their largest divisible dim (the fsdp recipe),
and the optimizer state inherits them. See also
``paddle_tpu.distributed.sharding.group_sharded_parallel``.
"""
from __future__ import annotations

import jax
from jax.sharding import PartitionSpec as P

from ....nn.layer.layers import Layer
from ... import mesh as _mesh_mod

__all__ = ["ShardingParallel", "annotate_fsdp_specs"]


def annotate_fsdp_specs(layer: Layer, axis="sharding", min_size=1024):
    """Give every parameter a spec sharding its largest dim divisible by
    the axis size (keeping any existing mp spec on other dims).

    Placement delegates to the canonical layout engine's
    ``place_axis`` — the same rule ``zero_spec`` uses for optimizer
    state, so param and state shards always align.
    """
    from ...auto_parallel.spec_layout import place_axis
    n = _mesh_mod.mesh_axis_size(axis)
    if n <= 1:
        return layer
    for _, p in layer.named_parameters():
        if p.size < min_size:
            continue
        spec = p._spec if p._spec is not None else P(*([None] * p.ndim))
        p._spec = place_axis(spec, tuple(p.shape), n, axis)
    return layer


class ShardingParallel(Layer):
    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        annotate_fsdp_specs(layers)
        from .tensor_parallel import place_parameters_on_mesh
        place_parameters_on_mesh(layers)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, *args, **kwargs):
        return self._layers.named_parameters(*args, **kwargs)
