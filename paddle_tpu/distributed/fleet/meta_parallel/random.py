"""TP dropout RNG (ref: ``fleet/meta_parallel/parallel_layers/random.py``).

The reference keeps one CUDA Philox state per (rank, region) so dropout
masks differ across mp ranks inside partitioned regions. TPU-native: one
functional tracker (``paddle_tpu.framework.random.RNGStatesTracker``);
rank decorrelation comes from folding the mp axis index into the key at
mesh-aware call sites — pure data flow, no device state.
"""
from __future__ import annotations

from ....framework.random import RNGStatesTracker, get_tracker

__all__ = ["get_rng_state_tracker", "model_parallel_random_seed",
           "RNGStatesTracker"]

MODEL_PARALLEL_RNG = "model_parallel_rng"


def get_rng_state_tracker() -> RNGStatesTracker:
    return get_tracker()


def model_parallel_random_seed(seed=None):
    """ref: random.py model_parallel_random_seed — derive decorrelated
    global/local seeds and register tracker states."""
    import random as pyrandom
    from ...env import get_rank
    if seed is None:
        seed = pyrandom.randint(0, 2 ** 31 - 1)
    global_seed = seed
    local_seed = seed + 1024 + get_rank()
    tracker = get_tracker()
    tracker.reset()
    tracker.add("global_seed", global_seed)
    tracker.add(MODEL_PARALLEL_RNG, local_seed)
