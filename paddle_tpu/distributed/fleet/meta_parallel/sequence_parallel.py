"""Sequence / context parallelism — a NEW first-class capability.

The reference snapshot has no SP/CP at all (SURVEY §5: no
sequence_parallel / ring_attention / ulysses symbol anywhere); its
long-context story is flash-attn + recompute + PP/TP.  Here the sequence
axis is a real mesh axis (``sep`` in the hybrid mesh,
paddle_tpu.distributed.mesh.HYBRID_AXES) and attention over sequences
larger than one chip's HBM is computed two ways:

* **Ring attention** (`ring_attention`): K/V shards rotate around the
  ``sep`` ring via ``lax.ppermute`` (compiled to ICI neighbor DMA);
  per-step partial softmax stats (out, lse) merge online, so no device
  ever materializes the full sequence — O(S/n) memory, exact result.
  Each step is wrapped in ``jax.checkpoint`` so backward recomputes the
  per-step attention instead of saving n partial score matrices.

* **Ulysses / all-to-all** (`ulysses_attention`): all_to_all swaps the
  sequence shard for a head shard, runs dense local attention over the
  full sequence on H/n heads, and swaps back.  Cheaper at moderate S
  when H divides nicely; the classic DeepSpeed-Ulysses layout.

All functions operate on raw (B, H, S_local, D) arrays *inside*
shard_map/jit over a mesh with the given axis; `RingFlashAttention` is
the Layer-facing wrapper taking paddle-layout (B, S, H, D) Tensors.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..._jax_compat import axis_size as _axis_size

__all__ = ["ring_attention", "ulysses_attention", "split_sequence",
           "gather_sequence", "RingFlashAttention"]


def _partial_attn(q, k, v, scale, mask):
    """Partial softmax attention vs one kv block → (out, lse) in f32.

    Fully-masked rows yield lse=-inf and out=0, which the online merge
    treats as a zero-weight contribution.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    s = jnp.where(mask, s, -jnp.inf)
    m = jnp.max(s, axis=-1)
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1)
    lse = jnp.where(l > 0, jnp.log(jnp.maximum(l, 1e-38)) + m_safe,
                    -jnp.inf)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    denom = jnp.where(l > 0, l, 1.0)
    return out / denom[..., None], lse


def _merge(o1, lse1, o2, lse2):
    """Merge two partial-softmax results (flash-attention combine)."""
    m = jnp.maximum(lse1, lse2)
    m = jnp.where(jnp.isfinite(m), m, 0.0)
    w1 = jnp.exp(lse1 - m)
    w2 = jnp.exp(lse2 - m)
    tot = w1 + w2
    lse = jnp.where(tot > 0, jnp.log(jnp.maximum(tot, 1e-38)) + m, -jnp.inf)
    safe = jnp.where(tot > 0, tot, 1.0)
    o = (o1 * w1[..., None] + o2 * w2[..., None]) / safe[..., None]
    return o, lse


def ring_attention(q, k, v, axis_name="sep", causal=False, sm_scale=None,
                   use_kernel=None, interpret=None):
    """Exact attention over a sequence sharded on ``axis_name``.

    Args are local shards (B, H, S_local, D) inside shard_map. Returns
    the local (B, H, S_local, D) output shard.

    ``use_kernel=True`` computes each ring step's partial attention with
    the Pallas flash kernel (``ops.pallas_ops.mha``) instead of the XLA
    O(S_local^2) softmax: the kernel's traced ``causal_shift`` encodes
    the per-step (my_rank - src_rank) * S_local diagonal offset, and its
    differentiable lse output feeds the online merge. Default: kernel on
    TPU backends, XLA elsewhere.
    """
    n = _axis_size(axis_name)
    r = lax.axis_index(axis_name)
    b, h, sl, d = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / (d ** 0.5)
    perm = [(i, (i + 1) % n) for i in range(n)]
    if use_kernel is None:
        use_kernel = jax.default_backend() == "tpu"

    qpos = r * sl + lax.broadcasted_iota(jnp.int32, (sl, 1), 0)

    @functools.partial(jax.checkpoint, static_argnums=())
    def step_attn(q, kk, vv, src):
        if use_kernel:
            from ....ops.pallas_ops import mha
            o, lse = mha(q, kk, vv, causal=causal, sm_scale=scale,
                         causal_shift=(r - src) * sl if causal else None,
                         return_lse=True, interpret=interpret)
            return o.astype(jnp.float32), lse
        kpos = src * sl + lax.broadcasted_iota(jnp.int32, (1, sl), 1)
        if causal:
            mask = kpos <= qpos  # (sl, sl) global causal mask
        else:
            mask = jnp.ones((sl, sl), dtype=bool)
        return _partial_attn(q, kk, vv, scale, mask[None, None])

    def body(carry, _):
        o, lse, kk, vv, src = carry
        o2, lse2 = step_attn(q, kk, vv, src)
        o, lse = _merge(o, lse, o2, lse2)
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        src = ((src + n - 1) % n).astype(jnp.int32)
        return (o, lse, kk, vv, src), None

    o0 = jnp.zeros((b, h, sl, d), jnp.float32)
    lse0 = jnp.full((b, h, sl), -jnp.inf, jnp.float32)
    # the merged carries become device-varying after step 1; mark the
    # initial values as varying over the ring axis so scan's carry type
    # is stable (jax vma tracking)
    if hasattr(lax, "pcast"):
        o0 = lax.pcast(o0, (axis_name,), to="varying")
        lse0 = lax.pcast(lse0, (axis_name,), to="varying")
    (o, lse, _, _, _), _ = lax.scan(
        body, (o0, lse0, k, v, r.astype(jnp.int32)), None, length=n)
    return o.astype(q.dtype)


def ulysses_attention(q, k, v, axis_name="sep", causal=False, sm_scale=None,
                      attn_fn=None, use_kernel=None, interpret=None):
    """DeepSpeed-Ulysses: all_to_all seq-shard ↔ head-shard, dense local
    attention on H/n heads over the full sequence, all_to_all back.

    Local shards (B, H, S_local, D); H must be divisible by the axis
    size.
    """
    n = _axis_size(axis_name)
    b, h, sl, d = q.shape
    if h % n:
        raise ValueError(f"heads {h} not divisible by sep degree {n}")

    def to_heads(x):  # (B,H,Sl,D) -> (B,H/n,S,D)
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def to_seq(x):  # (B,H/n,S,D) -> (B,H,Sl,D)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    if attn_fn is None:
        scale = sm_scale if sm_scale is not None else 1.0 / (d ** 0.5)
        if use_kernel is None:
            # same gate as SDPA: below flash_min_seq XLA's fused
            # attention is measured faster than the kernel
            from ....framework import flags as _flags
            full_seq = sl * n
            use_kernel = (jax.default_backend() == "tpu"
                          and full_seq >= int(_flags.flag("flash_min_seq")))
        if use_kernel:
            # dense attention over the FULL sequence with H/n heads —
            # exactly the flash kernel's O(S) sweet spot at long context
            from ....ops.pallas_ops import mha
            out = mha(qh, kh, vh, causal=causal, sm_scale=scale,
                      interpret=interpret)
        else:
            s = qh.shape[2]
            if causal:
                qi = lax.broadcasted_iota(jnp.int32, (s, s), 0)
                ki = lax.broadcasted_iota(jnp.int32, (s, s), 1)
                mask = (ki <= qi)[None, None]
            else:
                mask = jnp.ones((1, 1, s, s), dtype=bool)
            out, _ = _partial_attn(qh, kh, vh, scale, mask)
            out = out.astype(q.dtype)
    else:
        out = attn_fn(qh, kh, vh)
    return to_seq(out)


def split_sequence(x, axis_name="sep", axis=1):
    """Scatter a replicated tensor's sequence axis across the sep ring
    (the `_c_split` analog on the sequence dimension)."""
    n = _axis_size(axis_name)
    i = lax.axis_index(axis_name)
    sl = x.shape[axis] // n
    return lax.dynamic_slice_in_dim(x, i * sl, sl, axis=axis)


def gather_sequence(x, axis_name="sep", axis=1):
    """All-gather sequence shards back to the full sequence."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=True)


class RingFlashAttention:
    """Layer-facing wrapper: paddle layout (B, S_local, H, D) Tensors in
    eager/GSPMD mode, routing to `ring_attention` when executing inside
    a shard_map scope with a live ``sep`` axis, else plain attention.
    """

    def __init__(self, axis_name="sep", causal=True):
        self.axis_name = axis_name
        self.causal = causal

    def __call__(self, q, k, v):
        from ....ops.op_utils import ensure_tensor, nary
        q, k, v = ensure_tensor(q), ensure_tensor(k), ensure_tensor(v)
        ax = self.axis_name
        causal = self.causal

        def in_scope():
            try:
                _axis_size(ax)
                return True
            except NameError:
                return False

        if in_scope():
            def f(qd, kd, vd):
                o = ring_attention(jnp.swapaxes(qd, 1, 2),
                                   jnp.swapaxes(kd, 1, 2),
                                   jnp.swapaxes(vd, 1, 2),
                                   axis_name=ax, causal=causal)
                return jnp.swapaxes(o, 1, 2)
        else:
            from ....nn import functional as F
            return F.scaled_dot_product_attention(q, k, v,
                                                  is_causal=causal)
        return nary(f, [q, k, v], name="ring_flash_attention")
