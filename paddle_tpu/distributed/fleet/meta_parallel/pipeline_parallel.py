"""Pipeline-parallel execution.

ref: ``python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py``
(``PipelineParallel :124``, 1F1B schedule ``forward_backward_pipeline
:372``, interleaved ``:807``) and the P2P layer
(``pp_utils/p2p_communication.py:302``).

TPU-native mapping: the reference's host-driven 1F1B of NCCL sends/recvs
becomes ONE compiled program. When the ``pp`` mesh axis is >1 and the
stage stack is homogeneous, ``train_batch`` runs the compiled SPMD
pipeline (``pp_spmd.pipeline_spmd`` via
``distributed.train_step.build_train_step``): stacked stage parameters
sharded over ``pp``, the micro-batch tick loop inside ``lax.scan`` with
``ppermute`` hops — the ICI-native 1F1B. Otherwise the schedule degrades
to sequential micro-batch accumulation (identical numerics: pipelining
changes time, not math).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ....tensor import Tensor
from ....nn.layer.layers import Layer
from .parallel_layers.pp_layers import PipelineLayer
from .pp_spmd import PP_STACK_PREFIX, natural_stack

__all__ = ["PipelineParallel"]


class PipelineParallel(Layer):
    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError(
                "PipelineParallel expects a PipelineLayer (ref: "
                "pipeline_parallel.py:128)")
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        pcfg = (strategy.pipeline_configs
                if strategy is not None else {"accumulate_steps": 1})
        self.accumulate_steps = pcfg.get("accumulate_steps", 1)
        self.micro_batch_size = pcfg.get("micro_batch_size", None)
        # interleaved virtual stages (ref pipeline_parallel.py:807)
        self.virtual_pp_degree = pcfg.get("virtual_pp_degree", 1)
        self.total_loss = None
        # compiled-pipeline cache (built lazily on a pp>1 mesh)
        self._pp_step = None
        self._pp_state = None
        self._pp_optimizer = None
        self._pp_dirty = False

    # -- reference API surface --------------------------------------------
    def forward(self, x):
        return self._layers(x)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """ref: pipeline_parallel.py:572 train_batch → 1F1B schedule.

        data: (inputs, labels). Returns the averaged loss tensor.

        On a mesh with ``pp > 1`` and a homogeneous stage stack this runs
        the compiled SPMD 1F1B (one XLA program; stage params stacked and
        sharded over ``pp``); otherwise sequential micro-batch
        accumulation on the eager tape.
        """
        if self._layers._loss_fn is None:
            raise ValueError("train_batch requires PipelineLayer(loss_fn=..)")
        inputs, labels = data
        if self._pp_mesh_degree() > 1:
            # dynamic loss scaling compiles INTO the pipelined step (ref
            # runs its 1F1B with the scaler too,
            # ``hybrid_parallel_gradscaler.py``) — no silent degrade to
            # the sequential schedule for AMP users
            loss = self._compiled_train_batch(inputs, labels, optimizer,
                                              scaler)
            if loss is not None:
                if lr_scheduler is not None:
                    lr_scheduler.step()
                return loss
            # sequential fallback (e.g. a ragged last batch) trains the
            # LAYER tensors: land any compiled state into them first and
            # drop the compiled cache so the next compiled batch rebuilds
            # from the (about to be updated) layers instead of resuming a
            # stale _pp_state
            self._sync_state_to_layers()
            self._pp_step = None
            self._pp_state = None
            self._pp_optimizer = None
        micro_inputs = self._split_micro(inputs)
        micro_labels = self._split_micro(labels)
        n = len(micro_inputs)

        total = None
        for x, y in zip(micro_inputs, micro_labels):
            out = self._layers(x)
            loss = self._layers._loss_fn(out, y)
            if scaler is not None:
                scaled = scaler.scale(loss / n)
                scaled.backward()
            else:
                (loss / n).backward()
            total = loss.detach() if total is None else total + loss.detach()

        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        self.total_loss = total / n
        return self.total_loss

    def eval_batch(self, data, compute_loss=True):
        inputs, labels = data
        out = self._layers(inputs)
        if compute_loss and self._layers._loss_fn is not None:
            return self._layers._loss_fn(out, labels)
        return out

    def forward_backward_pipeline(self, data, optimizer, scaler=None):
        return self.train_batch(data, optimizer, scaler=scaler)

    # -- compiled SPMD path ------------------------------------------------
    def _pp_mesh_degree(self):
        from ... import mesh as _mesh_mod
        return _mesh_mod.mesh_axis_size("pp")

    def _compiled_train_batch(self, inputs, labels, optimizer, scaler=None):
        """Build (once) + run the compiled pipelined step. Returns the
        loss Tensor, or None when the stack cannot be pipelined (falls
        back to the sequential schedule — same math, no pipelining)."""
        from ...train_step import build_train_step, pipeline_compatible
        n_micro = max(self.accumulate_steps, self._pp_mesh_degree())
        batch = (inputs._data.shape[0] if isinstance(inputs, Tensor)
                 else np.asarray(inputs).shape[0])
        if batch % n_micro:
            return None  # sequential fallback handles ragged batches
        cached = self._pp_step is not None and \
            self._pp_optimizer is optimizer and \
            getattr(self, "_pp_scaler", None) is scaler
        v = max(int(self.virtual_pp_degree), 1)
        if not cached:
            # the compatibility scan is O(params) — only on (re)build
            pp = self._pp_mesh_degree()
            if not pipeline_compatible(self._layers, pp):
                return None
            if v > 1 and not pipeline_compatible(self._layers, pp * v):
                v = 1  # blocks don't divide pp*v: plain (non-interleaved)
            # a prior compiled state must land in the layer tensors
            # BEFORE rebuild re-extracts them (optimizer swap mid-run)
            self._sync_state_to_layers()
            from ..base.distributed_strategy import strategy_overlap_setup
            bucket_mb, pp_overlap, coll_sched = strategy_overlap_setup(
                self._strategy)
            self._pp_step, self._pp_state = build_train_step(
                self._layers, self._layers._loss_fn, optimizer,
                pipeline_microbatches=n_micro, scaler=scaler,
                pipeline_virtual_stages=v,
                autocast=getattr(self._strategy, "_amp_autocast", None),
                grad_bucket_mb=bucket_mb, pipeline_overlap=pp_overlap,
                collective_schedule=coll_sched)
            self._pp_optimizer = optimizer
            self._pp_scaler = scaler
        loss, self._pp_state = self._pp_step(self._pp_state, inputs, labels)
        self._pp_dirty = True
        ss = self._pp_state.get("scaler")
        if ss is not None and scaler is not None:
            # mirror device scaler state back (lazy jax scalars, no sync)
            scaler._scale = ss["scale"]
            scaler._good_steps = ss["good"]
            scaler._bad_steps = ss["bad"]
            scaler._found_inf = ss["found_inf"]
        return Tensor(loss)

    def _sync_state_to_layers(self):
        """Write compiled state (params, buffers, optimizer slots) back
        into the layer/optimizer objects — unstacking the pp-stacked
        blocks — so state_dict()s are current."""
        if not getattr(self, "_pp_dirty", False):
            return
        prefixes, _ = self._layers.pipeline_blocks()
        named = dict(self._layers.named_parameters())

        def for_each(k, v, apply):
            """apply(tensor, array) for the (possibly stacked) entry."""
            if k.startswith(PP_STACK_PREFIX):
                loc = k[len(PP_STACK_PREFIX):]
                v = natural_stack(v, len(prefixes))
                for i, pfx in enumerate(prefixes):
                    apply(named[pfx + loc], v[i])
            elif k in named:
                apply(named[k], v)

        for k, v in self._pp_state["params"].items():
            for_each(k, v, lambda t, a: setattr(t, "_data", a))
        named_b = dict(self._layers.named_buffers())
        for k, v in self._pp_state["buffers"].items():
            if k in named_b:
                named_b[k]._data = v
        # optimizer accumulators are keyed by tensor name, not model path
        opt = self._pp_optimizer
        opt_state = self._pp_state["opt"]
        for slot, d in opt_state["slots"].items():
            for k, v in d.items():
                for_each(k, v, lambda t, a, _s=slot:
                         opt._accumulators[_s].__setitem__(t.name, a))
        for k, v in opt_state["master"].items():
            for_each(k, v, lambda t, a:
                     opt._master_weights.__setitem__(t.name, a))
        opt._global_step = int(opt_state["step"])
        self._pp_dirty = False

    def _split_micro(self, t):
        n = self.accumulate_steps
        if n <= 1:
            return [t]
        arr = t._data if isinstance(t, Tensor) else jnp.asarray(t)
        if arr.shape[0] % n:
            raise ValueError(
                f"batch {arr.shape[0]} not divisible by accumulate_steps {n}")
        return [Tensor(a, stop_gradient=getattr(t, "stop_gradient", True))
                for a in jnp.split(arr, n, axis=0)]

    # delegation ----------------------------------------------------------
    def state_dict(self, *args, **kwargs):
        self._sync_state_to_layers()
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        # loaded weights invalidate the compiled-state cache: the next
        # train_batch rebuilds state from the (just-updated) layer tensors
        self._pp_step = None
        self._pp_state = None
        self._pp_dirty = False
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, *args, **kwargs):
        return self._layers.named_parameters(*args, **kwargs)
