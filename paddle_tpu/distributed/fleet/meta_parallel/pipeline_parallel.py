"""Pipeline-parallel execution.

ref: ``python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py``
(``PipelineParallel :124``, 1F1B schedule ``forward_backward_pipeline
:372``, interleaved ``:807``) and the P2P layer
(``pp_utils/p2p_communication.py:302``).

TPU-native mapping: the reference's host-driven 1F1B of NCCL sends/recvs
becomes ONE compiled program. ``train_batch`` splits the batch into
micro-batches and accumulates gradients; when the ``pp`` mesh axis is >1
and the stage stack is homogeneous, the compiled SPMD pipeline
(``paddle_tpu.distributed.fleet.meta_parallel.pp_spmd``) runs the
micro-batch loop inside ``lax.scan`` with ``ppermute`` hops between stage
shards — the ICI-native 1F1B. Otherwise the schedule degrades gracefully
to sequential micro-batch accumulation (identical numerics: pipelining
changes time, not math).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ....tensor import Tensor
from ....nn.layer.layers import Layer
from .parallel_layers.pp_layers import PipelineLayer

__all__ = ["PipelineParallel"]


class PipelineParallel(Layer):
    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError(
                "PipelineParallel expects a PipelineLayer (ref: "
                "pipeline_parallel.py:128)")
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        pcfg = (strategy.pipeline_configs
                if strategy is not None else {"accumulate_steps": 1})
        self.accumulate_steps = pcfg.get("accumulate_steps", 1)
        self.micro_batch_size = pcfg.get("micro_batch_size", None)
        self.total_loss = None

    # -- reference API surface --------------------------------------------
    def forward(self, x):
        return self._layers(x)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """ref: pipeline_parallel.py:572 train_batch → 1F1B schedule.

        data: (inputs, labels). Returns the averaged loss tensor.
        """
        if self._layers._loss_fn is None:
            raise ValueError("train_batch requires PipelineLayer(loss_fn=..)")
        inputs, labels = data
        micro_inputs = self._split_micro(inputs)
        micro_labels = self._split_micro(labels)
        n = len(micro_inputs)

        total = None
        for x, y in zip(micro_inputs, micro_labels):
            out = self._layers(x)
            loss = self._layers._loss_fn(out, y)
            if scaler is not None:
                scaled = scaler.scale(loss / n)
                scaled.backward()
            else:
                (loss / n).backward()
            total = loss.detach() if total is None else total + loss.detach()

        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        self.total_loss = total / n
        return self.total_loss

    def eval_batch(self, data, compute_loss=True):
        inputs, labels = data
        out = self._layers(inputs)
        if compute_loss and self._layers._loss_fn is not None:
            return self._layers._loss_fn(out, labels)
        return out

    def forward_backward_pipeline(self, data, optimizer, scaler=None):
        return self.train_batch(data, optimizer, scaler=scaler)

    def _split_micro(self, t):
        n = self.accumulate_steps
        if n <= 1:
            return [t]
        arr = t._data if isinstance(t, Tensor) else jnp.asarray(t)
        if arr.shape[0] % n:
            raise ValueError(
                f"batch {arr.shape[0]} not divisible by accumulate_steps {n}")
        return [Tensor(a, stop_gradient=getattr(t, "stop_gradient", True))
                for a in jnp.split(arr, n, axis=0)]

    # delegation ----------------------------------------------------------
    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, *args, **kwargs):
        return self._layers.named_parameters(*args, **kwargs)
