"""ref: ``python/paddle/distributed/fleet/meta_parallel/``."""
from .parallel_layers.mp_layers import (  # noqa: F401
    VocabParallelEmbedding, ColumnParallelLinear, RowParallelLinear,
    ParallelCrossEntropy,
)
from .parallel_layers.pp_layers import (  # noqa: F401
    PipelineLayer, LayerDesc, SharedLayerDesc,
)
from ....framework.random import RNGStatesTracker, get_tracker  # noqa: F401
from .random import get_rng_state_tracker, model_parallel_random_seed  # noqa: F401
from .tensor_parallel import TensorParallel  # noqa: F401
from .pipeline_parallel import PipelineParallel  # noqa: F401
from .sharding_parallel import ShardingParallel  # noqa: F401
from . import mp_ops  # noqa: F401
from .sequence_parallel import (  # noqa: F401
    ring_attention, ulysses_attention, split_sequence, gather_sequence,
    RingFlashAttention,
)
from . import pp_spmd  # noqa: F401
from .pp_spmd import (  # noqa: F401
    pipeline_spmd, stack_trees, unstack_tree, pipeline_executor_scope,
    current_pipeline_executor,
)
