"""Tensor-parallel communication primitives.

ref: ``python/paddle/distributed/fleet/layers/mpu/mp_ops.py``
(``_c_identity :26``, ``_c_concat :90``, ``_c_split :152``,
``_mp_allreduce :218``). The reference implements these as custom autograd
ops over NCCL; here they are ``jax.custom_vjp`` wrappers over ``lax``
collectives, meaningful when tracing inside ``shard_map`` over the ``mp``
axis (manual-SPMD mode). Outside that scope GSPMD owns partitioning and
these reduce to identity/no-ops — calling code works in both modes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ....tensor import Tensor
from ...collective import _group_of, _in_axis_scope

__all__ = ["_c_identity", "_c_concat", "_c_split", "_mp_allreduce",
           "_parallel_linear", "split"]


def _axis_of(group):
    # None means "the model-parallel axis of the global mesh", NOT the
    # default (world) group — TP layers default to mp_group=None
    return group.axis_name if group is not None else "mp"


def _axis_n(group, ax):
    if group is not None:
        return group.nranks
    try:
        return jax.lax.axis_size(ax)
    except Exception:
        from ... import mesh as _mesh_mod
        return _mesh_mod.mesh_axis_size(ax)


def _arr(x):
    return x._data if isinstance(x, Tensor) else x


def _wrap(x, arr):
    return Tensor(arr, stop_gradient=getattr(x, "stop_gradient", True)) \
        if isinstance(x, Tensor) else arr


def _c_identity(x, group=None):
    """Identity forward, all-reduce backward (the f operator of Megatron).
    ref: mp_ops.py:26."""
    ax = _axis_of(group)
    a = _arr(x)
    if not _in_axis_scope(ax):
        return x

    @jax.custom_vjp
    def f(v):
        return v

    f.defvjp(lambda v: (v, None),
             lambda _, g: (lax.psum(g, ax),))
    return _wrap(x, f(a))


def _mp_allreduce(x, group=None, use_calc_stream=True, use_model_parallel=True,
                  op=None):
    """All-reduce forward, identity backward (the g operator).
    ref: mp_ops.py:218."""
    ax = _axis_of(group)
    a = _arr(x)
    if not _in_axis_scope(ax):
        return x

    @jax.custom_vjp
    def f(v):
        return lax.psum(v, ax)

    f.defvjp(lambda v: (lax.psum(v, ax), None),
             lambda _, g: (g,))
    return _wrap(x, f(a))


def _c_split(x, group=None):
    """Keep this rank's chunk of the last dim; backward all-gathers.
    ref: mp_ops.py:152."""
    ax = _axis_of(group)
    a = _arr(x)
    if not _in_axis_scope(ax):
        return x
    n = _axis_n(group, ax)

    @jax.custom_vjp
    def f(v):
        i = lax.axis_index(ax)
        chunk = v.shape[-1] // n
        return lax.dynamic_slice_in_dim(v, i * chunk, chunk, axis=-1)

    def fwd(v):
        return f(v), None

    def bwd(_, ct):
        return (lax.all_gather(ct, ax, axis=ct.ndim - 1, tiled=True),)

    f.defvjp(fwd, bwd)
    return _wrap(x, f(a))


def _c_concat(x, group=None):
    """All-gather chunks along the last dim; backward takes this rank's
    slice. ref: mp_ops.py:90."""
    ax = _axis_of(group)
    a = _arr(x)
    if not _in_axis_scope(ax):
        return x
    n = _axis_n(group, ax)

    @jax.custom_vjp
    def f(v):
        return lax.all_gather(v, ax, axis=v.ndim - 1, tiled=True)

    def fwd(v):
        return f(v), v.shape[-1]

    def bwd(local_dim, ct):
        i = lax.axis_index(ax)
        return (lax.dynamic_slice_in_dim(ct, i * local_dim, local_dim,
                                         axis=-1),)

    f.defvjp(fwd, bwd)
    return _wrap(x, f(a))


def _parallel_linear(x, num_rows, num_cols, axis, param_attr, bias_attr,
                     gather_out, inner_rank, nranks, split_tensor, name,
                     group=None):
    """ref: mp_ops.py _parallel_linear — functional row/col split linear."""
    from .parallel_layers.mp_layers import (ColumnParallelLinear,
                                            RowParallelLinear)
    if axis == 0:
        layer = RowParallelLinear(num_rows, num_cols, weight_attr=param_attr,
                                  has_bias=bias_attr is not False,
                                  input_is_parallel=split_tensor, mp_group=group)
    else:
        layer = ColumnParallelLinear(num_rows, num_cols,
                                     weight_attr=param_attr,
                                     has_bias=bias_attr is not False,
                                     gather_output=gather_out, mp_group=group)
    return layer(x)


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """``paddle.distributed.split`` (ref: mp_ops.py:664): build + apply a
    megatron-split linear/embedding in one call."""
    if operation == "linear":
        return _parallel_linear(x, size[0], size[1], axis, weight_attr,
                                bias_attr, gather_out, 0, num_partitions,
                                axis == 0, name)
    if operation == "embedding":
        from .parallel_layers.mp_layers import VocabParallelEmbedding
        layer = VocabParallelEmbedding(size[0], size[1],
                                       weight_attr=weight_attr)
        return layer(x)
    raise ValueError(f"unsupported split operation {operation!r}")
