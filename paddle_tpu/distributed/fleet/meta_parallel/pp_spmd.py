"""Compiled SPMD pipeline parallelism — the TPU-native 1F1B.

ref: ``python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py``
(1F1B host schedule ``forward_backward_pipeline :372``, interleaved ``:807``)
and the NCCL P2P layer (``pp_utils/p2p_communication.py:302,436,478``).

TPU-first re-design: instead of a host loop issuing per-micro-batch NCCL
sends/recvs, the WHOLE schedule is one XLA program:

 - the homogeneous stage blocks' parameters are *stacked* along a new
   leading axis of size ``n_blocks`` and sharded over the ``pp`` mesh axis
   (stage s owns blocks ``[s*L, (s+1)*L)``) — each chip stores only its
   stage, the pipeline memory win;
 - a ``shard_map`` manual only over ``pp`` (dp/mp/sharding/sep stay under
   GSPMD) runs the tick loop in ``lax.scan``: at tick ``t`` stage ``s``
   processes micro-batch ``t - s``, then hands its activation to stage
   ``s+1`` with one ``lax.ppermute`` hop over ICI;
 - backward is ``jax.grad`` through the scan (``ppermute`` transposes to
   the reverse hop — the compiled analog of ``send_backward``/
   ``recv_backward``), with ``jax.checkpoint`` on the stage body so the
   scan stores only per-tick stage *inputs* and recomputes inside
   backward. The schedule is therefore GPipe-family (all forwards, one
   backward sweep) with 1F1B's activation-residency achieved via remat —
   not a literal host-interleaved 1F1B;
 - interleaved virtual stages (ref ``:807``) via ``virtual_stages=v``:
   each chip holds ``v`` non-adjacent block groups and the bubble
   fraction drops from ``(pp-1)/(M+pp-1)`` to ``(pp-1)/(M·v+pp-1)``.

The bubble executes masked dummy work (standard SPMD pipelining); with
``M`` micro-batches utilization is ``M·v / (M·v + pp - 1)``.
"""
from __future__ import annotations

import contextlib
import threading

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ... import mesh as _mesh_mod
from ....framework import random as _random

__all__ = ["stack_trees", "unstack_tree", "natural_stack", "pipeline_spmd",
           "microbatch_utilization", "pipeline_executor_scope",
           "current_pipeline_executor", "PP_STACK_PREFIX"]

# flat-dict key prefix for stacked block parameters in a pipelined
# train-step state (build_train_step): "__ppstack__.<block-local name>"
PP_STACK_PREFIX = "__ppstack__."

_executor_tls = threading.local()


@contextlib.contextmanager
def pipeline_executor_scope(fn):
    """While active, pipeline-aware models route their homogeneous block
    loop through ``fn(x, *extras) -> x`` instead of running it inline."""
    prev = getattr(_executor_tls, "fn", None)
    _executor_tls.fn = fn
    try:
        yield
    finally:
        _executor_tls.fn = prev


def current_pipeline_executor():
    return getattr(_executor_tls, "fn", None)


def stack_trees(trees):
    """Stack a list of identical pytrees along a new leading axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)


def unstack_tree(tree, n):
    """Inverse of :func:`stack_trees`: one pytree -> list of n pytrees."""
    return [jax.tree.map(lambda a, i=i: a[i], tree) for i in range(n)]


def natural_stack(arr, n_blocks):
    """View a ``__ppstack__`` leaf in natural ``[n_blocks, ...]`` block
    order, flattening the interleaved ``[v, pp*Lv, ...]`` layout when
    present (both are row-major views of the same order)."""
    if arr.shape[0] != n_blocks:
        return arr.reshape((n_blocks,) + tuple(arr.shape[2:]))
    return arr


def microbatch_utilization(num_microbatches, pp):
    """Fraction of non-bubble ticks: M / (M + pp - 1)."""
    return num_microbatches / (num_microbatches + pp - 1)


def pipeline_spmd(stage_fn, stage_params, x, num_microbatches, *,
                  mesh=None, axis_name="pp", remat=True, extras=(),
                  virtual_stages=1, overlap=None):
    """Run ``x`` through ``pp`` pipeline stages as one compiled schedule.

    stage_fn(stage_params_group, h, *extras_mb) -> h' where
    ``stage_params_group`` is ``stage_params`` reduced to the blocks this
    stage applies on this visit (leading axis = blocks-per-call), and
    ``h``/``h'`` are one micro-batch of activations with identical
    shape/dtype (homogeneous-stage requirement, same as the reference's
    ``PipelineLayer`` contract).

    stage_params: pytree. With ``virtual_stages == 1`` every leaf is
    ``[n_blocks, ...]`` sharded ``P(axis_name, ...)``. With
    ``virtual_stages == v > 1`` every leaf is the row-major reshape
    ``[v, pp * Lv, ...]`` (``Lv = n_blocks / (pp * v)``) sharded
    ``P(None, axis_name, ...)`` — chip ``s`` then physically owns virtual
    stages ``{g * pp + s}``, the Megatron interleaved placement (ref
    ``pipeline_parallel.py:807 PipelineParallelWithInterleave``), with NO
    block permutation: the reshape alone interleaves ownership.

    Schedule (one generalized ring): an activation circulates the pp ring
    ``v`` times; on lap ``g`` chip ``s`` applies virtual stage
    ``g * pp + s`` (its local group ``g``). A micro-batch enters chip 0
    whenever the arriving ring slot is free (initial fill, or its previous
    occupant finished lap ``v``). Total ticks
    ``T = ((M-1)//pp)·v·pp + (M-1)%pp + v·pp``; for ``pp | M`` that is
    ``M·v + pp - 1`` ticks of ``Lv`` blocks each — the bubble shrinks by
    ``v`` versus the non-interleaved schedule (utilization
    ``M·v / (M·v + pp - 1)``).

    This is a GPipe-family synchronous schedule compiled into ``lax.scan``
    (all micro-batch forwards, then one backward through the scan with
    ``jax.checkpoint`` on the stage body — per-tick stage *inputs* are the
    only stored activations); it is not literal host-scheduled 1F1B, but
    matches its activation-residency discipline via remat.

    x: ``[B, ...]`` activations entering stage 0; ``B`` must be divisible
    by ``num_microbatches``. The micro-batch buffer keeps its ``dp``
    sharding on the batch dim (pinned below); it is replicated over the
    ``pp`` axis only.

    extras: auxiliary arrays fed to every stage call (e.g. an attention
    mask). An extra whose leading dim equals ``B`` is split into
    micro-batches and indexed at the micro-batch each chip is processing;
    other extras (broadcast masks etc.) pass through whole.

    overlap: double-buffer the ring hop so tick ``t`` TRANSPORTS tick
    ``t-1``'s activations while COMPUTING tick ``t``'s — the ``ppermute``
    has no data dependence on the tick's stage compute, letting XLA's
    async collectives run the hop on the ICI under the MXU work (the
    compiled analog of the reference's separate P2P comm stream,
    ``pp_utils/p2p_communication.py``). Hop latency becomes 2 ticks: the
    ring deepens to ``2·pp`` slots (two interleaved phases), fill/drain
    doubles but steady-state stays one micro-batch per tick, so
    ``T₂ = τ₂(M−1) + 2·v·pp − 1`` with
    ``τ₂(m) = (m // 2pp)·2·v·pp + m % 2pp``. Default from
    ``PT_PP_OVERLAP`` (on); pass ``False``/``True`` to force.

    Returns ``[B, ...]`` activations leaving the last stage (read from the
    last stage's shard — no all-reduce; XLA broadcasts on consumption).
    Differentiable (gradients flow to ``stage_params``, ``x`` and split
    ``extras``).
    """
    import os
    if overlap is None:
        overlap = os.environ.get("PT_PP_OVERLAP", "1") not in (
            "0", "false", "off")
    overlap = bool(overlap)
    mesh = mesh or _mesh_mod.get_mesh()
    pp = mesh.shape.get(axis_name, 1)
    M = int(num_microbatches)
    v = int(virtual_stages)
    B = x.shape[0]
    if B % M:
        raise ValueError(
            f"batch {B} not divisible by num_microbatches {M}")

    if pp <= 1:
        # no pp axis: plain sequential over the stacked blocks
        if v > 1:  # flatten [v, Lv*pp, ...] back to natural block order
            stage_params = jax.tree.map(
                lambda a: a.reshape((-1,) + a.shape[2:]), stage_params)
        return stage_fn(stage_params, x, *extras)

    mb_shape = (M, B // M) + tuple(x.shape[1:])
    split_mask = [getattr(e, "ndim", 0) >= 1 and e.shape[0] == B
                  for e in extras]
    extras_in = tuple(
        jnp.reshape(e, (M, B // M) + tuple(e.shape[1:])) if sp else e
        for e, sp in zip(extras, split_mask))
    body = jax.checkpoint(stage_fn) if remat else stage_fn
    if overlap:
        # 2-tick hop: ring deepens to 2·pp slots (two interleaved
        # phases); injection blocks only once 2·pp micro-batches are in
        # flight, each occupying its slot 2·v·pp ticks
        T = (((M - 1) // (2 * pp)) * 2 * v * pp + (M - 1) % (2 * pp)
             + 2 * v * pp - 1)
    else:
        T = ((M - 1) // pp) * v * pp + (M - 1) % pp + v * pp

    def pipelined(sp, mbs, key, *extras_mb):
        # sp leaves arrive [n_local, ...] (v==1) or [v, Lv, ...] (v>1):
        # this chip's blocks only. mbs [M, mb, ...] replicated over pp
        # (dp-sharded on the batch dim via the auto axes).
        idx = lax.axis_index(axis_name)
        # per-stage, per-tick RNG: distinct dropout keys on every stage
        stage_key = jax.random.fold_in(key, idx)
        perm = [(i, (i + 1) % pp) for i in range(pp)]

        def process(act, r, m, n_inj, out_buf, t):
            """One stage visit: inject at stage 0 into a free slot, run
            the stage body, write finished micro-batches, advance laps.
            Returns the outgoing (y, r_next, m_cur) slot."""
            # the arriving ring slot is free iff its occupant has finished
            # all v laps (init: r = v marks every slot free)
            inject = (idx == 0) & (r >= v) & (n_inj < M)
            x_in = jnp.where(inject, mbs[jnp.clip(n_inj, 0, M - 1)], act)
            r_cur = jnp.where(inject, 0, r)
            m_cur = jnp.where(inject, n_inj, m)
            n_inj = n_inj + inject.astype(jnp.int32)

            mb_i = jnp.clip(m_cur, 0, M - 1)
            e_in = tuple(e[mb_i] if sp_ else e
                         for e, sp_ in zip(extras_mb, split_mask))
            g = jnp.clip(r_cur, 0, v - 1)
            sp_g = sp if v == 1 else jax.tree.map(lambda a: a[g], sp)

            def run(h, key):
                with _random.trace_key_scope(key):
                    return body(sp_g, h, *e_in)

            y = run(x_in, jax.random.fold_in(stage_key, t))
            # a micro-batch leaves the pipeline at the last chip of its
            # final lap; bubble slots (r_cur >= v) never write
            done = (idx == pp - 1) & (r_cur == v - 1)
            upd = jnp.where(done, y, out_buf[mb_i])
            out_buf = lax.dynamic_update_index_in_dim(out_buf, upd, mb_i, 0)
            # laps advance when the activation wraps pp-1 -> 0
            r_next = jnp.where(idx == pp - 1, r_cur + 1, r_cur)
            return (y, r_next, m_cur), n_inj, out_buf

        def hop(slot):
            # hand (activation, lap, micro-batch id) to the next stage
            return tuple(lax.ppermute(s, axis_name, perm) for s in slot)

        def tick(carry, t):
            act, r, m, n_inj, out_buf = carry
            out_slot, n_inj, out_buf = process(act, r, m, n_inj, out_buf, t)
            act, r, m = hop(out_slot)
            return (act, r, m, n_inj, out_buf), None

        def tick_overlap(carry, t):
            # double-buffered edge state: transport LAST tick's output
            # while running THIS tick's compute — the ppermute has no
            # data dependence on process(), so the latency-hiding
            # scheduler runs it under the stage body (async collective
            # on ICI). The hop takes 2 ticks; even/odd ticks form two
            # interleaved pipeline phases.
            cur, pend, n_inj, out_buf = carry
            arrived = hop(pend)
            act, r, m = cur
            out_slot, n_inj, out_buf = process(act, r, m, n_inj, out_buf, t)
            return (arrived, out_slot, n_inj, out_buf), None

        free_slot = (jnp.zeros(mb_shape[1:], x.dtype),
                     jnp.int32(v), jnp.int32(0))
        out0 = jnp.zeros(mb_shape, x.dtype)
        if overlap:
            init = (free_slot, free_slot, jnp.int32(0), out0)
            (_, _, _, out_buf), _ = lax.scan(
                tick_overlap, init, jnp.arange(T))
        else:
            init = free_slot + (jnp.int32(0), out0)
            (_, _, _, _, out_buf), _ = lax.scan(tick, init, jnp.arange(T))
        # out_specs stacks the per-stage buffers over pp; only the last
        # stage's row is real (cheaper than the old full-output psum:
        # consumers slice row pp-1 and XLA broadcasts just that)
        return out_buf[None]

    mbs = jnp.reshape(x, mb_shape)
    # keep the micro-batch buffer dp-sharded inside the shard_map: pin the
    # batch dim (dim 1 after the reshape) to 'dp' when it divides
    dp = mesh.shape.get("dp", 1)
    if dp > 1 and mb_shape[1] % dp == 0:
        mbs = jax.lax.with_sharding_constraint(
            mbs, jax.sharding.NamedSharding(
                mesh, P(None, "dp", *([None] * (len(mb_shape) - 2)))))
    # RNG: when a functional trace scope is active (build_train_step), fold
    # from its traced key; otherwise use a fresh literal key — we must NOT
    # touch the global generator here, or its cached root key would be
    # created as a tracer inside this trace and leak.
    if _random._trace_key_state() is not None:
        key = _random.next_key()
    else:
        key = jax.random.key(0)
    sp_spec = P(axis_name) if v == 1 else P(None, axis_name)
    from ..._jax_compat import shard_map
    mapped = shard_map(
        pipelined, mesh=mesh,
        in_specs=(sp_spec, P(), P()) + tuple(P() for _ in extras_in),
        out_specs=P(axis_name), axis_names={axis_name}, check_vma=False)
    out = mapped(stage_params, mbs, key, *extras_in)
    return jnp.reshape(out[pp - 1], x.shape)
