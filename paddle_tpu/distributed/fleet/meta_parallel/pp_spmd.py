"""Compiled SPMD pipeline parallelism — the TPU-native 1F1B.

ref: ``python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py``
(1F1B host schedule ``forward_backward_pipeline :372``, interleaved ``:807``)
and the NCCL P2P layer (``pp_utils/p2p_communication.py:302,436,478``).

TPU-first re-design: instead of a host loop issuing per-micro-batch NCCL
sends/recvs, the WHOLE schedule is one XLA program:

 - the homogeneous stage blocks' parameters are *stacked* along a new
   leading axis of size ``n_blocks`` and sharded over the ``pp`` mesh axis
   (stage s owns blocks ``[s*L, (s+1)*L)``) — each chip stores only its
   stage, the pipeline memory win;
 - a ``shard_map`` manual only over ``pp`` (dp/mp/sharding/sep stay under
   GSPMD) runs the tick loop in ``lax.scan``: at tick ``t`` stage ``s``
   processes micro-batch ``t - s``, then hands its activation to stage
   ``s+1`` with one ``lax.ppermute`` hop over ICI;
 - backward is ``jax.grad`` through the scan (``ppermute`` transposes to
   the reverse hop — the compiled analog of ``send_backward``/
   ``recv_backward``), with ``jax.checkpoint`` on the stage body so the
   scan stores only per-tick stage *inputs* (the 1F1B activation-memory
   discipline) and recomputes inside backward.

The bubble executes masked dummy work (standard SPMD pipelining); with
``M`` micro-batches utilization is ``M / (M + pp - 1)``.
"""
from __future__ import annotations

import contextlib
import threading

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ... import mesh as _mesh_mod
from ....framework import random as _random

__all__ = ["stack_trees", "unstack_tree", "pipeline_spmd",
           "microbatch_utilization", "pipeline_executor_scope",
           "current_pipeline_executor", "PP_STACK_PREFIX"]

# flat-dict key prefix for stacked block parameters in a pipelined
# train-step state (build_train_step): "__ppstack__.<block-local name>"
PP_STACK_PREFIX = "__ppstack__."

_executor_tls = threading.local()


@contextlib.contextmanager
def pipeline_executor_scope(fn):
    """While active, pipeline-aware models route their homogeneous block
    loop through ``fn(x, *extras) -> x`` instead of running it inline."""
    prev = getattr(_executor_tls, "fn", None)
    _executor_tls.fn = fn
    try:
        yield
    finally:
        _executor_tls.fn = prev


def current_pipeline_executor():
    return getattr(_executor_tls, "fn", None)


def stack_trees(trees):
    """Stack a list of identical pytrees along a new leading axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)


def unstack_tree(tree, n):
    """Inverse of :func:`stack_trees`: one pytree -> list of n pytrees."""
    return [jax.tree.map(lambda a, i=i: a[i], tree) for i in range(n)]


def microbatch_utilization(num_microbatches, pp):
    """Fraction of non-bubble ticks: M / (M + pp - 1)."""
    return num_microbatches / (num_microbatches + pp - 1)


def pipeline_spmd(stage_fn, stage_params, x, num_microbatches, *,
                  mesh=None, axis_name="pp", remat=True, extras=()):
    """Run ``x`` through ``pp`` pipeline stages as one compiled schedule.

    stage_fn(stage_params_local, h, *extras_mb) -> h' where
    ``stage_params_local`` is ``stage_params`` with the leading (stage)
    axis reduced to this stage's slice, and ``h``/``h'`` are one
    micro-batch of activations with identical shape/dtype
    (homogeneous-stage requirement, same as the reference's
    ``PipelineLayer`` contract).

    stage_params: pytree; every leaf has leading dim divisible by ``pp``
    (``n_blocks`` total blocks → ``L = n_blocks/pp`` per stage) and is
    expected to be sharded ``P(axis_name, ...)`` on that axis.

    x: ``[B, ...]`` activations entering stage 0; ``B`` must be divisible
    by ``num_microbatches``.

    extras: auxiliary arrays fed to every stage call (e.g. an attention
    mask). An extra whose leading dim equals ``B`` is split into
    micro-batches and indexed at each stage's own offset ``t - s`` (stage
    ``s`` processes micro-batch ``t - s`` at tick ``t``); other extras
    (broadcast masks etc.) pass through whole.

    Returns ``[B, ...]`` activations leaving the last stage. Differentiable
    (gradients flow to ``stage_params``, ``x`` and split ``extras``).
    """
    mesh = mesh or _mesh_mod.get_mesh()
    pp = mesh.shape.get(axis_name, 1)
    M = int(num_microbatches)
    B = x.shape[0]
    if B % M:
        raise ValueError(
            f"batch {B} not divisible by num_microbatches {M}")

    if pp <= 1:
        # no pp axis: plain sequential over the stacked blocks
        return stage_fn(stage_params, x, *extras)

    mb_shape = (M, B // M) + tuple(x.shape[1:])
    split_mask = [getattr(e, "ndim", 0) >= 1 and e.shape[0] == B
                  for e in extras]
    extras_in = tuple(
        jnp.reshape(e, (M, B // M) + tuple(e.shape[1:])) if sp else e
        for e, sp in zip(extras, split_mask))
    body = jax.checkpoint(stage_fn) if remat else stage_fn

    def pipelined(sp, mbs, key, *extras_mb):
        # sp leaves arrive [n_blocks/pp, ...] (this stage's slice);
        # mbs [M, mb, ...] replicated over pp.
        idx = lax.axis_index(axis_name)
        # per-stage, per-tick RNG: distinct dropout keys on every stage
        stage_key = jax.random.fold_in(key, idx)

        perm = [(i, (i + 1) % pp) for i in range(pp)]
        T = M + pp - 1

        def tick(carry, t):
            act, out_buf = carry
            x_in = jnp.where(idx == 0, mbs[jnp.clip(t, 0, M - 1)], act)
            # stage s processes micro-batch t - s at tick t
            mb_i = jnp.clip(t - idx, 0, M - 1)
            e_in = tuple(e[mb_i] if sp else e
                         for e, sp in zip(extras_mb, split_mask))

            def run(h, key):
                with _random.trace_key_scope(key):
                    return body(sp, h, *e_in)

            y = run(x_in, jax.random.fold_in(stage_key, t))
            out_t = t - (pp - 1)
            oc = jnp.clip(out_t, 0, M - 1)
            valid = (out_t >= 0) & (out_t < M) & (idx == pp - 1)
            upd = jnp.where(valid, y, out_buf[oc])
            out_buf = lax.dynamic_update_index_in_dim(out_buf, upd, oc, 0)
            # hand activations to the next stage over ICI
            act = lax.ppermute(y, axis_name, perm)
            return (act, out_buf), None

        init = (jnp.zeros(mb_shape[1:], x.dtype),
                jnp.zeros(mb_shape, x.dtype))
        (_act, out_buf), _ = lax.scan(tick, init, jnp.arange(T))
        # only the last stage holds real outputs; psum over pp replicates
        # them (everyone else contributes zeros)
        out = lax.psum(jnp.where(idx == pp - 1, out_buf,
                                 jnp.zeros_like(out_buf)), axis_name)
        return out

    mbs = jnp.reshape(x, mb_shape)
    # RNG: when a functional trace scope is active (build_train_step), fold
    # from its traced key; otherwise use a fresh literal key — we must NOT
    # touch the global generator here, or its cached root key would be
    # created as a tracer inside this trace and leak.
    if _random._trace_key_state() is not None:
        key = _random.next_key()
    else:
        key = jax.random.key(0)
    mapped = jax.shard_map(
        pipelined, mesh=mesh,
        in_specs=(P(axis_name), P(), P()) + tuple(P() for _ in extras_in),
        out_specs=P(), axis_names={axis_name}, check_vma=False)
    out = mapped(stage_params, mbs, key, *extras_in)
    return jnp.reshape(out, x.shape)
