"""TensorParallel model wrapper (ref:
``fleet/meta_parallel/tensor_parallel.py``).

The reference broadcasts initial parameters across the mp group and wires
grad sync; under GSPMD the mp-sharded parameters are a single logical
array (always consistent) and grad collectives are compiled in, so the
wrapper's job reduces to: place mp-annotated parameters onto the mesh and
shard inputs over dp.
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ....tensor import Tensor
from ....nn.layer.layers import Layer
from ... import mesh as _mesh_mod

__all__ = ["TensorParallel"]


def place_parameters_on_mesh(layer: Layer, mesh=None):
    """device_put every parameter according to its ``_spec`` annotation
    (replicated if none). Idempotent; the distributed entry point."""
    mesh = mesh or _mesh_mod.get_mesh()
    if mesh is None:
        return layer
    for _, p in layer.named_parameters():
        if isinstance(p._data, jax.core.Tracer):
            continue
        spec = p._spec or P()
        try:
            p._data = jax.device_put(p._data, NamedSharding(mesh, spec))
        except ValueError:
            p._data = jax.device_put(p._data, NamedSharding(mesh, P()))
    for _, b in layer.named_buffers():
        if not isinstance(b._data, jax.core.Tracer):
            b._data = jax.device_put(b._data, NamedSharding(mesh, P()))
    return layer


class TensorParallel(Layer):
    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        place_parameters_on_mesh(layers)

    def forward(self, *inputs, **kwargs):
        mesh = _mesh_mod.get_mesh()
        if mesh is not None and mesh.shape.get("dp", 1) > 1:
            from ...parallel import shard_batch_inputs
            inputs, kwargs = shard_batch_inputs(mesh, inputs, kwargs)
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, *args, **kwargs):
        return self._layers.named_parameters(*args, **kwargs)
