"""ref: ``python/paddle/distributed/fleet/utils/`` — recompute lives here
in the reference's public API (``fleet.utils.recompute``)."""
from ..recompute import recompute, recompute_sequential  # noqa: F401


class LocalFS:
    """Minimal filesystem shim (ref: ``fleet/utils/fs.py LocalFS``)."""

    def ls_dir(self, path):
        import os
        if not os.path.isdir(path):
            return [], []
        entries = os.listdir(path)
        dirs = [e for e in entries
                if os.path.isdir(os.path.join(path, e))]
        files = [e for e in entries
                 if not os.path.isdir(os.path.join(path, e))]
        return dirs, files

    def is_exist(self, path):
        import os
        return os.path.exists(path)

    def mkdirs(self, path):
        import os
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        import shutil, os
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)
