"""ref: ``python/paddle/distributed/fleet/utils/`` — recompute lives here
in the reference's public API (``fleet.utils.recompute``)."""
from ..recompute import recompute, recompute_sequential  # noqa: F401


class LocalFS:
    """Minimal filesystem shim (ref: ``fleet/utils/fs.py LocalFS``)."""

    def ls_dir(self, path):
        import os
        if not os.path.isdir(path):
            return [], []
        entries = os.listdir(path)
        dirs = [e for e in entries
                if os.path.isdir(os.path.join(path, e))]
        files = [e for e in entries
                 if not os.path.isdir(os.path.join(path, e))]
        return dirs, files

    def is_exist(self, path):
        import os
        return os.path.exists(path)

    def mkdirs(self, path):
        import os
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        import shutil, os
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)


class HDFSClient:
    """HDFS filesystem client over the hadoop CLI (ref:
    ``fleet/utils/fs.py:424 HDFSClient`` — the reference shells out to
    ``hadoop fs`` exactly the same way). Requires a hadoop installation;
    constructing without one raises immediately with the reason."""

    def __init__(self, hadoop_home, configs=None, time_out=5 * 60 * 1000,
                 sleep_inter=1000):
        import os
        self._base = os.path.join(hadoop_home, "bin", "hadoop")
        if not os.path.exists(self._base):
            raise RuntimeError(
                f"hadoop binary not found at {self._base}; HDFSClient "
                f"needs a hadoop installation (hadoop_home)")
        self._cfg = []
        for k, v in (configs or {}).items():
            self._cfg += ["-D", f"{k}={v}"]
        self._timeout = time_out / 1000.0

    def _run(self, *args):
        import subprocess
        out = subprocess.run([self._base, "fs"] + self._cfg + list(args),
                             capture_output=True, text=True,
                             timeout=self._timeout)
        return out.returncode, out.stdout, out.stderr

    def is_exist(self, path):
        rc, _, _ = self._run("-test", "-e", path)
        return rc == 0

    def is_dir(self, path):
        rc, _, _ = self._run("-test", "-d", path)
        return rc == 0

    def is_file(self, path):
        return self.is_exist(path) and not self.is_dir(path)

    def ls_dir(self, path):
        rc, out, err = self._run("-ls", path)
        if rc != 0:
            return [], []
        dirs, files = [], []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = parts[-1].rsplit("/", 1)[-1]
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def mkdirs(self, path):
        rc, _, err = self._run("-mkdir", "-p", path)
        if rc != 0:
            raise RuntimeError(f"hdfs mkdirs failed: {err.strip()}")

    def delete(self, path):
        # -f: deleting a missing path is success, real failures raise
        rc, _, err = self._run("-rm", "-r", "-f", path)
        if rc != 0:
            raise RuntimeError(f"hdfs delete failed: {err.strip()}")

    def upload(self, local_path, fs_path, multi_processes=1,
               overwrite=False):
        if overwrite:
            self.delete(fs_path)
        rc, _, err = self._run("-put", local_path, fs_path)
        if rc != 0:
            raise RuntimeError(f"hdfs upload failed: {err.strip()}")

    def download(self, fs_path, local_path, multi_processes=1,
                 overwrite=False):
        rc, _, err = self._run("-get", fs_path, local_path)
        if rc != 0:
            raise RuntimeError(f"hdfs download failed: {err.strip()}")

    def touch(self, fs_path, exist_ok=True):
        rc, _, err = self._run("-touchz", fs_path)
        if rc != 0 and not exist_ok:
            raise RuntimeError(f"hdfs touch failed: {err.strip()}")

    def mv(self, src, dst, overwrite=False):
        if overwrite:
            self.delete(dst)
        rc, _, err = self._run("-mv", src, dst)
        if rc != 0:
            raise RuntimeError(f"hdfs mv failed: {err.strip()}")

    def cat(self, fs_path):
        rc, out, _ = self._run("-cat", fs_path)
        return out if rc == 0 else ""


class DistributedInfer:
    """PS-era distributed inference helper (ref:
    ``fleet/utils/ps_util.py:24``): in the reference it rewrites the
    program to pull remote sparse tables before inference. Tables here
    live in the executor scope already, so get_dirname/init handling
    reduces to loading persistables if a dirname is given."""

    def __init__(self, main_program=None, startup_program=None):
        from ....static.graph import (default_main_program,
                                      default_startup_program)
        self.origin_main_program = main_program \
            if main_program is not None else default_main_program()
        self.startup_program = startup_program \
            if startup_program is not None else default_startup_program()
        self._inited = False

    def init_distributed_infer_env(self, exe, loss, role_maker=None,
                                   dirname=None):
        if self._inited:
            return
        if dirname:
            from ...io import load_persistables
            load_persistables(exe, dirname, self.origin_main_program)
        self._inited = True

    def get_dist_infer_program(self):
        """The reference splices sparse-table pulls into a clone; the
        scope-resident tables make the original program already the
        inference program."""
        return self.origin_main_program


__all__ = ["LocalFS", "HDFSClient", "DistributedInfer", "recompute",
           "recompute_sequential"]
