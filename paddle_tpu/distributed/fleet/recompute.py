"""Activation recompute (checkpointing).

ref: ``python/paddle/distributed/fleet/recompute/recompute.py`` (+
``recompute_hybrid.py``). The reference re-runs forward under saved RNG
state in the backward pass; the TPU-native design maps this to
``jax.checkpoint`` (rematerialization) inside the compiled program — XLA
re-schedules the recomputation into the backward where it saves HBM, and
RNG replay is free because jax PRNG keys are pure values.

In eager (tape) mode recompute executes normally — the memory win only
exists on the compiled path, which is where TPU training runs
(``to_static`` / ``functional_call``).
"""
from __future__ import annotations

import jax

from ... import autograd
from ...tensor import Tensor

__all__ = ["recompute", "recompute_sequential"]


def _to_arrays(tree):
    return jax.tree_util.tree_map(
        lambda t: t._data if isinstance(t, Tensor) else t, tree,
        is_leaf=lambda t: isinstance(t, Tensor))


_POLICIES = {
    None: None,
    "full": None,
    # save matmul/dot outputs, recompute the cheap elementwise tail —
    # the sweet spot between full remat (recompute ~1/3 more FLOPs) and
    # no remat (O(L) activation residency); the reference exposes the
    # same dial as recompute granularity "core_attn"/"full"
    "dots": "dots_saveable",
    "dots_saveable": "dots_saveable",
    "dots_with_no_batch_dims": "dots_with_no_batch_dims_saveable",
    "dots_with_no_batch_dims_saveable": "dots_with_no_batch_dims_saveable",
    "nothing": "nothing_saveable",
    "everything": "everything_saveable",
}


def _resolve_policy(policy):
    if callable(policy):
        return policy
    name = _POLICIES.get(policy, policy)
    return None if name is None else getattr(jax.checkpoint_policies, name)


def recompute(function, *args, **kwargs):
    """Drop-in for ``paddle.distributed.fleet.utils.recompute``.

    kwargs accepted for parity: ``use_reentrant`` (ignored — no reentrant
    autograd here), ``preserve_rng_state`` (always true: keys are values).
    ``policy`` selects what XLA may keep instead of recomputing
    (string from ``_POLICIES`` or a ``jax.checkpoint_policies`` callable).
    """
    kwargs.pop("use_reentrant", None)
    kwargs.pop("preserve_rng_state", None)
    policy = _resolve_policy(kwargs.pop("policy", None))
    if not autograd.in_functional_mode():
        return function(*args, **kwargs)

    flat_args, struct = jax.tree_util.tree_flatten(
        args, is_leaf=lambda t: isinstance(t, Tensor))
    tensor_idx = [i for i, a in enumerate(flat_args)
                  if isinstance(a, Tensor)]
    arrays = [flat_args[i]._data for i in tensor_idx]

    def pure(*arrs):
        leaves = list(flat_args)
        for i, a in zip(tensor_idx, arrs):
            leaves[i] = Tensor(a, stop_gradient=flat_args[i].stop_gradient)
        rebuilt = jax.tree_util.tree_unflatten(struct, leaves)
        out = function(*rebuilt, **kwargs)
        return _to_arrays(out)

    out_arrays = jax.checkpoint(pure, policy=policy)(*arrays)
    return jax.tree_util.tree_map(lambda a: Tensor(a), out_arrays)


def recompute_sequential(ctx, functions, *args, **kwargs):
    """ref: ``recompute_sequential`` — checkpoint a Sequential in segments."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    layers = list(functions)
    n = len(layers)
    seg = max(n // max(segments, 1), 1)
    out = args
    i = 0
    while i < n:
        chunk = layers[i:i + seg]

        def run_chunk(*xs, _chunk=chunk):
            y = xs
            for l in _chunk:
                y = l(*y) if isinstance(y, tuple) else l(y)
                if not isinstance(y, tuple):
                    y = (y,)
            return y[0] if len(y) == 1 else y

        out = recompute(run_chunk, *out) if isinstance(out, tuple) \
            else recompute(run_chunk, out)
        if not isinstance(out, tuple):
            out = (out,)
        i += seg
    return out[0] if isinstance(out, tuple) and len(out) == 1 else out


def recompute_hybrid(ctx, function, *args, **kwargs):
    """Recompute under hybrid parallelism (ref ``recompute.py:520``):
    the mp_group/offload knobs in ``ctx`` tune the reference's CUDA rng
    + offload bookkeeping; on TPU XLA remat owns scheduling, so they
    are accepted and the function recomputes like :func:`recompute`."""
    return recompute(function, *args, **kwargs)
