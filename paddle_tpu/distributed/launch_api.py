"""spawn/launch entry (ref: ``python/paddle/distributed/spawn.py`` and the
launcher ``python/paddle/distributed/launch/main.py:18``).

Single-host TPU reality: ONE process drives all local chips, so the
reference's N-processes-per-node model maps to (a) spawn with nprocs=1
per host, or (b) multi-host launches where each host runs one process
(env contract preserved: PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM /
PADDLE_TRAINER_ENDPOINTS / MASTER_ADDR). The full process-manager CLI
lives in ``paddle_tpu.distributed.launch``.
"""
from __future__ import annotations

import multiprocessing as mp
import os

__all__ = ["spawn", "launch"]


def _worker(fn, rank, nprocs, env, args):
    os.environ.update(env)
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    fn(*args)


def spawn(func, args=(), nprocs=1, join=True, daemon=False, **options):
    """ref: spawn.py:spawn. nprocs defaults to 1 (one controller per host
    drives every local chip — unlike one-process-per-GPU)."""
    if nprocs <= 1:
        os.environ.setdefault("PADDLE_TRAINER_ID", "0")
        os.environ.setdefault("PADDLE_TRAINERS_NUM", "1")
        func(*args)
        return None
    ctx = mp.get_context("spawn")
    env = {k: v for k, v in os.environ.items()}
    procs = []
    for rank in range(nprocs):
        p = ctx.Process(target=_worker,
                        args=(func, rank, nprocs, env, args), daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            # bounded joins: a wedged worker keeps surfacing here every
            # minute instead of hanging the launcher invisibly
            while p.is_alive():
                p.join(timeout=60.0)
        bad = [p.exitcode for p in procs if p.exitcode]
        if bad:
            raise RuntimeError(f"spawned workers failed with codes {bad}")
    return procs


def launch():
    from .launch.main import main
    main()
