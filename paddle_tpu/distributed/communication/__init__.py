"""``paddle.distributed.communication`` package (ref:
``python/paddle/distributed/communication/``): the same collective
surface re-exported, plus the ``stream`` sub-namespace."""
from . import stream  # noqa: F401
