"""``paddle.distributed.communication.stream`` (ref:
``python/paddle/distributed/communication/stream/``).

The reference's stream variants exist to issue a collective on a chosen
CUDA stream (``use_calc_stream``) and return a waitable ``Task``. XLA
runtime streams are compiler-scheduled: every collective here is already
async-dispatched and ordered by data dependence, so the stream entries
are the same operations with the reference's extra knobs accepted —
``use_calc_stream=True`` (the only behavior XLA has) and ``sync_op``
forwarded. They remain separate callables so ported code keeps working
untouched.
"""
from __future__ import annotations

from .. import collective as _c

__all__ = ["all_reduce", "all_gather", "alltoall", "alltoall_single",
           "broadcast", "gather", "reduce", "reduce_scatter", "scatter",
           "send", "recv"]


def _check_stream(sync_op, use_calc_stream):
    """Reference parity guard (``stream/all_reduce.py``): use_calc_stream
    is only legal in sync-op behavior."""
    if use_calc_stream and not sync_op:
        raise RuntimeError(
            "use_calc_stream can only be True in sync op behavior")


def all_reduce(tensor, op=_c.ReduceOp.SUM, group=None, sync_op=True,
               use_calc_stream=False):
    _check_stream(sync_op, use_calc_stream)
    return _c.all_reduce(tensor, op=op, group=group, sync_op=sync_op)


def all_gather(tensor_or_tensor_list, tensor, group=None, sync_op=True,
               use_calc_stream=False):
    _check_stream(sync_op, use_calc_stream)
    return _c.all_gather(tensor_or_tensor_list, tensor, group=group,
                         sync_op=sync_op)


def alltoall(out_tensor_list, in_tensor_list, group=None, sync_op=True,
               use_calc_stream=False):
    _check_stream(sync_op, use_calc_stream)
    return _c.alltoall(out_tensor_list, in_tensor_list, group=group,
                       sync_op=sync_op)


def alltoall_single(out_tensor, in_tensor, out_split_sizes=None,
                      in_split_sizes=None, group=None, sync_op=True,
                      use_calc_stream=False):
    _check_stream(sync_op, use_calc_stream)
    return _c.alltoall_single(in_tensor, out_tensor,
                              in_split_sizes=in_split_sizes,
                              out_split_sizes=out_split_sizes,
                              group=group, sync_op=sync_op)


def broadcast(tensor, src, group=None, sync_op=True, use_calc_stream=False):
    _check_stream(sync_op, use_calc_stream)
    return _c.broadcast(tensor, src=src, group=group, sync_op=sync_op)


def reduce(tensor, dst=0, op=_c.ReduceOp.SUM, group=None, sync_op=True,
           use_calc_stream=False):
    _check_stream(sync_op, use_calc_stream)
    return _c.reduce(tensor, dst=dst, op=op, group=group, sync_op=sync_op)


def reduce_scatter(tensor, tensor_list=None, op=_c.ReduceOp.SUM, group=None,
                   sync_op=True, use_calc_stream=False):
    _check_stream(sync_op, use_calc_stream)
    return _c.reduce_scatter(tensor, tensor_list, op=op, group=group,
                             sync_op=sync_op)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True,
            use_calc_stream=False):
    _check_stream(sync_op, use_calc_stream)
    return _c.scatter(tensor, tensor_list, src=src, group=group,
                      sync_op=sync_op)


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True,
           use_calc_stream=False):
    _check_stream(sync_op, use_calc_stream)
    return _c.gather(tensor, gather_list, dst=dst, group=group,
                     sync_op=sync_op)


def send(tensor, dst=0, group=None, sync_op=True, use_calc_stream=False):
    _check_stream(sync_op, use_calc_stream)
    return _c.send(tensor, dst=dst, group=group, sync_op=sync_op)


def recv(tensor, src=0, group=None, sync_op=True, use_calc_stream=False):
    _check_stream(sync_op, use_calc_stream)
    return _c.recv(tensor, src=src, group=group, sync_op=sync_op)
