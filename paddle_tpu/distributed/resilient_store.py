"""Auto-reconnecting TCPStore client with endpoint re-resolution and
generation fencing.

The raw :class:`~paddle_tpu.core.TCPStore` client dies with the master:
one ``ConnectionError`` and every barrier, heartbeat and staged commit
built on it fails instantly — even though a supervised master respawns
from its WAL within a second.  :class:`ResilientStore` is the client
half of store failover:

 - every op runs through :func:`~paddle_tpu.utils.retry.retry_call`
   backoff: a transient ``ConnectionError`` / ``TimeoutError`` /
   ``OSError`` tears down the cached connection, re-resolves the master
   endpoint (from the on-disk **endpoint file** the supervisor rewrites
   on respawn — the respawned master may sit on a new port), reconnects,
   and retries the op;
 - reconnects are **generation-fenced**: a durable master advertises a
   monotonic ``store/generation`` key (WAL replay bumps it).  Once a
   client has observed generation ``g >= 1``, a reconnect that finds a
   LOWER generation — in particular a missing key, i.e. a master that
   lost or never had its WAL — is an amnesiac master that forgot every
   barrier arrival and lease; rendezvousing against it would deadlock
   or, worse, release barriers early.  The client refuses, immediately
   and permanently.
 - after ``deadline`` seconds of failed attempts the op raises
   :class:`StoreUnavailableError` naming the endpoint, op, key and
   elapsed time — callers degrade loudly, never hang.

``set``/``get``/``delete``/``wait``/``num_keys`` are idempotent and
retried transparently.  ``add`` is retried too but is **at-least-once**:
a reply lost to the crash re-applies the delta on retry.  Barrier code
must therefore seal on idempotent per-rank keys, not on the counter
value (see ``checkpoint.store_barrier``).
"""
from __future__ import annotations

import logging
import os
import time

from ..utils.retry import retry_call, wait_until

__all__ = ["StoreUnavailableError", "ResilientStore", "GENERATION_KEY",
           "write_endpoint_file", "read_endpoint_file"]

logger = logging.getLogger(__name__)

# mirrors core.store_server.GENERATION_KEY without importing core here
# (this module must stay importable in processes that never load the
# native lib); the test suite pins the two constants equal.
GENERATION_KEY = "store/generation"

_TRANSIENT = (ConnectionError, TimeoutError, OSError)


class StoreUnavailableError(ConnectionError):
    """The store master stayed unreachable (or was fenced as amnesiac)
    past the client's deadline.

    Subclasses ``ConnectionError`` so pre-existing ``except
    ConnectionError`` consumers keep working, but carries structured
    context: ``endpoint``, ``op``, ``key``, ``elapsed``.
    """

    def __init__(self, message, *, endpoint=None, op=None, key=None,
                 elapsed=None):
        super().__init__(message)
        self.endpoint = endpoint
        self.op = op
        self.key = key
        self.elapsed = elapsed


class _FencedMaster(RuntimeError):
    """Internal: reconnect found a lower generation than ever observed.
    Deliberately NOT a ConnectionError so it pierces retry_call's
    ``retry_on=_TRANSIENT`` filter — fencing is terminal, not
    transient."""


def write_endpoint_file(path, host, port):
    """Atomically publish ``host:port`` (tmp + rename: a reader never
    sees a torn endpoint, only the old one or the new one)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="ascii") as f:
        f.write(f"{host}:{int(port)}\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_endpoint_file(path):
    """Parse ``(host, port)`` from an endpoint file; None while the
    file is absent or torn (supervisor mid-respawn)."""
    try:
        with open(path, "r", encoding="ascii") as f:
            text = f.read().strip()
    except (OSError, UnicodeDecodeError):
        return None
    if ":" not in text:
        return None
    host, _, port = text.rpartition(":")
    try:
        return host, int(port)
    except ValueError:
        return None


class ResilientStore:
    """TCPStore client that survives master restarts.

    Fixed endpoint: ``ResilientStore(host, port)``.  Supervised master:
    ``ResilientStore(endpoint_file=...)`` — each (re)connect re-reads
    the file, so a respawn on a new port is transparent.

    ``deadline`` bounds every op's total retry budget; ``store_factory``
    is injectable for tests (defaults to the native TCPStore client).
    """

    def __init__(self, host=None, port=None, *, endpoint_file=None,
                 deadline=60.0, connect_timeout=5.0, store_factory=None):
        if endpoint_file is None and (host is None or port is None):
            raise ValueError("ResilientStore needs host+port or an "
                             "endpoint_file")
        self._host = host
        self._port = port
        self._endpoint_file = endpoint_file
        self.deadline = float(deadline)
        self.connect_timeout = float(connect_timeout)
        self._factory = store_factory or self._default_factory
        self._store = None
        self._gen = None  # highest generation ever observed

    @staticmethod
    def _default_factory(host, port, timeout):
        from ..core import TCPStore
        return TCPStore(host, port, is_master=False, timeout=timeout)

    # -- connection management ----------------------------------------------

    def _resolve(self):
        if self._endpoint_file is not None:
            ep = read_endpoint_file(self._endpoint_file)
            if ep is None:
                raise ConnectionError(
                    f"store endpoint file {self._endpoint_file} absent "
                    f"or unparseable (master not (re)spawned yet?)")
            return ep
        return self._host, self._port

    def _drop(self):
        s, self._store = self._store, None
        if s is not None:
            try:
                s.close()
            except Exception as e:
                logger.debug("store close failed (already dead): %s", e)

    def _connect_once(self):
        host, port = self._resolve()
        store = self._factory(host, port, self.connect_timeout)
        try:
            self._fence(store, host, port)
        except BaseException:
            try:
                store.close()
            except Exception as e:
                logger.debug("store close failed: %s", e)
            raise
        self._store = store
        return store

    def _fence(self, store, host, port):
        """Refuse a master whose generation moved backwards: it lost
        the WAL (or never had one) and forgot this client's barrier
        arrivals/leases."""
        raw = store.get(GENERATION_KEY, wait=False)
        gen = 0
        if raw is not None:
            try:
                gen = int(raw.decode("ascii"))
            except (ValueError, UnicodeDecodeError):
                gen = 0
        if self._gen is not None and self._gen >= 1 and gen < self._gen:
            raise _FencedMaster(
                f"store master at {host}:{port} advertises generation "
                f"{gen} but this client already observed generation "
                f"{self._gen} — an amnesiac master (lost/disabled WAL) "
                f"that forgot barrier and lease state; refusing to "
                f"rendezvous against it")
        if gen > 0:
            self._gen = gen

    def _conn(self):
        return self._store if self._store is not None \
            else self._connect_once()

    # -- op plumbing --------------------------------------------------------

    def _run(self, op, key, fn):
        """Run ``fn(store)`` with transparent reconnect-and-retry; after
        ``self.deadline`` of transient failures (or instantly on a
        fence) raise StoreUnavailableError."""
        t0 = time.monotonic()

        def _attempt():
            try:
                return fn(self._conn())
            except _TRANSIENT:
                self._drop()
                raise

        def _on_retry(attempt, exc, delay):
            logger.warning(
                "store %s(%s) failed (%s: %s); reconnect attempt %d in "
                "%.2fs", op, key if key is not None else "",
                type(exc).__name__, exc, attempt, delay)
            _telemetry_reconnect(op)

        try:
            result = retry_call(_attempt, retry_on=_TRANSIENT,
                                deadline=self.deadline, base=0.05,
                                max_delay=1.0, on_retry=_on_retry)
        except (_FencedMaster, *_TRANSIENT) as e:
            elapsed = time.monotonic() - t0
            endpoint = self._endpoint_str()
            _telemetry_unavailable(elapsed, op=op, endpoint=endpoint)
            raise StoreUnavailableError(
                f"store {op} for key {key!r} failed against master "
                f"{endpoint} after {elapsed:.1f}s "
                f"(deadline {self.deadline:.1f}s): {e}",
                endpoint=endpoint, op=op, key=key,
                elapsed=elapsed) from e
        _telemetry_ok(self._gen)
        return result

    def _endpoint_str(self):
        try:
            host, port = self._resolve()
            return f"{host}:{port}"
        except ConnectionError:
            if self._endpoint_file is not None:
                return f"<unresolved: {self._endpoint_file}>"
            return f"{self._host}:{self._port}"

    # -- public store API ---------------------------------------------------

    @property
    def generation(self):
        """Highest master generation observed (None before the first
        contact with a durable master)."""
        return self._gen

    @property
    def host(self):
        h, _p = (self._resolve() if self._store is None
                 else (self._store.host, self._store.port))
        return h

    @property
    def port(self):
        _h, p = (self._resolve() if self._store is None
                 else (self._store.host, self._store.port))
        return p

    def set(self, key, value):
        """Idempotent; retried transparently."""
        return self._run("set", key,
                         lambda s: s.set(key, value))

    def get(self, key, wait=True, timeout=None):
        """Nonblocking fetch, or ``wait=True`` poll until the key is
        set.  The wait loop lives HERE (client side, over nonblocking
        gets) so an inner TimeoutError can only ever mean connection
        trouble — retryable — never 'key still absent', which must keep
        polling until ``timeout``."""
        if not wait:
            return self._run("get", key,
                             lambda s: s.get(key, wait=False))

        def _poll():
            v = self._run("get", key, lambda s: s.get(key, wait=False))
            return (v,) if v is not None else None  # b"" is a value

        got = _poll()
        if got is None:
            try:
                got = wait_until(_poll, timeout, base=0.01, factor=1.5,
                                 max_delay=0.25, desc=f"key {key!r}")
            except TimeoutError:
                raise TimeoutError(
                    f"store: key '{key}' not set within {timeout}s at "
                    f"{self._endpoint_str()} (a peer rank may have died "
                    f"before rendezvous)")
        return got[0]

    def add(self, key, delta=1):
        """At-least-once under reconnect (a lost reply re-applies the
        delta) — callers needing exactly-once must seal on idempotent
        per-rank keys instead of the counter value."""
        return self._run("add", key, lambda s: s.add(key, delta))

    def delete(self, key):
        return self._run("delete", key, lambda s: s.delete(key))

    def num_keys(self):
        return self._run("num_keys", None, lambda s: s.num_keys())

    def wait(self, keys, timeout=300.0):
        if isinstance(keys, str):
            keys = [keys]
        deadline = time.monotonic() + timeout
        for k in keys:
            self.get(k, wait=True,
                     timeout=max(0.0, deadline - time.monotonic()))

    def close(self):
        self._drop()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False


# -- telemetry shims (observability is optional at this layer) --------------

def _telemetry_ok(generation):
    try:
        from ..observability import get_telemetry
        get_telemetry().record_store_op(generation=generation)
    except Exception as e:
        logger.debug("store telemetry hook failed: %s", e)


def _telemetry_reconnect(op):
    try:
        from ..observability import get_telemetry
        get_telemetry().record_store_reconnect(op)
    except Exception as e:
        logger.debug("store telemetry hook failed: %s", e)


def _telemetry_unavailable(elapsed, op=None, endpoint=None):
    try:
        from ..observability import get_telemetry
        get_telemetry().record_store_unavailable(elapsed, op=op,
                                                 endpoint=endpoint)
    except Exception as e:
        logger.debug("store telemetry hook failed: %s", e)
