"""Global device mesh: the TPU-native replacement for process groups.

Re-design of the reference's communicator bookkeeping
(``python/paddle/distributed/fleet/base/topology.py:58 CommunicateTopology``
and the per-axis NCCL comm creation in ``collective.py:178 new_group`` /
``paddle/phi/core/distributed/comm_context_manager.h:48``): instead of
creating one NCCL communicator per topology axis, we build ONE
``jax.sharding.Mesh`` whose named axes are the parallelism dimensions.
XLA compiles collectives against axis names; ICI/DCN routing is the
compiler's job, not ours.

Axis order follows the reference's hybrid topology order ``[dp, pp,
sharding, mp]`` (``topology.py:58``), extended with ``sep`` (sequence /
context parallel — a new first-class capability, absent in the reference
snapshot per SURVEY §5).
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh

__all__ = ["HYBRID_AXES", "build_mesh", "init_mesh", "get_mesh", "set_mesh",
           "mesh_axis_size", "default_device_count"]

# canonical axis order (outer→inner; mp innermost rides ICI fastest
# links). 'ep' is the expert-parallel axis — MoE dispatch/combine
# einsums sharded over it lower to XLA all_to_all (the reference's
# global_scatter/global_gather NCCL path, moe_layer.py:263).
HYBRID_AXES = ("dp", "pp", "sharding", "sep", "ep", "mp")

_GLOBAL_MESH: Mesh | None = None


def default_device_count() -> int:
    return jax.device_count()


def build_mesh(degrees: dict | None = None, devices=None) -> Mesh:
    """Build a hybrid mesh from per-axis degrees.

    ``degrees`` maps axis name → size (e.g. ``{"dp": 2, "mp": 4}``);
    missing axes get size 1; one unset axis may be -1 to absorb the
    remaining devices. Axes of size 1 are still present in the mesh so
    sharding specs can always name them.
    """
    degrees = dict(degrees or {})
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    sizes = []
    infer_idx = None
    for ax in HYBRID_AXES:
        d = int(degrees.pop(ax, 1))
        if d == -1:
            infer_idx = len(sizes)
            d = 1
        sizes.append(d)
    if degrees:
        raise ValueError(f"unknown mesh axes {sorted(degrees)}; "
                         f"valid: {HYBRID_AXES}")
    prod = int(np.prod(sizes))
    if infer_idx is not None:
        if n % prod:
            raise ValueError(f"{n} devices not divisible by {prod}")
        sizes[infer_idx] = n // prod
        prod = n
    if prod > n:
        raise ValueError(f"mesh {dict(zip(HYBRID_AXES, sizes))} needs {prod} "
                         f"devices, have {n}")
    devs = np.array(devices[:prod]).reshape(sizes)
    return Mesh(devs, axis_names=HYBRID_AXES)


def init_mesh(degrees: dict | None = None, devices=None) -> Mesh:
    global _GLOBAL_MESH
    _GLOBAL_MESH = build_mesh(degrees, devices)
    return _GLOBAL_MESH


def set_mesh(mesh: Mesh | None):
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh


def get_mesh(create_default=True) -> Mesh | None:
    """Current global mesh; lazily a pure-dp mesh over all devices."""
    global _GLOBAL_MESH
    if _GLOBAL_MESH is None and create_default:
        _GLOBAL_MESH = build_mesh({"dp": -1})
    return _GLOBAL_MESH


def mesh_axis_size(axis: str) -> int:
    m = get_mesh()
    return m.shape[axis] if m is not None and axis in m.shape else 1
