"""Whole-process kill injection for fault drills.

Unlike tests/fault_injection.py (which raises a catchable exception
through the write seams), this module's only weapon is
``SIGKILL(self)`` — nothing unwinds, no ``finally`` runs, fds and
barrier membership vanish exactly as on a real preemption or OOM kill.

Armed from environment variables (set by the drill runner on every
worker; each worker self-selects by rank):

 - ``DRILL_KILL_PHASE``: ``mid-stage`` | ``pre-marker`` | ``mid-marker``
   | ``mid-barrier`` | ``none``/unset
 - ``DRILL_KILL_STEP``:  the checkpoint step whose save is sabotaged
 - ``DRILL_KILL_RANK``:  which rank dies (compared to ``DRILL_RANK``)

The patches target the same module-level seams the in-process fault
harness uses (``_write_file`` / ``_write_commit_marker`` /
``_barrier_arrive``), so a drill exercises the identical code paths a
production save takes.
"""
from __future__ import annotations

import os
import signal

from .. import checkpoint as _ckpt

__all__ = ["PHASES", "install", "install_from_env"]

PHASES = ("mid-stage", "pre-marker", "mid-marker", "mid-barrier")


def _die():
    """SIGKILL our own process — the one fault no handler can soften."""
    os.kill(os.getpid(), signal.SIGKILL)


def _torn_write(path, data):
    """Leave a half-written file behind, bypassing fsync — what the
    kernel plausibly persists when a process dies mid-write."""
    with open(path, "wb") as f:
        f.write(data[: max(1, len(data) // 2)])


def install(phase, step):
    """Patch the checkpoint seams so THIS process SIGKILLs itself at
    ``phase`` of the save of checkpoint step ``step``."""
    if phase not in PHASES:
        raise ValueError(f"unknown drill phase {phase!r}; "
                         f"expected one of {PHASES}")
    needle = f"step_{int(step):08d}"
    real_write = _ckpt._write_file
    real_marker = _ckpt._write_commit_marker
    real_arrive = _ckpt._barrier_arrive

    if phase == "mid-stage":
        def _write(path, data, durable=True):
            if needle in path and f"{os.sep}data{os.sep}" in path:
                _torn_write(path, data)
                _die()
            return real_write(path, data, durable=durable)
        _ckpt._write_file = _write
    elif phase == "mid-marker":
        def _write(path, data, durable=True):
            if needle in path and \
                    os.path.basename(path).startswith("COMMIT."):
                _torn_write(path, data)
                _die()
            return real_write(path, data, durable=durable)
        _ckpt._write_file = _write
    elif phase == "pre-marker":
        def _marker(root, proc, world, manifest, durable=True,
                    nonce=None):
            if needle in root:
                _die()
            return real_marker(root, proc, world, manifest,
                               durable=durable, nonce=nonce)
        _ckpt._write_commit_marker = _marker
    else:  # mid-barrier: announce arrival, then die before the seal
        def _arrive(store, key, rank=None):
            if needle in key:
                real_arrive(store, key, rank)
                _die()
            return real_arrive(store, key, rank)
        _ckpt._barrier_arrive = _arrive


def install_from_env():
    """Arm the kill described by ``DRILL_KILL_*`` if this rank is the
    victim; returns True when armed."""
    phase = os.environ.get("DRILL_KILL_PHASE", "")
    if not phase or phase == "none":
        return False
    rank = int(os.environ.get("DRILL_RANK", "0"))
    victim = int(os.environ.get("DRILL_KILL_RANK", "0"))
    if rank != victim:
        return False
    install(phase, int(os.environ.get("DRILL_KILL_STEP", "0")))
    return True
