"""Multi-process fault drills: kill a REAL process mid-save, prove the
fleet recovers.

The crash-consistency layer (:mod:`..checkpoint`) is easy to test with
simulated kills (tests/fault_injection.py raises through the write
seams) — but a simulated kill cannot lie about OS-level atomicity the
way a real SIGKILL can: a whole process dying takes its page cache,
its file descriptors and its barrier participation with it.  This
package drills exactly that:

 - :mod:`.runner` spawns N real worker subprocesses coordinated by a
   TCPStore (``JAX_PLATFORMS=cpu`` — the protocol under test is
   filesystem + store, not XLA), SIGKILLs a scripted victim at a
   scripted phase of a scripted save, then asserts the survivors fail
   *cleanly* and a relaunched fleet — possibly at a different world
   size — restores the newest fully-committed step bit-for-bit.
 - :mod:`.worker` is the subprocess entry point
   (``python -m paddle_tpu.distributed.drill.worker``): a deterministic
   numpy "training" loop whose state is saved through
   :class:`~paddle_tpu.distributed.checkpoint_manager.CheckpointManager`
   with :class:`~paddle_tpu.distributed.checkpoint.HostLocalShard`
   row-partitioned leaves, so the runner can replay a bit-exact oracle.
 - :mod:`.injector` arms the kill: SIGKILL of the *whole process* at
   one of four phases of the commit protocol — ``mid-stage`` (torn
   data file), ``pre-marker`` (all data staged, no COMMIT marker),
   ``mid-marker`` (torn COMMIT marker), ``mid-barrier`` (marker
   durable, victim announced at the commit barrier, then death).

What each phase proves (victim = non-zero rank, staged store commit):

 ============  =====================================================
 phase         expected recovery
 ============  =====================================================
 mid-stage     staging dir torn → step K never promotes; resume K-1
 pre-marker    victim's marker missing → barrier times out naming
               the victim's rank; resume K-1
 mid-marker    torn COMMIT bytes stay in staging; resume K-1
 mid-barrier   victim arrived ⇒ rank 0 promotes K; survivors fail at
               K+1; resume K (kill rank 0 instead ⇒ no promote, K-1)
 ============  =====================================================

Store-failover drills (:func:`.runner.run_store_kill_drill`) invert
the victim: the TCPStore MASTER itself is SIGKILLed mid-save while
every worker rank is provably in-flight (a ready/go rendezvous through
the doomed master), then respawned from its WAL
(:mod:`paddle_tpu.core.store_server`) — clients reconnect through
:class:`~paddle_tpu.distributed.resilient_store.ResilientStore`, the
respawned master seals the commit barrier from REPLAYED arrivals, and
the run finishes bit-for-bit.  Respawned WITHOUT the WAL, the
generation fence trips and every rank exits ``EXIT_STORE_LOST``
within its deadline instead of hanging.

Scrape drills (:func:`.runner.run_scrape_drill`) exercise the
cluster-observability plane instead of the checkpoint plane: every
worker publishes its real /metrics endpoint into the store, a real
aggregator subprocess (``python -m paddle_tpu.observability.aggregator``)
discovers and scrapes the fleet, and the drill proves summed counters,
merged histogram buckets, nonzero cross-rank step-time skew, the
cross-rank recompile-storm alarm, stale-marking of a SIGKILLed rank
(bounded — never a hang), aggregator restart reconvergence, and the
``observability.merge`` CLI stitching per-rank telemetry JSONL into
one time-ordered stream.

Supervisor drills (:func:`.runner.run_supervisor_drill`) put the
self-healing supervisor (:mod:`paddle_tpu.distributed.supervisor`) on
trial: a SIGKILLed worker must cost exactly one budgeted fleet
relaunch and still converge bit-for-bit; a SIGKILLed store MASTER must
cost *nothing* — the supervisor's hot standby (a
:class:`~paddle_tpu.core.store_server.StoreFollower` tailing the WAL)
is promoted, the endpoint file atomically republished, and every
worker rides through with zero exits at a bumped store generation; a
deterministically crash-looping rank must exhaust its restart budget
and fail the job naming the rank and its quarantined data shard.

Serve chaos drills (:func:`.runner.run_serve_chaos_drill`) point the
same real-subprocess discipline at the serving plane: a real engine
(``python -m paddle_tpu.serving``) is SIGKILLed mid-decode (the
relaunch must rebuild its AOT ladder, report a clean page pool, and
serve bit-identically to a solo-decode oracle with zero request-path
compiles), deadline-stormed (every infeasible deadline shed 429 +
Retry-After, zero page leaks afterward), abandoned by a disconnecting
client (cancelled, pages recovered), and finally SIGTERMed under load
(in-flight requests complete in full, drain-window admission answers
503, exit code 143).

Trace drills (:func:`.runner.run_trace_drill`) exercise the step
tracer: every worker records a deterministic staggered
compute/collective step profile, exports a per-rank Chrome trace and
a flight dump, and the ``observability.merge --trace`` CLI stitches
the per-rank files into ONE schema-valid cluster timeline (rank as
pid) with a strictly positive measured overlap fraction.  Fault
drills run with ``flight_dir`` set additionally prove the SIGKILLed
victim left a parseable flight-recorder dump behind.

Numerics drills (:func:`.runner.run_numerics_drill`) exercise the
numerics sentinels end-to-end: every worker trains a REAL captured
MLP with the monitor armed, one rank's input is poisoned with a NaN
at a scripted step (same shape/dtype — no retrace), and the drill
proves the poisoned rank detected the trip within one cadence window,
named the offending parameter path, and left a flight dump carrying
that name — while every clean rank stayed quiet and each captured
step compiled exactly once.  The halt variant proves
``PT_NUMERICS_HALT`` converts the trip into a clean
``EXIT_NUMERICS_HALT`` exit instead of a poisoned-forever run.

OOM drills (:func:`.runner.run_oom_drill`) exercise the memory
postmortem end-to-end: every worker trains a REAL captured MLP with
the memory monitor armed, one rank's compiled entry is swapped for a
``RESOURCE_EXHAUSTED``-raising callable at a scripted step, and the
drill proves the capture intercept booked a flight dump pinning
``oom:<program>:<parameter path>`` (census + per-program footprints +
watermark history in ``extra.memory``), the victim exited ``EXIT_OOM``
cleanly, clean ranks booked nothing — and, replaying each rank's
metrics exposition through a local aggregator, that the fleet sees the
cross-rank memory skew and the near-OOM health trip.

SDC drills (:func:`.runner.run_sdc_drill`) exercise the
silent-data-corruption sentry end-to-end: ``world`` dp-replica
workers train the SAME captured MLP from the SAME seed (bit-identical
by construction) with the consensus fingerprints armed, one rank
flips ONE mantissa bit of a parameter mid-run — finite everywhere,
invisible to the numerics sentinel — and the drill proves the
majority vote fingered exactly that rank within one cadence window,
named a divergent tensor, pinned a flight dump, and halted the victim
into a clean ``EXIT_SDC`` while clean ranks attributed the verdict
and finished.  The quarantine scenario reruns the poisoned fleet
under a real Supervisor: repeated verdicts charge the hardware ledger
(never the code-crash budget), quarantine the rank, and the fleet
downsizes elastically around the suspect host; the restore scenario
plants a bit flip UNDER a committed checkpoint's manifest CRC
(:func:`.runner.poison_shard`) and proves only the per-leaf content
digests refuse the restore, naming the leaf.

Overlap drills (:func:`.runner.run_overlap_drill`) exercise the
optimization half of GC3: the span timelines pinned down by the
bucketed vs monolithic gradient reduction (real ``partition_buckets``
output, synthetic timestamps) feed the real tracer, proving the
measured ``pt_compute_collective_overlap_fraction`` is strictly
higher with bucketing enabled than disabled.  The sharded-mesh
variant (:func:`.runner.run_sharded_overlap_drill`) replays the ZeRO
dp×sharding timelines — the GSPMD monolithic reduction vs the
planned per-bucket ``reduce_scatter → all_reduce → all_gather``
schedule — and proves the scheduled buckets lift overlap from 0 to
above one half.
"""
__all__ = ["KillSpec", "StoreKillSpec", "ObsSpec", "TraceSpec",
           "NumericsSpec", "OomSpec", "SdcSpec", "run_drill",
           "run_store_kill_drill", "run_scrape_drill",
           "run_serve_chaos_drill", "run_supervisor_drill",
           "run_trace_drill", "run_numerics_drill", "run_oom_drill",
           "run_sdc_drill", "run_overlap_drill",
           "run_sharded_overlap_drill", "poison_shard",
           "spawn_worker", "spawn_store_master", "spawn_aggregator",
           "spawn_serve_worker", "reap_all"]


def __getattr__(name):
    # lazy: `python -m paddle_tpu.distributed.drill.worker` must not
    # pre-import the worker module through the package (runpy warns),
    # and a worker subprocess has no use for the runner
    if name in __all__:
        from . import runner
        return getattr(runner, name)
    raise AttributeError(name)
