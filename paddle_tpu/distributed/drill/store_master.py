"""Standalone TCPStore-master process for store-failover drills.

Run by FILE PATH (``python .../drill/store_master.py``), never as a
package module: a respawn after SIGKILL must cost one interpreter
start, not a jax import, so this script path-loads the stdlib-only
``paddle_tpu.core.store_server`` module directly and touches nothing
else in the package.

Publishes ``host:port`` to ``--endpoint-file`` (atomic tmp+rename)
once the server is listening — the drill runner and every
ResilientStore client resolve the master through that file, so a
respawn on a fresh ephemeral port is transparent.  ``--wal`` makes the
master durable (replay + generation bump); omit it to spawn the
amnesiac master the fencing drills need.
"""
from __future__ import annotations

import argparse
import logging
import os
import sys
import threading

# load core/store_server.py as a top-level module: no package import,
# no native lib, no jax — the whole point of the standalone entry
_CORE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "core")
sys.path.insert(0, _CORE_DIR)
import store_server  # noqa: E402

logger = logging.getLogger("paddle_tpu.drill.store_master")


def _write_endpoint(path, host, port):
    # atomic publish (mirrors resilient_store.write_endpoint_file,
    # which this script must not import)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="ascii") as f:
        f.write(f"{host}:{int(port)}\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--endpoint-file", required=True)
    ap.add_argument("--wal", default=None,
                    help="WAL path; omit for a volatile (amnesiac) "
                         "master")
    args = ap.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO, stream=sys.stderr,
        format="[store-master] %(levelname)s %(message)s")

    server = store_server.DurableTCPStoreServer(
        port=args.port, host=args.host, wal_path=args.wal)
    _write_endpoint(args.endpoint_file, server.host, server.port)
    logger.info("serving on %s:%d (generation=%s, wal=%s)",
                server.host, server.port, server.generation, args.wal)
    # block until killed — the drill's weapon is SIGKILL, so there is
    # deliberately no graceful-shutdown path to hide behind (bounded
    # waits in a loop, never one unbounded park)
    hold = threading.Event()
    while not hold.wait(60.0):
        pass


if __name__ == "__main__":
    main()
