"""Standalone TCPStore-master process for store-failover drills.

Run by FILE PATH (``python .../drill/store_master.py``), never as a
package module: a respawn after SIGKILL must cost one interpreter
start, not a jax import, so this script path-loads the stdlib-only
``paddle_tpu.core.store_server`` module directly and touches nothing
else in the package.

Publishes ``host:port`` to ``--endpoint-file`` (atomic tmp+rename)
once the server is listening — the drill runner and every
ResilientStore client resolve the master through that file, so a
respawn on a fresh ephemeral port is transparent.  ``--wal`` makes the
master durable (replay + generation bump); omit it to spawn the
amnesiac master the fencing drills need.
"""
from __future__ import annotations

import argparse
import logging
import os
import sys
import threading

# load core/store_server.py as a top-level module: no package import,
# no native lib, no jax — the whole point of the standalone entry
_CORE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "core")
sys.path.insert(0, _CORE_DIR)
import store_server  # noqa: E402

logger = logging.getLogger("paddle_tpu.drill.store_master")


def _write_endpoint(path, host, port):
    # atomic publish (mirrors resilient_store.write_endpoint_file,
    # which this script must not import)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="ascii") as f:
        f.write(f"{host}:{int(port)}\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _hold_forever():
    # block until killed — the drill's weapon is SIGKILL, so there is
    # deliberately no graceful-shutdown path to hide behind (bounded
    # waits in a loop, never one unbounded park)
    hold = threading.Event()
    while not hold.wait(60.0):
        pass


def _standby_main(args):
    """Hot-standby mode: tail the master's WAL with a StoreFollower,
    promote the moment ``--promote-file`` appears, atomically republish
    the endpoint file, then serve until killed.

    The promote trigger is a file (touched by the supervisor) rather
    than a signal so the drill can assert the exact promote moment and
    the standby stays testable on any POSIX host.
    """
    follower = store_server.StoreFollower(args.wal)
    logger.info("standby tailing %s (promote trigger: %s)",
                args.wal, args.promote_file)
    wait = threading.Event()
    while not os.path.exists(args.promote_file):
        follower.poll()
        wait.wait(args.poll_interval)
    server = follower.promote(port=args.port, host=args.host)
    _write_endpoint(args.endpoint_file, server.host, server.port)
    logger.info(
        "promoted: serving on %s:%d (generation=%s, %d records tailed)",
        server.host, server.port, server.generation,
        follower.records_applied)
    _hold_forever()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--endpoint-file", required=True)
    ap.add_argument("--wal", default=None,
                    help="WAL path; omit for a volatile (amnesiac) "
                         "master")
    ap.add_argument("--standby", action="store_true",
                    help="hot-standby mode: tail --wal without serving, "
                         "promote (and republish --endpoint-file) when "
                         "--promote-file appears")
    ap.add_argument("--promote-file", default=None,
                    help="standby mode's promote trigger file")
    ap.add_argument("--poll-interval", type=float, default=0.05,
                    help="standby WAL/trigger poll cadence (seconds)")
    args = ap.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO, stream=sys.stderr,
        format="[store-master] %(levelname)s %(message)s")

    if args.standby:
        if not args.wal or not args.promote_file:
            ap.error("--standby requires --wal and --promote-file")
        _standby_main(args)
        return

    server = store_server.DurableTCPStoreServer(
        port=args.port, host=args.host, wal_path=args.wal)
    _write_endpoint(args.endpoint_file, server.host, server.port)
    logger.info("serving on %s:%d (generation=%s, wal=%s)",
                server.host, server.port, server.generation, args.wal)
    _hold_forever()


if __name__ == "__main__":
    main()
