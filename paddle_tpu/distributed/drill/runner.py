"""Drill runner: spawn real worker fleets, kill one, prove recovery.

The runner is the drill's control plane AND its oracle: it hosts the
TCPStore master, launches each generation of workers
(``python -m paddle_tpu.distributed.drill.worker``), waits for the
scripted SIGKILL to play out, then independently replays the
deterministic update (:func:`..drill.worker.advance`) and compares the
newest committed checkpoint bit-for-bit (``ndarray.tobytes()`` — CRC
verification happens inside ``verify_checkpoint`` first).

Every spawned process is tracked in a module-level registry so a test
harness can guarantee no leaked children regardless of how an
assertion fails (see tests/drills/conftest.py's reaper fixture).
"""
from __future__ import annotations

import logging
import os
import signal
import subprocess
import sys
import uuid

from ...core import TCPStore
from ...utils.retry import wait_until
from ..checkpoint import read_leaf, verify_checkpoint
from ..checkpoint_manager import CheckpointManager
from .worker import EXIT_SAVE_FAILED, advance, init_state

__all__ = ["KillSpec", "DrillFailure", "spawn_worker", "run_drill",
           "reap_all"]

logger = logging.getLogger(__name__)

# repo root (…/paddle_tpu/distributed/drill/runner.py → 4 levels up) so
# spawned workers can import the package without an install step
_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

_LIVE: set = set()  # every Popen this module ever spawned, minus reaped


class DrillFailure(AssertionError):
    """A drill's recovery invariant did not hold."""


class KillSpec:
    """Scripted kill: SIGKILL ``rank`` at ``phase`` of step ``step``'s
    save (phases: see :mod:`.injector`)."""

    __slots__ = ("phase", "step", "rank")

    def __init__(self, phase, step, rank=1):
        self.phase = phase
        self.step = int(step)
        self.rank = int(rank)

    def expected_commit(self):
        """Newest step that must be committed after this kill plays
        out: ``mid-barrier`` is the one phase where the victim has
        already sealed its part, so rank 0 still promotes step K —
        unless the victim IS rank 0, which dies before promoting."""
        if self.phase == "mid-barrier" and self.rank != 0:
            return self.step
        return self.step - 1


def reap_all():
    """SIGKILL + wait every worker this module spawned and is still
    tracking — the no-leaked-children guarantee for test harnesses."""
    for p in list(_LIVE):
        if p.poll() is None:
            try:
                p.kill()
            except OSError:
                pass
        try:
            p.wait(timeout=10)
        except Exception:
            logger.warning("drill reaper: pid %s did not exit", p.pid)
        _LIVE.discard(p)


def spawn_worker(rank, world, *, root, port, total_steps, run_id,
                 barrier_timeout, kill=None, elastic=True,
                 orphan_age=None, log_path=None):
    """Launch one drill worker subprocess; returns its Popen (also
    registered for :func:`reap_all`)."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("DRILL_")}
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PT_RUN_ID": run_id,
        "DRILL_RANK": str(rank),
        "DRILL_WORLD": str(world),
        "DRILL_CKPT": root,
        "DRILL_STORE_PORT": str(port),
        "DRILL_TOTAL_STEPS": str(total_steps),
        "DRILL_RUN_ID": run_id,
        "DRILL_BARRIER_TIMEOUT": str(barrier_timeout),
        "DRILL_ELASTIC": "1" if elastic else "0",
    })
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    if orphan_age is not None:
        env["DRILL_ORPHAN_AGE"] = str(orphan_age)
    if kill is not None:
        env["DRILL_KILL_PHASE"] = kill.phase
        env["DRILL_KILL_STEP"] = str(kill.step)
        env["DRILL_KILL_RANK"] = str(kill.rank)
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.drill.worker"]
    if log_path:
        with open(log_path, "ab") as out:
            p = subprocess.Popen(cmd, env=env, stdout=out,
                                 stderr=subprocess.STDOUT)
    else:
        p = subprocess.Popen(cmd, env=env,
                             stdout=subprocess.DEVNULL,
                             stderr=subprocess.DEVNULL)
    _LIVE.add(p)
    return p


def _wait_fleet(procs, timeout):
    """Block until every proc exits; returns their return codes.  On
    timeout the fleet is reaped and the drill fails."""
    try:
        wait_until(lambda: all(p.poll() is not None for p in procs),
                   timeout, desc=f"drill fleet of {len(procs)} to exit")
    except TimeoutError as e:
        reap_all()
        raise DrillFailure(f"drill generation hung: {e}") from e
    rcs = []
    for p in procs:
        rcs.append(p.wait())
        _LIVE.discard(p)
    return rcs


def _latest_step(root):
    # read-only probe (orphan_age=None: the probe must not janitor)
    return CheckpointManager(root, keep_last_n=None,
                             orphan_age=None).latest_step()


def _verify_bit_for_bit(root, step):
    """CRC-verify step's checkpoint, then compare every leaf byte-wise
    against the replayed oracle."""
    d = os.path.join(root, f"step_{int(step):08d}")
    verify_checkpoint(d, integrity="full")
    w0, b0 = init_state()
    we, be = advance(w0, b0, int(step))
    w = read_leaf(d, "w", integrity="off")
    b = read_leaf(d, "bias", integrity="off")
    if w.tobytes() != we.tobytes() or b.tobytes() != be.tobytes():
        raise DrillFailure(
            f"step {step} restored state is not bit-identical to the "
            f"oracle replay (max |w-we| = {abs(w - we).max()})")


def run_drill(root, generations, total_steps, *, barrier_timeout=6.0,
              gen_timeout=120.0, orphan_age=None, log_dir=None):
    """Run a multi-generation fault drill.

    ``generations``: list of ``(world_size, KillSpec-or-None)``.  Each
    generation is a full fleet launch sharing the checkpoint ``root``;
    a generation with a kill is expected to end with the victim
    SIGKILLed (rc ``-9``) and every survivor exiting
    ``EXIT_SAVE_FAILED`` after its commit barrier names the dead rank
    — after which the newest committed step must equal the kill's
    :meth:`KillSpec.expected_commit` and verify bit-for-bit.  The last
    generation should have no kill: it must run to ``total_steps`` with
    every rank exiting 0, resuming elastically when its world size
    differs from the writer's.

    Returns a per-generation report (worlds, return codes, newest
    committed step) for further assertions.
    """
    master = TCPStore("127.0.0.1", 0, is_master=True)
    report = []
    try:
        for g, (world, kill) in enumerate(generations):
            run_id = f"g{g}-{uuid.uuid4().hex[:6]}"
            procs = [
                spawn_worker(
                    r, world, root=root, port=master.port,
                    total_steps=total_steps, run_id=run_id,
                    barrier_timeout=barrier_timeout, kill=kill,
                    orphan_age=orphan_age,
                    log_path=(os.path.join(log_dir, f"gen{g}_rank{r}.log")
                              if log_dir else None))
                for r in range(world)
            ]
            rcs = _wait_fleet(procs, gen_timeout)
            latest = _latest_step(root)
            report.append({"world": world, "rcs": rcs, "latest": latest})
            if kill is None:
                if any(rc != 0 for rc in rcs):
                    raise DrillFailure(
                        f"generation {g} (no kill) exit codes {rcs}")
                if latest != total_steps:
                    raise DrillFailure(
                        f"generation {g} finished but newest committed "
                        f"step is {latest}, wanted {total_steps}")
            else:
                if rcs[kill.rank] != -signal.SIGKILL:
                    raise DrillFailure(
                        f"generation {g}: victim rank {kill.rank} "
                        f"exited {rcs[kill.rank]}, expected SIGKILL")
                survivors = [rc for r, rc in enumerate(rcs)
                             if r != kill.rank]
                if any(rc != EXIT_SAVE_FAILED for rc in survivors):
                    raise DrillFailure(
                        f"generation {g}: survivor exit codes "
                        f"{survivors}, expected all {EXIT_SAVE_FAILED}")
                want = kill.expected_commit()
                if (latest or 0) != want:
                    raise DrillFailure(
                        f"generation {g}: newest committed step is "
                        f"{latest} after a {kill.phase} kill at step "
                        f"{kill.step}, expected {want}")
            if latest is not None:
                _verify_bit_for_bit(root, latest)
    finally:
        reap_all()
        master.close()
    return report
