"""Drill runner: spawn real worker fleets, kill one, prove recovery.

The runner is the drill's control plane AND its oracle: it hosts the
TCPStore master, launches each generation of workers
(``python -m paddle_tpu.distributed.drill.worker``), waits for the
scripted SIGKILL to play out, then independently replays the
deterministic update (:func:`..drill.worker.advance`) and compares the
newest committed checkpoint bit-for-bit (``ndarray.tobytes()`` — CRC
verification happens inside ``verify_checkpoint`` first).

Every spawned process is tracked in a module-level registry so a test
harness can guarantee no leaked children regardless of how an
assertion fails (see tests/drills/conftest.py's reaper fixture).
"""
from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request
import uuid

from ...core import TCPStore
from ...utils.retry import wait_until
from ..checkpoint import (CheckpointCorruptError, read_leaf,
                          verify_checkpoint)
from ..checkpoint_manager import CheckpointManager
from ..resilient_store import ResilientStore, read_endpoint_file
from .worker import (EXIT_NUMERICS_HALT, EXIT_OOM, EXIT_SAVE_FAILED,
                     EXIT_SDC, EXIT_STORE_LOST, advance, init_state,
                     numerics_report_path, obs_ready_key,
                     obs_release_key, oom_metrics_path,
                     oom_report_path, sdc_report_path,
                     trace_report_path)

__all__ = ["KillSpec", "StoreKillSpec", "ObsSpec", "TraceSpec",
           "NumericsSpec", "OomSpec", "SdcSpec", "DrillFailure",
           "spawn_worker", "spawn_store_master", "spawn_aggregator",
           "spawn_serve_worker", "poison_shard", "run_drill",
           "run_store_kill_drill", "run_scrape_drill",
           "run_serve_chaos_drill", "run_supervisor_drill",
           "run_trace_drill", "run_numerics_drill", "run_oom_drill",
           "run_sdc_drill", "run_overlap_drill",
           "run_sharded_overlap_drill", "reap_all"]

logger = logging.getLogger(__name__)

# repo root (…/paddle_tpu/distributed/drill/runner.py → 4 levels up) so
# spawned workers can import the package without an install step
_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

_LIVE: set = set()  # every Popen this module ever spawned, minus reaped


class DrillFailure(AssertionError):
    """A drill's recovery invariant did not hold."""


class KillSpec:
    """Scripted kill: SIGKILL ``rank`` at ``phase`` of step ``step``'s
    save (phases: see :mod:`.injector`)."""

    __slots__ = ("phase", "step", "rank")

    def __init__(self, phase, step, rank=1):
        self.phase = phase
        self.step = int(step)
        self.rank = int(rank)

    def expected_commit(self):
        """Newest step that must be committed after this kill plays
        out: ``mid-barrier`` is the one phase where the victim has
        already sealed its part, so rank 0 still promotes step K —
        unless the victim IS rank 0, which dies before promoting."""
        if self.phase == "mid-barrier" and self.rank != 0:
            return self.step
        return self.step - 1


class ObsSpec:
    """Scripted cluster-observability worker (``DRILL_OBS=1``): enable
    real telemetry, publish the /metrics endpoint, record a rank-skewed
    synthetic step profile (+ optionally a genuine recompile-sentinel
    trip), then hold the endpoint open until released."""

    __slots__ = ("telemetry_dir", "step_base", "storm",
                 "sentinel_threshold", "hold_timeout", "anomalies",
                 "mem_bytes", "shed", "served", "sdc_verdicts")

    def __init__(self, telemetry_dir, step_base=0.01, storm=True,
                 sentinel_threshold=3, hold_timeout=120.0,
                 anomalies=0, mem_bytes=0, shed=0, served=0,
                 sdc_verdicts=0):
        self.telemetry_dir = telemetry_dir
        self.step_base = float(step_base)
        self.storm = bool(storm)
        self.sentinel_threshold = int(sentinel_threshold)
        self.hold_timeout = float(hold_timeout)
        self.anomalies = int(anomalies)
        # nonzero: feed a rank-scaled synthetic memory watermark
        # (mem_bytes * (1 + rank)) so the aggregator's skew/near-OOM
        # derivations are assertable
        self.mem_bytes = int(mem_bytes)
        # scripted serve admission profile: each rank books ``shed``
        # load-shed refusals and ``served`` accepted requests, so the
        # aggregator's fleet shed ratio is exactly
        # shed / (shed + served) and its shed-storm alarm assertable
        self.shed = int(shed)
        self.served = int(served)
        # scripted SDC consensus verdicts: each rank books this many
        # pt_sdc_divergence_total increments (fingering a fixed peer,
        # halt disarmed), arming the aggregator's cluster SDC alarm
        self.sdc_verdicts = int(sdc_verdicts)


class TraceSpec:
    """Scripted step-tracing worker (``DRILL_TRACE=1``): enable the
    real tracer, record a deterministic staggered compute/collective
    step profile (synthetic timestamps, no sleeping), export a
    per-rank Chrome trace into ``trace_dir`` and — when ``flight_dir``
    is set — a flight dump, then write a report JSON with the tracer
    snapshot."""

    __slots__ = ("trace_dir", "flight_dir", "step_ms")

    def __init__(self, trace_dir, flight_dir=None, step_ms=10.0):
        self.trace_dir = trace_dir
        self.flight_dir = flight_dir
        self.step_ms = float(step_ms)


class NumericsSpec:
    """Scripted NaN-injection worker (``DRILL_NUMERICS=1``): train a
    real captured MLP with the numerics monitor armed, poison one
    input element with NaN on ``poison_rank`` at ``poison_step``, and
    write a per-rank detection report into ``out_dir``.  ``halt``
    arms ``PT_NUMERICS_HALT`` semantics (worker exits
    ``EXIT_NUMERICS_HALT`` after the sentinel raises)."""

    __slots__ = ("out_dir", "poison_step", "poison_rank", "cadence",
                 "halt")

    def __init__(self, out_dir, poison_step=5, poison_rank=1,
                 cadence=4, halt=False):
        self.out_dir = out_dir
        self.poison_step = int(poison_step)
        self.poison_rank = int(poison_rank)
        self.cadence = int(cadence)
        self.halt = bool(halt)


class OomSpec:
    """Scripted allocator-exhaustion worker (``DRILL_OOM=1``): train a
    real captured MLP with the memory monitor armed, inject a
    ``RESOURCE_EXHAUSTED`` into ``oom_rank``'s compiled entry at
    ``oom_step``, and write the postmortem evidence (report + metrics
    exposition) into ``out_dir``.  ``mem_bytes`` scales each rank's
    synthetic watermark feed (rank r exports ``mem_bytes * (1 + r)``)."""

    __slots__ = ("out_dir", "oom_step", "oom_rank", "mem_bytes")

    def __init__(self, out_dir, oom_step=5, oom_rank=1,
                 mem_bytes=1_000_000):
        self.out_dir = out_dir
        self.oom_step = int(oom_step)
        self.oom_rank = int(oom_rank)
        self.mem_bytes = int(mem_bytes)


class SdcSpec:
    """Scripted silent-data-corruption worker (``DRILL_SDC=1``): every
    rank trains the SAME captured MLP from the SAME seed with the SDC
    sentry armed and its fingerprint exchange wired to the drill
    store; ``poison_rank`` (-1 = nobody) flips one mantissa bit of its
    first captured parameter at ``poison_step``."""

    __slots__ = ("out_dir", "poison_step", "poison_rank", "cadence",
                 "bit", "exchange_timeout")

    def __init__(self, out_dir, poison_step=5, poison_rank=1,
                 cadence=4, bit=3, exchange_timeout=30.0):
        self.out_dir = out_dir
        self.poison_step = int(poison_step)
        self.poison_rank = int(poison_rank)
        self.cadence = int(cadence)
        self.bit = int(bit)
        self.exchange_timeout = float(exchange_timeout)


class StoreKillSpec:
    """Scripted STORE-MASTER kill: every rank rendezvouses at ``phase``
    of step ``step``'s save (``pre-save`` | ``mid-barrier``), and the
    runner SIGKILLs the master inside that window.  ``timeout`` bounds
    each rank's wait for the post-respawn release key."""

    __slots__ = ("phase", "step", "timeout")

    def __init__(self, phase, step, timeout=60.0):
        if phase not in ("pre-save", "mid-barrier"):
            raise ValueError(f"unknown storekill phase {phase!r}")
        self.phase = phase
        self.step = int(step)
        self.timeout = float(timeout)


def reap_all():
    """SIGKILL + wait every worker this module spawned and is still
    tracking — the no-leaked-children guarantee for test harnesses."""
    for p in list(_LIVE):
        if p.poll() is None:
            try:
                p.kill()
            except OSError:
                pass
        try:
            p.wait(timeout=10)
        except Exception:
            logger.warning("drill reaper: pid %s did not exit", p.pid)
        _LIVE.discard(p)


def spawn_worker(rank, world, *, root, port=0, total_steps, run_id,
                 barrier_timeout, kill=None, elastic=True,
                 orphan_age=None, log_path=None, endpoint_file=None,
                 store_deadline=None, storekill=None, obs=None,
                 trace=None, numerics=None, oom=None, sdc=None,
                 restore_integrity=None, flight_dir=None,
                 fail=None, data_shard=None):
    """Launch one drill worker subprocess; returns its Popen (also
    registered for :func:`reap_all`).

    ``endpoint_file`` switches the worker to a ResilientStore resolved
    through that file (the store-failover mode; ``port`` is then
    ignored); ``storekill`` (a :class:`StoreKillSpec`) arms the
    master-kill rendezvous in every rank; ``obs`` (an
    :class:`ObsSpec`) switches the worker to the cluster-observability
    mode (requires ``endpoint_file``; ``total_steps`` becomes the
    synthetic step count); ``trace`` (a :class:`TraceSpec`) switches
    to the storeless step-tracing mode; ``numerics`` (a
    :class:`NumericsSpec`) switches to the storeless NaN-injection
    mode; ``oom`` (an :class:`OomSpec`) switches to the storeless
    OOM-postmortem mode; ``sdc`` (an :class:`SdcSpec`) switches to the
    silent-data-corruption consensus mode (needs a store for the
    fingerprint exchange: ``port`` or ``endpoint_file``);
    ``restore_integrity`` sets the checkpoint-mode resume integrity
    level ("full" also recomputes per-leaf content digests; a refusal
    exits ``EXIT_SDC``); ``flight_dir`` arms the flight recorder
    (``PT_FLIGHT_RECORDER``); ``fail=(step, exit_code)`` scripts a
    deterministic crash at the top of ``step`` (the supervisor drill's
    crash-loop: a resumed worker reaches the same step and dies again);
    ``data_shard`` names the worker's data shard (``PT_DATA_SHARD``)
    for crash/shard correlation diagnostics.
    """
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("DRILL_")}
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PT_RUN_ID": run_id,
        "PT_PROCESS_INDEX": str(rank),
        "DRILL_RANK": str(rank),
        "DRILL_WORLD": str(world),
        "DRILL_CKPT": root,
        "DRILL_STORE_PORT": str(port),
        "DRILL_TOTAL_STEPS": str(total_steps),
        "DRILL_RUN_ID": run_id,
        "DRILL_BARRIER_TIMEOUT": str(barrier_timeout),
        "DRILL_ELASTIC": "1" if elastic else "0",
    })
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    if orphan_age is not None:
        env["DRILL_ORPHAN_AGE"] = str(orphan_age)
    if kill is not None:
        env["DRILL_KILL_PHASE"] = kill.phase
        env["DRILL_KILL_STEP"] = str(kill.step)
        env["DRILL_KILL_RANK"] = str(kill.rank)
    if endpoint_file is not None:
        env["DRILL_ENDPOINT_FILE"] = endpoint_file
    if store_deadline is not None:
        env["DRILL_STORE_DEADLINE"] = str(store_deadline)
    if storekill is not None:
        env["DRILL_STOREKILL_PHASE"] = storekill.phase
        env["DRILL_STOREKILL_STEP"] = str(storekill.step)
        env["DRILL_STOREKILL_TIMEOUT"] = str(storekill.timeout)
    if obs is not None:
        if endpoint_file is None:
            raise ValueError("ObsSpec workers publish endpoints via "
                             "the store: endpoint_file is required")
        env["DRILL_OBS"] = "1"
        env["DRILL_TELEMETRY_DIR"] = obs.telemetry_dir
        env["DRILL_OBS_STEP_BASE"] = str(obs.step_base)
        env["DRILL_OBS_STORM"] = "1" if obs.storm else "0"
        env["DRILL_OBS_TIMEOUT"] = str(obs.hold_timeout)
        env["PT_RECOMPILE_THRESHOLD"] = str(obs.sentinel_threshold)
        if obs.anomalies:
            env["DRILL_OBS_ANOMALIES"] = str(obs.anomalies)
        if obs.mem_bytes:
            env["DRILL_OBS_MEM_BYTES"] = str(obs.mem_bytes)
        if obs.shed:
            env["DRILL_OBS_SHED"] = str(obs.shed)
        if obs.served:
            env["DRILL_OBS_SERVED"] = str(obs.served)
        if obs.sdc_verdicts:
            env["DRILL_OBS_SDC"] = str(obs.sdc_verdicts)
    if trace is not None:
        env["DRILL_TRACE"] = "1"
        env["DRILL_TRACE_DIR"] = trace.trace_dir
        env["DRILL_TRACE_STEP_MS"] = str(trace.step_ms)
        if trace.flight_dir:
            env["PT_FLIGHT_RECORDER"] = trace.flight_dir
    if numerics is not None:
        env["DRILL_NUMERICS"] = "1"
        env["DRILL_NUMERICS_DIR"] = numerics.out_dir
        env["DRILL_POISON_STEP"] = str(numerics.poison_step)
        env["DRILL_POISON_RANK"] = str(numerics.poison_rank)
        env["DRILL_NUMERICS_CADENCE"] = str(numerics.cadence)
        env["DRILL_NUMERICS_HALT"] = "1" if numerics.halt else "0"
    if oom is not None:
        env["DRILL_OOM"] = "1"
        env["DRILL_OOM_DIR"] = oom.out_dir
        env["DRILL_OOM_STEP"] = str(oom.oom_step)
        env["DRILL_OOM_RANK"] = str(oom.oom_rank)
        env["DRILL_OOM_MEM_BYTES"] = str(oom.mem_bytes)
    if sdc is not None:
        env["DRILL_SDC"] = "1"
        env["DRILL_SDC_DIR"] = sdc.out_dir
        env["DRILL_POISON_STEP"] = str(sdc.poison_step)
        env["DRILL_POISON_RANK"] = str(sdc.poison_rank)
        env["DRILL_SDC_CADENCE"] = str(sdc.cadence)
        env["DRILL_SDC_BIT"] = str(sdc.bit)
        env["DRILL_SDC_EXCHANGE_TIMEOUT"] = str(sdc.exchange_timeout)
    if restore_integrity is not None:
        env["DRILL_RESTORE_INTEGRITY"] = str(restore_integrity)
    if flight_dir is not None:
        env["PT_FLIGHT_RECORDER"] = flight_dir
    if fail is not None:
        env["DRILL_FAIL_STEP"] = str(fail[0])
        env["DRILL_FAIL_EXIT"] = str(fail[1])
    if data_shard is not None:
        env["PT_DATA_SHARD"] = str(data_shard)
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.drill.worker"]
    if log_path:
        with open(log_path, "ab") as out:
            p = subprocess.Popen(cmd, env=env, stdout=out,
                                 stderr=subprocess.STDOUT)
    else:
        p = subprocess.Popen(cmd, env=env,
                             stdout=subprocess.DEVNULL,
                             stderr=subprocess.DEVNULL)
    _LIVE.add(p)
    return p


def spawn_store_master(*, endpoint_file, wal_path=None, port=0,
                       log_path=None, spawn_timeout=30.0):
    """Launch (or respawn) a store-master subprocess and wait for it to
    publish its endpoint.  Returns ``(Popen, (host, port))``; the
    process is registered for :func:`reap_all` like any drill child.

    The endpoint file is unlinked FIRST so a client re-resolving during
    the respawn can never read the dead master's address as current.
    """
    try:
        os.unlink(endpoint_file)
    except FileNotFoundError:
        pass
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "store_master.py")
    cmd = [sys.executable, script, "--endpoint-file", endpoint_file,
           "--port", str(port)]
    if wal_path:
        cmd += ["--wal", wal_path]
    if log_path:
        with open(log_path, "ab") as out:
            p = subprocess.Popen(cmd, stdout=out,
                                 stderr=subprocess.STDOUT)
    else:
        p = subprocess.Popen(cmd, stdout=subprocess.DEVNULL,
                             stderr=subprocess.DEVNULL)
    _LIVE.add(p)

    def _published():
        if p.poll() is not None:
            raise DrillFailure(
                f"store master died during startup (rc {p.poll()})")
        return read_endpoint_file(endpoint_file)

    try:
        ep = wait_until(_published, spawn_timeout,
                        desc="store master to publish its endpoint")
    except TimeoutError as e:
        raise DrillFailure(f"store master never came up: {e}") from e
    logger.info("store master pid %d serving at %s:%d (wal=%s)",
                p.pid, ep[0], ep[1], wal_path)
    return p, ep


def spawn_aggregator(*, endpoint_file, run_id, port_file,
                     interval=0.25, stale_after=2.0, storm_threshold=1,
                     anomaly_threshold=10, sdc_threshold=None,
                     mem_threshold=0, shed_threshold=0.0,
                     scrape_timeout=2.0, store_deadline=10.0,
                     log_path=None, spawn_timeout=60.0):
    """Launch the cluster aggregator as a REAL subprocess
    (``python -m paddle_tpu.observability.aggregator``) discovering
    rank endpoints through the store, and wait for it to publish its
    own bound address into ``port_file``.  Returns
    ``(Popen, (host, port))``; registered for :func:`reap_all`."""
    try:
        os.unlink(port_file)
    except FileNotFoundError:
        pass
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [sys.executable, "-m", "paddle_tpu.observability.aggregator",
           "--run-id", run_id,
           "--store-endpoint-file", endpoint_file,
           "--store-deadline", str(store_deadline),
           "--port-file", port_file,
           "--interval", str(interval),
           "--stale-after", str(stale_after),
           "--scrape-timeout", str(scrape_timeout),
           "--storm-threshold", str(storm_threshold),
           "--anomaly-threshold", str(anomaly_threshold)]
    if sdc_threshold is not None:
        cmd += ["--sdc-threshold", str(sdc_threshold)]
    if mem_threshold:
        cmd += ["--mem-threshold", str(mem_threshold)]
    if shed_threshold:
        cmd += ["--shed-threshold", str(shed_threshold)]
    if log_path:
        with open(log_path, "ab") as out:
            p = subprocess.Popen(cmd, env=env, stdout=out,
                                 stderr=subprocess.STDOUT)
    else:
        p = subprocess.Popen(cmd, env=env, stdout=subprocess.DEVNULL,
                             stderr=subprocess.DEVNULL)
    _LIVE.add(p)

    def _published():
        if p.poll() is not None:
            raise DrillFailure(
                f"aggregator died during startup (rc {p.poll()})")
        return read_endpoint_file(port_file)

    try:
        ep = wait_until(_published, spawn_timeout,
                        desc="aggregator to publish its endpoint")
    except TimeoutError as e:
        raise DrillFailure(f"aggregator never came up: {e}") from e
    logger.info("aggregator pid %d serving at %s:%d", p.pid, ep[0],
                ep[1])
    return p, ep


def _http_get(url, timeout=5.0):
    """Bounded GET returning (status, body-text); a 503 (/healthz with
    the alarm up) still returns its body."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read().decode("utf-8")
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode("utf-8")


def _sample_value(families, name, **labels):
    """First sample of ``name`` whose labels are a superset of
    ``labels`` (None when absent) — tolerant of extra labels like
    run_id so drill assertions only pin what they mean to pin."""
    fam = families.get(name)
    if fam is None:
        for f in families.values():
            for sname, lbls, value in f["samples"]:
                if sname == name and all(
                        lbls.get(k) == v for k, v in labels.items()):
                    return value
        return None
    for sname, lbls, value in fam["samples"]:
        if sname == name and all(lbls.get(k) == v
                                 for k, v in labels.items()):
            return value
    return None


def _wait_fleet(procs, timeout):
    """Block until every proc exits; returns their return codes.  On
    timeout the fleet is reaped and the drill fails."""
    try:
        wait_until(lambda: all(p.poll() is not None for p in procs),
                   timeout, desc=f"drill fleet of {len(procs)} to exit")
    except TimeoutError as e:
        reap_all()
        raise DrillFailure(f"drill generation hung: {e}") from e
    rcs = []
    for p in procs:
        # poll() above proved exit; the wait just reaps, so a short
        # bound is safe
        rcs.append(p.wait(timeout=5.0))
        _LIVE.discard(p)
    return rcs


def _latest_step(root):
    # read-only probe (orphan_age=None: the probe must not janitor)
    return CheckpointManager(root, keep_last_n=None,
                             orphan_age=None).latest_step()


def _verify_bit_for_bit(root, step):
    """CRC-verify step's checkpoint, then compare every leaf byte-wise
    against the replayed oracle."""
    d = os.path.join(root, f"step_{int(step):08d}")
    verify_checkpoint(d, integrity="full")
    w0, b0 = init_state()
    we, be = advance(w0, b0, int(step))
    w = read_leaf(d, "w", integrity="off")
    b = read_leaf(d, "bias", integrity="off")
    if w.tobytes() != we.tobytes() or b.tobytes() != be.tobytes():
        raise DrillFailure(
            f"step {step} restored state is not bit-identical to the "
            f"oracle replay (max |w-we| = {abs(w - we).max()})")


def poison_shard(ckpt_dir, rel_path=None, bit=0, offset=None):
    """Flip one payload bit in a committed shard file AND re-seal the
    COMMIT manifest's crc32 to match the corrupted bytes.

    This models silent corruption that happened between device memory
    and serialization: the file-level CRC was computed over an
    already-corrupt buffer, so manifest verification passes and only
    the per-leaf *content* digest (recorded from the live array at
    save) can refuse the restore.  Returns the relative path of the
    poisoned file.  Canonical here — the restore-refusal leg of
    :func:`run_sdc_drill` is the primary consumer — and re-exported by
    tests/fault_injection.py for the checkpoint-digest unit tests.

    ``offset`` is the byte offset inside the .npy payload to hit
    (defaults to the last byte — element data, safely past the
    header); ``bit`` selects the bit within that byte.
    """
    import zlib

    files = []
    data_root = os.path.join(ckpt_dir, "data")
    for droot, _dirs, fnames in os.walk(data_root):
        for fn in fnames:
            files.append(os.path.relpath(os.path.join(droot, fn),
                                         ckpt_dir))
    files.sort()
    if not files:
        raise ValueError(f"no shard files under {ckpt_dir}")
    rel = rel_path or files[0]
    path = os.path.join(ckpt_dir, rel)
    with open(path, "r+b") as f:
        if offset is None:
            f.seek(-1, os.SEEK_END)
        else:
            f.seek(offset)
        pos = f.tell()
        b = f.read(1)
        if not b:
            raise ValueError(f"offset {offset} out of range for {path}")
        f.seek(pos)
        f.write(bytes([b[0] ^ (1 << (int(bit) % 8))]))
    with open(path, "rb") as f:
        data = f.read()
    crc = zlib.crc32(data) & 0xFFFFFFFF
    patched = False
    for name in os.listdir(ckpt_dir):
        if not name.startswith("COMMIT."):
            continue
        marker_path = os.path.join(ckpt_dir, name)
        with open(marker_path) as f:
            marker = json.load(f)
        entry = marker.get("files", {}).get(rel)
        if entry is None:
            continue
        entry["crc32"] = crc
        entry["size"] = len(data)
        with open(marker_path, "w") as f:
            json.dump(marker, f)
        patched = True
    if not patched:
        raise ValueError(f"{rel} is not covered by any COMMIT manifest")
    return rel


def run_drill(root, generations, total_steps, *, barrier_timeout=6.0,
              gen_timeout=120.0, orphan_age=None, log_dir=None,
              flight_dir=None):
    """Run a multi-generation fault drill.

    ``generations``: list of ``(world_size, KillSpec-or-None)``.  Each
    generation is a full fleet launch sharing the checkpoint ``root``;
    a generation with a kill is expected to end with the victim
    SIGKILLed (rc ``-9``) and every survivor exiting
    ``EXIT_SAVE_FAILED`` after its commit barrier names the dead rank
    — after which the newest committed step must equal the kill's
    :meth:`KillSpec.expected_commit` and verify bit-for-bit.  The last
    generation should have no kill: it must run to ``total_steps`` with
    every rank exiting 0, resuming elastically when its world size
    differs from the writer's.

    ``flight_dir`` arms the flight recorder in every worker: a killed
    generation then additionally asserts the SIGKILLed victim left a
    parseable ``flight-<run_id>-<rank>.json`` behind — the recorder's
    no-handlers-run acceptance (arm-time dump + watchdog refresh).

    Returns a per-generation report (worlds, return codes, newest
    committed step, run_id) for further assertions.
    """
    master = TCPStore("127.0.0.1", 0, is_master=True)
    report = []
    try:
        for g, (world, kill) in enumerate(generations):
            run_id = f"g{g}-{uuid.uuid4().hex[:6]}"
            procs = [
                spawn_worker(
                    r, world, root=root, port=master.port,
                    total_steps=total_steps, run_id=run_id,
                    barrier_timeout=barrier_timeout, kill=kill,
                    orphan_age=orphan_age, flight_dir=flight_dir,
                    log_path=(os.path.join(log_dir, f"gen{g}_rank{r}.log")
                              if log_dir else None))
                for r in range(world)
            ]
            rcs = _wait_fleet(procs, gen_timeout)
            latest = _latest_step(root)
            gen_report = {"world": world, "rcs": rcs, "latest": latest,
                          "run_id": run_id}
            report.append(gen_report)
            if kill is None:
                if any(rc != 0 for rc in rcs):
                    raise DrillFailure(
                        f"generation {g} (no kill) exit codes {rcs}")
                if latest != total_steps:
                    raise DrillFailure(
                        f"generation {g} finished but newest committed "
                        f"step is {latest}, wanted {total_steps}")
            else:
                if rcs[kill.rank] != -signal.SIGKILL:
                    raise DrillFailure(
                        f"generation {g}: victim rank {kill.rank} "
                        f"exited {rcs[kill.rank]}, expected SIGKILL")
                survivors = [rc for r, rc in enumerate(rcs)
                             if r != kill.rank]
                if any(rc != EXIT_SAVE_FAILED for rc in survivors):
                    raise DrillFailure(
                        f"generation {g}: survivor exit codes "
                        f"{survivors}, expected all {EXIT_SAVE_FAILED}")
                want = kill.expected_commit()
                if (latest or 0) != want:
                    raise DrillFailure(
                        f"generation {g}: newest committed step is "
                        f"{latest} after a {kill.phase} kill at step "
                        f"{kill.step}, expected {want}")
                if flight_dir is not None:
                    # SIGKILL runs no handlers: the dump on disk is the
                    # arm-time/watchdog one, and it must be whole
                    fpath = os.path.join(
                        flight_dir,
                        f"flight-{run_id}-{kill.rank}.json")
                    try:
                        with open(fpath, "r", encoding="utf-8") as f:
                            flight = json.load(f)
                    except (OSError, ValueError) as e:
                        raise DrillFailure(
                            f"generation {g}: SIGKILLed rank "
                            f"{kill.rank} left no parseable flight "
                            f"dump at {fpath}: {e}") from e
                    if flight.get("process_index") != kill.rank or \
                            flight.get("run_id") != run_id:
                        raise DrillFailure(
                            f"generation {g}: flight dump identity "
                            f"{flight.get('run_id')!r}/"
                            f"{flight.get('process_index')!r} does not "
                            f"match victim {run_id!r}/{kill.rank}")
                    gen_report["flight"] = fpath
            if latest is not None:
                _verify_bit_for_bit(root, latest)
    finally:
        reap_all()
        master.close()
    return report


def run_store_kill_drill(root, *, world=2, total_steps=5, kill_step=3,
                         phase="mid-barrier", wal=True, respawn=True,
                         respawn_with_wal=True, barrier_timeout=10.0,
                         store_deadline=8.0, storekill_timeout=45.0,
                         gen_timeout=120.0, log_dir=None,
                         relaunch_extra_steps=0):
    """SIGKILL the TCPStore MASTER mid-save and prove the fleet either
    recovers (durable master respawned from its WAL) or degrades
    cleanly (``StoreUnavailableError`` → every rank exits
    ``EXIT_STORE_LOST`` within its deadline — never a hang).

    Deterministic kill window: every rank rendezvouses at ``phase`` of
    step ``kill_step``'s save (``ready`` keys through the doomed
    master, blocking on a ``go`` key), the runner SIGKILLs the master
    only once ALL ranks are provably in-flight, then — when ``respawn``
    — relaunches it (from the WAL, or amnesiac when
    ``respawn_with_wal=False``) and releases ``go`` through the new
    master.  Recovery asserts every rank finishes to ``total_steps``
    with the respawned master sealing the barrier from REPLAYED
    arrivals, bit-for-bit verified; ``relaunch_extra_steps > 0`` then
    runs a fresh no-kill generation against the same master to prove a
    relaunch resumes bit-for-bit too.

    Returns a report dict (``rcs``, ``latest``, ``generations``
    observed from the release client, endpoints, recovery mode).
    """
    endpoint_file = os.path.join(root, "store.endpoint")
    wal_path = os.path.join(root, "store.wal") if wal else None
    expect_recovery = respawn and respawn_with_wal and wal

    def _log(name):
        return os.path.join(log_dir, name) if log_dir else None

    master, ep0 = spawn_store_master(
        endpoint_file=endpoint_file, wal_path=wal_path,
        log_path=_log("store_master_0.log"))
    report = {"endpoints": [ep0], "recovered": expect_recovery}
    try:
        run_id = f"storekill-{uuid.uuid4().hex[:6]}"
        sk = StoreKillSpec(phase, kill_step, timeout=storekill_timeout)
        procs = [
            spawn_worker(
                r, world, root=root, total_steps=total_steps,
                run_id=run_id, barrier_timeout=barrier_timeout,
                endpoint_file=endpoint_file,
                store_deadline=store_deadline, storekill=sk,
                log_path=_log(f"storekill_rank{r}.log"))
            for r in range(world)
        ]

        # wait until EVERY rank is provably inside the kill window
        watch = ResilientStore(endpoint_file=endpoint_file,
                               deadline=store_deadline)
        try:
            for r in range(world):
                watch.get(f"storekill/{run_id}/ready/{r}", wait=True,
                          timeout=gen_timeout / 2)
        finally:
            watch.close()
        logger.info("all %d ranks at the storekill rendezvous; "
                    "SIGKILLing master pid %d", world, master.pid)
        master.kill()
        master.wait(timeout=30)
        _LIVE.discard(master)

        gen = None
        if respawn:
            master, ep1 = spawn_store_master(
                endpoint_file=endpoint_file,
                wal_path=wal_path if respawn_with_wal else None,
                log_path=_log("store_master_1.log"))
            report["endpoints"].append(ep1)
            # release the fleet through the NEW master (fresh client:
            # the release must work even against an amnesiac master —
            # it is the WORKERS whose fence must trip, not ours)
            release = ResilientStore(endpoint_file=endpoint_file,
                                     deadline=store_deadline)
            try:
                release.set(f"storekill/{run_id}/go", b"1")
                gen = release.generation
            finally:
                release.close()
        report["generation"] = gen

        rcs = _wait_fleet(procs, gen_timeout)
        latest = _latest_step(root)
        report.update({"rcs": rcs, "latest": latest})

        if expect_recovery:
            if any(rc != 0 for rc in rcs):
                raise DrillFailure(
                    f"store-kill recovery: exit codes {rcs}, expected "
                    f"all 0 (master respawned from WAL should have "
                    f"sealed the barrier from replayed arrivals)")
            if latest != total_steps:
                raise DrillFailure(
                    f"store-kill recovery: newest committed step is "
                    f"{latest}, wanted {total_steps}")
            if gen is None or gen < 2:
                raise DrillFailure(
                    f"respawned WAL master advertises generation {gen}, "
                    f"expected >= 2 (replay must bump it)")
        else:
            if any(rc != EXIT_STORE_LOST for rc in rcs):
                raise DrillFailure(
                    f"store-kill clean-failure: exit codes {rcs}, "
                    f"expected all {EXIT_STORE_LOST} "
                    f"(StoreUnavailableError)")
            want = kill_step - 1
            if (latest or 0) != want:
                raise DrillFailure(
                    f"store-kill clean-failure: newest committed step "
                    f"is {latest}, expected {want} (step {kill_step} "
                    f"must never have promoted)")
        if latest:
            _verify_bit_for_bit(root, latest)

        if expect_recovery and relaunch_extra_steps > 0:
            # relaunch generation: fresh fleet, same respawned master,
            # resumes from `latest` and runs further — the
            # resume-bit-for-bit half of the acceptance criterion
            run_id2 = f"storekill-relaunch-{uuid.uuid4().hex[:6]}"
            more = total_steps + relaunch_extra_steps
            procs2 = [
                spawn_worker(
                    r, world, root=root, total_steps=more,
                    run_id=run_id2, barrier_timeout=barrier_timeout,
                    endpoint_file=endpoint_file,
                    store_deadline=store_deadline,
                    log_path=_log(f"relaunch_rank{r}.log"))
                for r in range(world)
            ]
            rcs2 = _wait_fleet(procs2, gen_timeout)
            latest2 = _latest_step(root)
            report.update({"relaunch_rcs": rcs2,
                           "relaunch_latest": latest2})
            if any(rc != 0 for rc in rcs2):
                raise DrillFailure(
                    f"relaunch after store failover: exit codes {rcs2}")
            if latest2 != more:
                raise DrillFailure(
                    f"relaunch after store failover: newest step "
                    f"{latest2}, wanted {more}")
            _verify_bit_for_bit(root, latest2)
    finally:
        reap_all()
    return report


def run_scrape_drill(root, *, world=3, steps=12, step_base=0.01,
                     kill_rank=2, storm=True, anomalies=0,
                     sdc_verdicts=0,
                     mem_bytes=0, mem_threshold=0,
                     shed=0, served=0, shed_threshold=0.0,
                     restart_aggregator=False,
                     respawn_master=False, stale_after=2.0,
                     scrape_interval=0.25, store_deadline=10.0,
                     gen_timeout=120.0, log_dir=None):
    """End-to-end cluster-observability drill: ``world`` REAL worker
    processes publish their /metrics endpoints into the store, a REAL
    aggregator subprocess discovers and scrapes them, and the runner
    asserts the cluster view — summed counters, merged histogram
    buckets, a nonzero cross-rank step-time skew (each rank's synthetic
    step profile is ``step_base * (1 + rank)``), and (when ``storm``)
    the recompile-storm alarm tripping on the CROSS-RANK aggregate.

    Every obs worker also feeds a deterministic synthetic goodput
    profile (1/5 data_wait, 4/5 compute per virtual step), so the
    derived ``pt_cluster_goodput`` min/mean must both read exactly
    0.8; ``anomalies`` (per-rank scripted numerics trips) arms the
    cross-rank anomaly alarm, whose threshold is then set to
    ``world * anomalies`` so it trips exactly — and flips /healthz to
    503 even without a recompile storm.  ``sdc_verdicts`` does the
    same for the silent-data-corruption plane: each rank books that
    many scripted consensus divergence verdicts (fingering a fixed
    peer, halt disarmed), the aggregator's SDC threshold is set to
    ``world * sdc_verdicts`` so ``pt_cluster_sdc_alarm`` trips
    exactly, and /healthz must answer 503 on the corruption signal
    alone.  ``mem_bytes`` feeds each rank
    a synthetic allocator watermark (rank r exports
    ``mem_bytes * (1 + r)``) so the cluster memory-skew gauge must
    read exactly ``mem_bytes * (world - 1)``; with ``mem_threshold``
    at or below ``mem_bytes * world`` the near-OOM alarm must trip and
    flip /healthz to 503 on the memory signal alone.  ``shed`` /
    ``served`` script a per-rank serve admission profile (each rank
    books that many ``pt_serve_shed_total`` refusals and accepted
    requests), pinning the aggregator's fleet shed ratio to exactly
    ``shed / (shed + served)``; with ``shed_threshold`` at or below
    that ratio the shed-storm alarm must trip and flip /healthz to
    503 on the load-shedding signal alone.

    ``kill_rank`` (None to skip) is then SIGKILLed while still holding
    its endpoint open: the aggregator must mark it stale
    (``pt_rank_up 0``, ``pt_cluster_ranks_up`` down by one) within
    bounded polls — never hang.  ``restart_aggregator`` kills and
    respawns the aggregator itself mid-drill (its cluster view must
    reconverge from store discovery alone); ``respawn_master``
    SIGKILLs the WAL-backed store master and proves discovery survives
    the failover.  Finally the fleet is released, exit codes checked,
    and ``python -m paddle_tpu.observability.merge`` stitches the
    per-rank telemetry JSONL into one time-ordered rank-labeled stream
    that is validated line-for-line.  Returns a report dict.
    """
    endpoint_file = os.path.join(root, "store.endpoint")
    wal_path = os.path.join(root, "store.wal")
    port_file = os.path.join(root, "aggregator.endpoint")
    telemetry_dir = os.path.join(root, "telemetry")
    os.makedirs(telemetry_dir, exist_ok=True)
    sentinel_threshold = 3
    storm_threshold = world if storm else world * 1000
    anomaly_threshold = world * anomalies if anomalies else world * 1000
    sdc_threshold = (world * sdc_verdicts if sdc_verdicts
                     else world * 1000)

    def _log(name):
        return os.path.join(log_dir, name) if log_dir else None

    master, _ep = spawn_store_master(
        endpoint_file=endpoint_file, wal_path=wal_path,
        log_path=_log("store_master.log"))
    run_id = f"obs-{uuid.uuid4().hex[:6]}"
    spec = ObsSpec(telemetry_dir=telemetry_dir, step_base=step_base,
                   storm=storm, sentinel_threshold=sentinel_threshold,
                   hold_timeout=gen_timeout, anomalies=anomalies,
                   mem_bytes=mem_bytes, shed=shed, served=served,
                   sdc_verdicts=sdc_verdicts)
    mem_alarm_expected = bool(
        mem_bytes and mem_threshold
        and mem_bytes * world >= mem_threshold)
    shed_ratio_expected = (
        shed / float(shed + served) if (shed or served) else None)
    shed_alarm_expected = bool(
        shed_threshold and shed_ratio_expected is not None
        and shed_ratio_expected >= shed_threshold)
    report = {"run_id": run_id, "world": world, "steps": steps,
              "aggregator_restarted": False, "master_respawned": False}
    watch = None
    try:
        procs = [
            spawn_worker(
                r, world, root=root, total_steps=steps, run_id=run_id,
                barrier_timeout=gen_timeout,
                endpoint_file=endpoint_file,
                store_deadline=store_deadline, obs=spec,
                log_path=_log(f"obs_rank{r}.log"))
            for r in range(world)
        ]

        # every rank has published its endpoint, observed its steps
        # (and tripped its sentinel) before we let the aggregator judge
        watch = ResilientStore(endpoint_file=endpoint_file,
                               deadline=store_deadline)
        for r in range(world):
            watch.get(obs_ready_key(run_id, r), wait=True,
                      timeout=gen_timeout / 2)

        agg, (ahost, aport) = spawn_aggregator(
            endpoint_file=endpoint_file, run_id=run_id,
            port_file=port_file, interval=scrape_interval,
            stale_after=stale_after, storm_threshold=storm_threshold,
            anomaly_threshold=anomaly_threshold,
            sdc_threshold=sdc_threshold,
            mem_threshold=mem_threshold,
            shed_threshold=shed_threshold,
            store_deadline=store_deadline,
            log_path=_log("aggregator.log"))
        base = f"http://{ahost}:{aport}"

        from ...observability.aggregator import parse_prometheus_text

        def _cluster_families():
            """One bounded scrape of the aggregator; None while it is
            still converging or between restarts."""
            if agg.poll() is not None:
                raise DrillFailure(
                    f"aggregator exited mid-drill (rc {agg.poll()})")
            try:
                _status, body = _http_get(base + "/metrics", timeout=5.0)
            except OSError:
                return None
            try:
                return parse_prometheus_text(body)
            except ValueError as e:
                raise DrillFailure(
                    f"aggregated /metrics is not valid exposition "
                    f"format: {e}") from e

        def _converged(want_up, want_steps):
            def poll():
                fams = _cluster_families()
                if fams is None:
                    return None
                up = _sample_value(fams, "pt_cluster_ranks_up")
                total = _sample_value(fams, "pt_steps_total",
                                      mode="train")
                if up == want_up and (
                        want_steps is None or total == want_steps):
                    return fams
                return None
            return poll

        fams = wait_until(
            _converged(world, float(world * steps)), gen_timeout / 2,
            desc=f"aggregator to converge on {world} fresh ranks")

        # --- the cluster view: sums, merged buckets, skew, storms ----
        skew = _sample_value(fams, "pt_step_time_skew_seconds",
                             mode="train")
        if not skew or skew <= 0.0:
            raise DrillFailure(
                f"pt_step_time_skew_seconds is {skew!r}; rank-skewed "
                f"step profiles must yield a positive cross-rank skew")
        straggler = _sample_value(
            fams, "pt_step_time_straggler_ratio", mode="train")
        if not straggler or straggler < 1.0:
            raise DrillFailure(
                f"straggler ratio {straggler!r}, expected >= 1.0")
        hist_count = _sample_value(fams, "pt_step_time_seconds_count",
                                   mode="train")
        if hist_count != float(world * steps):
            raise DrillFailure(
                f"merged pt_step_time_seconds_count is {hist_count}, "
                f"expected {world * steps} (bucket merge lost samples)")
        storms_total = _sample_value(
            fams, "pt_cluster_recompile_storms_total")
        alarm = _sample_value(fams, "pt_cluster_recompile_storm_alarm")
        status, hbody = _http_get(base + "/healthz", timeout=5.0)
        health = json.loads(hbody)
        if storm:
            if storms_total != float(world):
                raise DrillFailure(
                    f"cluster recompile storms {storms_total}, expected "
                    f"{world} (one sentinel trip per rank)")
            if alarm != 1.0:
                raise DrillFailure(
                    f"storm alarm is {alarm}, expected 1 at cross-rank "
                    f"aggregate >= threshold {storm_threshold}")
            if status != 503 or not health.get("storm_alarm"):
                raise DrillFailure(
                    f"/healthz returned {status} storm_alarm="
                    f"{health.get('storm_alarm')}, expected 503/true")
        else:
            if alarm not in (0.0, None):
                raise DrillFailure(
                    f"storm alarm tripped ({alarm}) without a storm")
            want = 503 if (anomalies or sdc_verdicts
                           or mem_alarm_expected
                           or shed_alarm_expected) else 200
            if status != want:
                raise DrillFailure(
                    f"/healthz returned {status}, expected {want}")

        # --- derived fleet goodput: every obs worker's synthetic span
        # profile is 1/5 data_wait + 4/5 compute, so min == mean == 0.8
        gp_min = _sample_value(fams, "pt_cluster_goodput", stat="min")
        gp_mean = _sample_value(fams, "pt_cluster_goodput", stat="mean")
        for label, v in (("min", gp_min), ("mean", gp_mean)):
            if v is None or abs(v - 0.8) > 1e-6:
                raise DrillFailure(
                    f"pt_cluster_goodput{{stat={label}}} is {v!r}; the "
                    f"scripted span profile pins it to 0.8 exactly")
        hgp = health.get("cluster_goodput") or {}
        if abs(hgp.get("min", -1.0) - 0.8) > 1e-6:
            raise DrillFailure(
                f"/healthz cluster_goodput {hgp!r}, expected min 0.8")

        # --- cross-rank anomaly storm, mirroring the recompile trip --
        anomalies_total = _sample_value(
            fams, "pt_cluster_numerics_anomalies_total")
        anomaly_alarm = _sample_value(
            fams, "pt_cluster_numerics_anomaly_alarm")
        if anomalies:
            if anomalies_total != float(world * anomalies):
                raise DrillFailure(
                    f"cluster numerics anomalies {anomalies_total}, "
                    f"expected {world * anomalies}")
            if anomaly_alarm != 1.0 or not health.get("anomaly_alarm"):
                raise DrillFailure(
                    f"anomaly alarm metric={anomaly_alarm} "
                    f"healthz={health.get('anomaly_alarm')}, expected "
                    f"tripped at threshold {anomaly_threshold}")
        elif anomaly_alarm not in (0.0, None):
            raise DrillFailure(
                f"anomaly alarm tripped ({anomaly_alarm}) without "
                f"scripted anomalies")

        # --- cluster SDC verdicts + the corruption alarm -------------
        sdc_total = _sample_value(
            fams, "pt_cluster_sdc_divergences_total")
        sdc_alarm = _sample_value(fams, "pt_cluster_sdc_alarm")
        if sdc_verdicts:
            if sdc_total != float(world * sdc_verdicts):
                raise DrillFailure(
                    f"cluster SDC verdicts {sdc_total!r}, expected "
                    f"{world * sdc_verdicts} (scripted divergences "
                    f"summed across ranks)")
            if sdc_alarm != 1.0 or not health.get("sdc_alarm"):
                raise DrillFailure(
                    f"SDC alarm metric={sdc_alarm} "
                    f"healthz={health.get('sdc_alarm')}, expected "
                    f"tripped at threshold {sdc_threshold}")
        elif sdc_alarm not in (0.0, None):
            raise DrillFailure(
                f"SDC alarm tripped ({sdc_alarm}) without scripted "
                f"divergence verdicts")

        # --- fleet memory view: skew gauge + the near-OOM trip -------
        mem_skew = _sample_value(fams, "pt_cluster_memory_skew_bytes")
        mem_alarm = _sample_value(fams, "pt_cluster_memory_alarm")
        if mem_bytes:
            want_skew = float(mem_bytes * (world - 1))
            if mem_skew != want_skew:
                raise DrillFailure(
                    f"pt_cluster_memory_skew_bytes is {mem_skew!r}; "
                    f"rank-scaled watermarks pin it to {want_skew}")
            if mem_alarm != (1.0 if mem_alarm_expected else 0.0):
                raise DrillFailure(
                    f"memory alarm is {mem_alarm!r}, expected "
                    f"{mem_alarm_expected} at threshold "
                    f"{mem_threshold} with max {mem_bytes * world}")
            hmem = health.get("memory") or {}
            if hmem.get("bytes_in_use_max") != mem_bytes * world \
                    or bool(hmem.get("mem_alarm")) != mem_alarm_expected:
                raise DrillFailure(
                    f"/healthz memory block {hmem!r} disagrees with "
                    f"the scripted watermarks (max "
                    f"{mem_bytes * world}, alarm {mem_alarm_expected})")
        elif mem_alarm not in (0.0, None):
            raise DrillFailure(
                f"memory alarm tripped ({mem_alarm}) without scripted "
                f"watermarks")

        # --- fleet load-shedding view: shed ratio + shed-storm trip --
        shed_total = _sample_value(fams, "pt_cluster_serve_shed_total")
        shed_ratio = _sample_value(fams, "pt_cluster_serve_shed_ratio")
        shed_alarm = _sample_value(fams, "pt_cluster_serve_shed_alarm")
        if shed or served:
            if shed_total != float(world * shed):
                raise DrillFailure(
                    f"pt_cluster_serve_shed_total is {shed_total!r}, "
                    f"expected {world * shed} (scripted sheds summed "
                    f"across ranks)")
            if shed_ratio is None \
                    or abs(shed_ratio - shed_ratio_expected) > 1e-6:
                raise DrillFailure(
                    f"pt_cluster_serve_shed_ratio is {shed_ratio!r}; "
                    f"the scripted admission profile pins it to "
                    f"{shed_ratio_expected}")
            if shed_alarm != (1.0 if shed_alarm_expected else 0.0):
                raise DrillFailure(
                    f"shed-storm alarm is {shed_alarm!r}, expected "
                    f"{shed_alarm_expected} at threshold "
                    f"{shed_threshold} with ratio {shed_ratio_expected}")
            hserve = health.get("serve") or {}
            if hserve.get("shed_total") != world * shed \
                    or bool(hserve.get("shed_alarm")) \
                    != shed_alarm_expected:
                raise DrillFailure(
                    f"/healthz serve block {hserve!r} disagrees with "
                    f"the scripted shed profile (total {world * shed},"
                    f" alarm {shed_alarm_expected})")
        elif shed_alarm not in (0.0, None):
            raise DrillFailure(
                f"shed-storm alarm tripped ({shed_alarm}) without "
                f"scripted sheds")
        report.update({
            "skew_seconds": skew, "straggler_ratio": straggler,
            "merged_steps": hist_count, "storms_total": storms_total,
            "storm_alarm": alarm, "healthz": health,
            "cluster_goodput": {"min": gp_min, "mean": gp_mean},
            "anomalies_total": anomalies_total,
            "anomaly_alarm": anomaly_alarm,
            "sdc_divergences_total": sdc_total,
            "sdc_alarm": sdc_alarm,
            "memory_skew_bytes": mem_skew,
            "memory_alarm": mem_alarm,
            "shed_total": shed_total,
            "shed_ratio": shed_ratio,
            "shed_alarm": shed_alarm,
        })

        if respawn_master:
            # store failover: the aggregator's discovery client must
            # ride the endpoint-file re-resolve onto the new master,
            # whose WAL replay still holds every published endpoint
            watch.close()
            watch = None
            master.kill()
            master.wait(timeout=30)
            _LIVE.discard(master)
            master, _ep = spawn_store_master(
                endpoint_file=endpoint_file, wal_path=wal_path,
                log_path=_log("store_master_respawn.log"))
            watch = ResilientStore(endpoint_file=endpoint_file,
                                   deadline=store_deadline)
            # prove the replayed master bumped its generation
            watch.get(obs_ready_key(run_id, 0), wait=False)
            gen = watch.generation
            if gen is None or gen < 2:
                raise DrillFailure(
                    f"respawned store master advertises generation "
                    f"{gen}, expected >= 2")
            report["store_generation"] = gen
            wait_until(
                _converged(world, float(world * steps)), gen_timeout / 2,
                desc="aggregator to reconverge after master respawn")
            report["master_respawned"] = True

        if kill_rank is not None:
            # a rank goes silent mid-run: the aggregator must mark it
            # stale within bounded scrapes — each poll here is itself
            # bounded, so a hang in the aggregator fails loudly
            procs[kill_rank].kill()

            def _stale():
                fams = _cluster_families()
                if fams is None:
                    return None
                dead = _sample_value(fams, "pt_rank_up",
                                     process_index=str(kill_rank))
                up = _sample_value(fams, "pt_cluster_ranks_up")
                if dead == 0.0 and up == float(world - 1):
                    return fams
                return None

            wait_until(
                _stale, gen_timeout / 4,
                desc=f"aggregator to mark killed rank {kill_rank} "
                     f"stale")
            report["stale_after_kill"] = True

        if restart_aggregator:
            # the aggregator itself dies and respawns: its cluster view
            # must reconverge from store discovery alone
            agg.kill()
            agg.wait(timeout=30)
            _LIVE.discard(agg)
            agg, (ahost, aport) = spawn_aggregator(
                endpoint_file=endpoint_file, run_id=run_id,
                port_file=port_file, interval=scrape_interval,
                stale_after=stale_after,
                storm_threshold=storm_threshold,
                anomaly_threshold=anomaly_threshold,
                sdc_threshold=sdc_threshold,
                store_deadline=store_deadline,
                log_path=_log("aggregator_restart.log"))
            base = f"http://{ahost}:{aport}"
            live = world - (0 if kill_rank is None else 1)
            live_steps = float(live * steps)
            wait_until(
                _converged(live, live_steps), gen_timeout / 2,
                desc="respawned aggregator to reconverge")
            report["aggregator_restarted"] = True

        # release the fleet and collect exit codes
        watch.set(obs_release_key(run_id), b"1")
        rcs = _wait_fleet(procs, gen_timeout)
        report["rcs"] = rcs
        for r, rc in enumerate(rcs):
            if kill_rank is not None and r == kill_rank:
                if rc != -signal.SIGKILL:
                    raise DrillFailure(
                        f"killed rank {r} exited {rc}, expected SIGKILL")
            elif rc != 0:
                raise DrillFailure(
                    f"obs rank {r} exited {rc}, expected 0")

        # --- merge CLI: one time-ordered rank-labeled stream ---------
        merged_path = os.path.join(root, "merged.jsonl")
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH",
                                                         "")
        cli = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.observability.merge",
             telemetry_dir, "--output", merged_path],
            env=env, capture_output=True, text=True, timeout=60)
        if cli.returncode != 0:
            raise DrillFailure(
                f"merge CLI exited {cli.returncode}: {cli.stderr}")
        expected_lines = 0
        for name in os.listdir(telemetry_dir):
            if name.endswith(".jsonl") or name.endswith(".jsonl.1"):
                with open(os.path.join(telemetry_dir, name)) as f:
                    expected_lines += sum(1 for ln in f if ln.strip())
        ranks_seen, run_ids, last_ts, merged_lines = set(), set(), "", 0
        with open(merged_path) as f:
            for line in f:
                if not line.strip():
                    continue
                merged_lines += 1
                rec = json.loads(line)
                ranks_seen.add(rec.get("process_index"))
                run_ids.add(rec.get("run_id"))
                ts = rec.get("ts") or ""
                if ts < last_ts:
                    raise DrillFailure(
                        f"merged stream is not time-ordered: {ts!r} "
                        f"after {last_ts!r}")
                last_ts = ts
        if merged_lines != expected_lines:
            raise DrillFailure(
                f"merge CLI wrote {merged_lines} records from "
                f"{expected_lines} input lines")
        if ranks_seen != set(range(world)):
            raise DrillFailure(
                f"merged stream labels ranks {sorted(ranks_seen)}, "
                f"expected 0..{world - 1}")
        if run_ids != {run_id}:
            raise DrillFailure(
                f"merged stream run_ids {run_ids}, expected "
                f"{{{run_id!r}}}")
        report.update({"merge_lines": merged_lines,
                       "expected_lines": expected_lines})
    finally:
        if watch is not None:
            watch.close()
        reap_all()
    return report


def run_trace_drill(root, *, world=2, steps=6, step_ms=10.0,
                    gen_timeout=60.0, log_dir=None):
    """Multi-process step-tracing drill: ``world`` REAL worker
    processes each enable the tracer, record a deterministic staggered
    compute/collective step profile, and export per-rank Chrome traces
    plus flight dumps; the runner then stitches the traces with the
    REAL merge CLI (``python -m paddle_tpu.observability.merge
    --trace``) and asserts ONE schema-valid cluster timeline — every
    rank present as a pid with its process_name metadata, "X" events
    complete and time-ordered — and that each rank's measured
    compute↔collective overlap fraction is strictly positive (the
    scripted stagger makes the analytic value 0.6).  Storeless: no
    TCPStore master, no checkpoints.  Returns a report dict."""
    trace_dir = os.path.join(root, "traces")
    flight_dir = os.path.join(root, "flight")
    os.makedirs(trace_dir, exist_ok=True)
    run_id = f"trace-{uuid.uuid4().hex[:6]}"
    spec = TraceSpec(trace_dir=trace_dir, flight_dir=flight_dir,
                     step_ms=step_ms)
    report = {"run_id": run_id, "world": world, "steps": steps}
    try:
        procs = [
            spawn_worker(
                r, world, root=root, total_steps=steps, run_id=run_id,
                barrier_timeout=gen_timeout, trace=spec,
                log_path=(os.path.join(log_dir, f"trace_rank{r}.log")
                          if log_dir else None))
            for r in range(world)
        ]
        rcs = _wait_fleet(procs, gen_timeout)
        report["rcs"] = rcs
        if any(rc != 0 for rc in rcs):
            raise DrillFailure(f"trace drill exit codes {rcs}, "
                               f"expected all 0")

        # --- per-rank artifacts: report, chrome export, flight dump --
        overlaps = []
        for r in range(world):
            rep_path = trace_report_path(trace_dir, r)
            try:
                with open(rep_path, "r", encoding="utf-8") as f:
                    snap = json.load(f)
            except (OSError, ValueError) as e:
                raise DrillFailure(
                    f"rank {r} wrote no parseable trace report at "
                    f"{rep_path}: {e}") from e
            ov = snap.get("overlap_fraction")
            if not ov or ov <= 0.0:
                raise DrillFailure(
                    f"rank {r} measured overlap fraction {ov!r}; the "
                    f"staggered collectives must yield > 0")
            overlaps.append(ov)
            if not snap.get("phase_ms"):
                raise DrillFailure(
                    f"rank {r} report has no phase percentiles")
            tpath = os.path.join(trace_dir,
                                 f"trace-{run_id}-{r}.json")
            if not os.path.exists(tpath):
                raise DrillFailure(
                    f"rank {r} Chrome export missing at {tpath}")
            fpath = os.path.join(flight_dir,
                                 f"flight-{run_id}-{r}.json")
            try:
                with open(fpath, "r", encoding="utf-8") as f:
                    flight = json.load(f)
            except (OSError, ValueError) as e:
                raise DrillFailure(
                    f"rank {r} flight dump unreadable at {fpath}: "
                    f"{e}") from e
            if flight.get("process_index") != r or not flight.get("spans"):
                raise DrillFailure(
                    f"rank {r} flight dump carries identity "
                    f"{flight.get('process_index')!r} and "
                    f"{len(flight.get('spans') or [])} spans")
        report["overlaps"] = overlaps

        # --- merge CLI: one schema-valid cluster timeline ------------
        merged_path = os.path.join(root, "merged_trace.json")
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH",
                                                         "")
        cli = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.observability.merge",
             "--trace", trace_dir, "--output", merged_path],
            env=env, capture_output=True, text=True, timeout=60)
        if cli.returncode != 0:
            raise DrillFailure(
                f"merge --trace CLI exited {cli.returncode}: "
                f"{cli.stderr}")
        with open(merged_path, "r", encoding="utf-8") as f:
            doc = json.load(f)
        evs = doc.get("traceEvents") if isinstance(doc, dict) else None
        if not isinstance(evs, list) or not evs:
            raise DrillFailure(
                f"merged trace is not a Chrome trace document: "
                f"{type(evs).__name__}")
        pids, meta_ranks, last_ts, x_events = set(), set(), None, 0
        for ev in evs:
            if not isinstance(ev, dict) or "name" not in ev \
                    or "ph" not in ev or "pid" not in ev:
                raise DrillFailure(f"malformed trace event: {ev!r}")
            pids.add(ev["pid"])
            if ev["ph"] == "M" and ev["name"] == "process_name":
                meta_ranks.add(ev["pid"])
            elif ev["ph"] == "X":
                x_events += 1
                if not {"ts", "dur", "cat"} <= ev.keys():
                    raise DrillFailure(
                        f"incomplete X event: {ev!r}")
                if last_ts is not None and ev["ts"] < last_ts:
                    raise DrillFailure(
                        f"merged trace is not time-ordered: "
                        f"{ev['ts']} after {last_ts}")
                last_ts = ev["ts"]
        if pids != set(range(world)):
            raise DrillFailure(
                f"merged trace pids {sorted(pids)}, expected ranks "
                f"0..{world - 1}")
        if meta_ranks != set(range(world)):
            raise DrillFailure(
                f"process_name metadata for ranks "
                f"{sorted(meta_ranks)}, expected all {world}")
        # 4 phase spans per step per rank land in the merged doc
        if x_events != world * steps * 4:
            raise DrillFailure(
                f"merged trace holds {x_events} X events from "
                f"{world} ranks x {steps} steps x 4 phases")
        report.update({"merged_events": x_events,
                       "merged_path": merged_path})
    finally:
        reap_all()
    return report


def run_numerics_drill(root, *, world=2, steps=12, poison_step=5,
                       poison_rank=1, cadence=4, halt=False,
                       gen_timeout=120.0, log_dir=None):
    """NaN-injection numerics drill: ``world`` REAL worker processes
    each train a captured MLP on CPU with the numerics monitor armed;
    ``poison_rank`` overwrites one input element with NaN at
    ``poison_step`` (same shape/dtype — the capture cache must not
    retrace).  The runner asserts from each rank's report that the
    poisoned rank's sentinel fired within ONE cadence window of the
    injection, named a real parameter path (or the loss), and left a
    flight dump whose recorded reason carries that name; that every
    clean rank stayed quiet (zero anomalies); and that every rank
    compiled its captured step exactly once.  With ``halt`` the
    poisoned worker must exit ``EXIT_NUMERICS_HALT`` cleanly (report
    still written); otherwise every rank exits 0.  Storeless: no
    TCPStore master, no checkpoints.  Returns a report dict."""
    out_dir = os.path.join(root, "numerics")
    flight_dir = os.path.join(root, "flight")
    os.makedirs(out_dir, exist_ok=True)
    run_id = f"numerics-{uuid.uuid4().hex[:6]}"
    spec = NumericsSpec(out_dir=out_dir, poison_step=poison_step,
                        poison_rank=poison_rank, cadence=cadence,
                        halt=halt)
    report = {"run_id": run_id, "world": world, "steps": steps,
              "poison_step": poison_step, "poison_rank": poison_rank,
              "cadence": cadence, "halt": halt}
    try:
        procs = [
            spawn_worker(
                r, world, root=root, total_steps=steps, run_id=run_id,
                barrier_timeout=gen_timeout, numerics=spec,
                flight_dir=flight_dir,
                log_path=(os.path.join(log_dir, f"numerics_rank{r}.log")
                          if log_dir else None))
            for r in range(world)
        ]
        rcs = _wait_fleet(procs, gen_timeout)
        report["rcs"] = rcs
        for r, rc in enumerate(rcs):
            want = EXIT_NUMERICS_HALT if (halt and r == poison_rank) \
                else 0
            if rc != want:
                raise DrillFailure(
                    f"numerics rank {r} exited {rc}, expected {want}")

        ranks = {}
        for r in range(world):
            rep_path = numerics_report_path(out_dir, r)
            try:
                with open(rep_path, "r", encoding="utf-8") as f:
                    rep = json.load(f)
            except (OSError, ValueError) as e:
                raise DrillFailure(
                    f"rank {r} wrote no parseable numerics report at "
                    f"{rep_path}: {e}") from e
            ranks[r] = rep
            if rep.get("compiles") != 1:
                raise DrillFailure(
                    f"rank {r} compiled its captured step "
                    f"{rep.get('compiles')} times; the monitored step "
                    f"must stay at exactly 1 compile")
            if rep.get("fallback"):
                raise DrillFailure(
                    f"rank {r} fell back to eager "
                    f"{rep.get('fallback')} times")
        report["ranks"] = ranks

        # --- the poisoned rank: detection, naming, flight dump -------
        rep = ranks[poison_rank]
        detected = rep.get("detected_step")
        if detected is None:
            raise DrillFailure(
                f"poisoned rank {poison_rank} never detected the "
                f"injected NaN: {rep!r}")
        if not poison_step <= detected <= poison_step + cadence:
            raise DrillFailure(
                f"detection at step {detected} is outside one cadence "
                f"window [{poison_step}, {poison_step + cadence}] of "
                f"the injection")
        if not rep.get("anomalies", {}).get("nonfinite"):
            raise DrillFailure(
                f"poisoned rank booked no 'nonfinite' anomaly: "
                f"{rep.get('anomalies')!r}")
        param_trips = [t for t in rep.get("tripped") or []
                       if t != "loss"]
        if not param_trips:
            raise DrillFailure(
                f"sentinel named no parameter path, only "
                f"{rep.get('tripped')!r}; a poisoned input must "
                f"surface non-finite grads by name")
        if halt and not rep.get("halted"):
            raise DrillFailure(
                "halt variant: the sentinel raise was never observed")
        fpath = rep.get("flight")
        try:
            with open(fpath, "r", encoding="utf-8") as f:
                flight = json.load(f)
        except (TypeError, OSError, ValueError) as e:
            raise DrillFailure(
                f"poisoned rank's flight dump unreadable at "
                f"{fpath!r}: {e}") from e
        reason = flight.get("reason") or ""
        named = reason.split(":", 2)[2] if reason.count(":") >= 2 \
            else ""
        if not reason.startswith("numerics:nonfinite") \
                or named not in param_trips:
            raise DrillFailure(
                f"flight dump reason {reason!r} must pin the first "
                f"non-finite trip to a parameter path (one of "
                f"{param_trips!r})")
        if flight.get("process_index") != poison_rank:
            raise DrillFailure(
                f"flight dump identity "
                f"{flight.get('process_index')!r} != poisoned rank "
                f"{poison_rank}")
        report.update({"detected_step": detected,
                       "named_tensor": named,
                       "flight_reason": reason})

        # --- clean ranks stay quiet ----------------------------------
        for r in range(world):
            if r == poison_rank:
                continue
            rep = ranks[r]
            if rep.get("anomalies"):
                raise DrillFailure(
                    f"clean rank {r} booked anomalies "
                    f"{rep['anomalies']!r}; the sentinel must stay "
                    f"quiet on healthy data")
            if rep.get("detected_step") is not None:
                raise DrillFailure(
                    f"clean rank {r} claims detection at step "
                    f"{rep['detected_step']}")
    finally:
        reap_all()
    return report


def run_sdc_drill(root, *, scenario="consensus", world=3, steps=12,
                  poison_step=5, poison_rank=1, cadence=4, bit=3,
                  quarantine_threshold=2, sdc_max_restarts=4,
                  barrier_timeout=6.0, gen_timeout=180.0, log_dir=None):
    """Silent-data-corruption drill: REAL worker processes, a real bit
    flip, and the full detect → attribute → quarantine → refuse chain.
    Three scenarios:

    - ``consensus``: ``world`` dp-replica workers (same seed, same
      data — bit-identical by construction) train a captured MLP with
      the SDC sentry armed, exchanging fingerprints through a real
      TCPStore.  The victim flips ONE mantissa bit of its first
      parameter at ``poison_step``; the majority vote must finger
      exactly that rank within one cadence window, name a divergent
      tensor path, pin a flight dump on the victim, and halt it into
      ``EXIT_SDC`` — while every clean rank books the verdict against
      the victim (and only the victim) and runs to completion with
      exactly one compile.  ``poison_rank=-1`` is the control run:
      everyone must stay verdict-free and exit 0.
    - ``quarantine``: the same poisoned fleet under a real
      :class:`~..supervisor.Supervisor`.  The victim re-poisons every
      generation at the original world size — a sticky bad host — so
      consensus fingers it ``quarantine_threshold`` times; the
      supervisor must charge every ``EXIT_SDC`` to the hardware ledger
      (never the code-crash budget), quarantine the rank, downsize the
      fleet around it, and the downsized generation (poison disabled:
      the bad host left the pool) must finish cleanly.
    - ``restore``: a clean single-rank checkpoint run, then
      :func:`poison_shard` plants a bit flip in the committed shard
      AND re-seals the manifest CRC over the corrupted bytes — the
      corruption a file-level CRC can never catch.  Manifest
      verification must still pass, ``integrity="full"`` must refuse
      naming the leaf and the digests, and a relaunched worker
      resuming with ``DRILL_RESTORE_INTEGRITY=full`` must exit
      ``EXIT_SDC`` instead of training on corrupt state.

    Returns a report dict for further assertions.
    """
    if scenario not in ("consensus", "quarantine", "restore"):
        raise ValueError(f"unknown sdc drill scenario {scenario!r}")
    out_dir = os.path.join(root, "sdc")
    flight_dir = os.path.join(root, "flight")
    os.makedirs(out_dir, exist_ok=True)
    exch_timeout = min(30.0, gen_timeout / 3.0)

    def _log(name):
        return os.path.join(log_dir, name) if log_dir else None

    report = {"scenario": scenario, "world": world, "steps": steps,
              "poison_step": poison_step, "poison_rank": poison_rank,
              "cadence": cadence, "bit": bit}

    if scenario == "restore":
        return _run_sdc_restore_leg(root, report, steps=steps, bit=bit,
                                    barrier_timeout=barrier_timeout,
                                    gen_timeout=gen_timeout, _log=_log)

    master = TCPStore("127.0.0.1", 0, is_master=True)
    try:
        if scenario == "consensus":
            run_id = f"sdc-{uuid.uuid4().hex[:6]}"
            spec = SdcSpec(out_dir=out_dir, poison_step=poison_step,
                           poison_rank=poison_rank, cadence=cadence,
                           bit=bit, exchange_timeout=exch_timeout)
            procs = [
                spawn_worker(
                    r, world, root=root, port=master.port,
                    total_steps=steps, run_id=run_id,
                    barrier_timeout=gen_timeout, sdc=spec,
                    flight_dir=flight_dir,
                    log_path=_log(f"sdc_rank{r}.log"))
                for r in range(world)
            ]
            rcs = _wait_fleet(procs, gen_timeout)
            report["rcs"] = rcs
            _assert_sdc_consensus(report, out_dir, rcs, world=world,
                                  steps=steps, poison_step=poison_step,
                                  poison_rank=poison_rank,
                                  cadence=cadence)
        else:  # quarantine
            from ..supervisor import Supervisor

            world0 = world

            def spawn(rank, w, run_id, generation):
                gdir = os.path.join(out_dir, f"g{generation}")
                os.makedirs(gdir, exist_ok=True)
                # the bad host re-poisons while it is in the pool; the
                # post-quarantine downsized world runs clean
                spec = SdcSpec(
                    out_dir=gdir, poison_step=poison_step,
                    poison_rank=poison_rank if w == world0 else -1,
                    cadence=cadence, bit=bit,
                    exchange_timeout=exch_timeout)
                return spawn_worker(
                    rank, w, root=root, port=master.port,
                    total_steps=steps, run_id=run_id,
                    barrier_timeout=gen_timeout, sdc=spec,
                    log_path=_log(f"sdc_q_g{generation}_rank{rank}.log"))

            sup = Supervisor(
                spawn, world, sdc_max_restarts=sdc_max_restarts,
                sdc_quarantine_threshold=quarantine_threshold,
                grace=3.0 * barrier_timeout,
                generation_timeout=gen_timeout,
                run_id_prefix=f"sdcq-{uuid.uuid4().hex[:6]}")
            snap = sup.run()
            report["supervision"] = snap
            _assert_sdc_quarantine(report, snap,
                                   poison_rank=poison_rank,
                                   threshold=quarantine_threshold,
                                   world=world)
    finally:
        try:
            master.close()
        except Exception as e:
            logger.debug("sdc drill: master close after run: %s", e)
        reap_all()
    return report


def _assert_sdc_consensus(report, out_dir, rcs, *, world, steps,
                          poison_step, poison_rank, cadence):
    """Assertions for the consensus scenario (shared with the control
    run, where ``poison_rank`` is -1 and nobody may be fingered)."""
    clean_run = poison_rank < 0
    for r, rc in enumerate(rcs):
        want = EXIT_SDC if (not clean_run and r == poison_rank) else 0
        if rc != want:
            raise DrillFailure(
                f"sdc rank {r} exited {rc}, expected {want}")
    ranks = {}
    for r in range(world):
        rep_path = sdc_report_path(out_dir, r)
        try:
            with open(rep_path, "r", encoding="utf-8") as f:
                rep = json.load(f)
        except (OSError, ValueError) as e:
            raise DrillFailure(
                f"rank {r} wrote no parseable sdc report at "
                f"{rep_path}: {e}") from e
        ranks[r] = rep
        if rep.get("compiles") != 1:
            raise DrillFailure(
                f"rank {r} compiled its captured step "
                f"{rep.get('compiles')} times; the fingerprinted step "
                f"must stay at exactly 1 compile")
        if rep.get("fallback"):
            raise DrillFailure(
                f"rank {r} fell back to eager "
                f"{rep.get('fallback')} times")
    report["ranks"] = ranks

    if clean_run:
        for r, rep in ranks.items():
            if rep.get("divergences_total"):
                raise DrillFailure(
                    f"control run: rank {r} booked verdicts "
                    f"{rep.get('divergences')!r} on bit-identical "
                    f"replicas")
        return

    # --- the victim: halt, detection window, attribution, flight -----
    rep = ranks[poison_rank]
    if not rep.get("halted"):
        raise DrillFailure(
            f"victim rank {poison_rank} never halted: {rep!r}")
    detected = rep.get("detected_step")
    if detected is None or \
            not poison_step < detected <= poison_step + cadence:
        raise DrillFailure(
            f"detection at step {detected} is outside one cadence "
            f"window ({poison_step}, {poison_step + cadence}] of the "
            f"injection")
    last = rep.get("last_divergence") or {}
    if last.get("rank") != poison_rank:
        raise DrillFailure(
            f"victim's own verdict names rank {last.get('rank')!r}, "
            f"expected {poison_rank}")
    named = last.get("tensor")
    if not named or not (named.startswith("param::")
                         or named.startswith("opt")):
        raise DrillFailure(
            f"consensus named no fingerprinted tensor path: {named!r}")
    fpath = rep.get("flight")
    try:
        with open(fpath, "r", encoding="utf-8") as f:
            flight = json.load(f)
    except (TypeError, OSError, ValueError) as e:
        raise DrillFailure(
            f"victim's flight dump unreadable at {fpath!r}: {e}") from e
    reason = flight.get("reason") or ""
    if not reason.startswith("sdc:divergence:") or named not in reason:
        raise DrillFailure(
            f"flight dump reason {reason!r} must pin the divergent "
            f"tensor {named!r}")
    if flight.get("process_index") != poison_rank:
        raise DrillFailure(
            f"flight dump identity {flight.get('process_index')!r} != "
            f"victim rank {poison_rank}")
    report.update({"detected_step": detected, "named_tensor": named,
                   "flight_reason": reason})

    # --- clean ranks: correct attribution, nothing else --------------
    for r in range(world):
        if r == poison_rank:
            continue
        rep = ranks[r]
        if rep.get("halted"):
            raise DrillFailure(f"clean rank {r} halted")
        div = rep.get("divergences") or {}
        if list(div) != [str(poison_rank)]:
            raise DrillFailure(
                f"clean rank {r} booked verdicts against {sorted(div)}"
                f", expected exactly [{poison_rank!r}] — consensus "
                f"must finger the victim and nobody else")
        peer_last = rep.get("last_divergence") or {}
        if peer_last.get("rank") != poison_rank:
            raise DrillFailure(
                f"clean rank {r} attributes the divergence to rank "
                f"{peer_last.get('rank')!r}, expected {poison_rank}")


def _assert_sdc_quarantine(report, snap, *, poison_rank, threshold,
                           world):
    """Assertions for the quarantine scenario."""
    final_rcs = snap.get("final_rcs") or {}
    if not final_rcs or any(rc != 0 for rc in final_rcs.values()):
        raise DrillFailure(
            f"quarantine: final generation rcs {final_rcs}, expected "
            f"a clean downsized fleet (all 0)")
    if snap.get("quarantined_ranks") != [poison_rank]:
        raise DrillFailure(
            f"quarantine: quarantined_ranks "
            f"{snap.get('quarantined_ranks')}, expected "
            f"[{poison_rank}]")
    verdicts = (snap.get("sdc_verdicts") or {}).get(str(poison_rank), 0)
    if verdicts < threshold:
        raise DrillFailure(
            f"quarantine: only {verdicts} consensus verdicts against "
            f"rank {poison_rank}, expected >= {threshold}")
    by_cause = snap.get("restarts_by_cause") or {}
    if by_cause.get("sdc", 0) < threshold:
        raise DrillFailure(
            f"quarantine: restarts_by_cause {by_cause} books "
            f"{by_cause.get('sdc', 0)} 'sdc' restarts, expected >= "
            f"{threshold} — EXIT_SDC must charge the hardware ledger")
    if any(c in by_cause for c in ("crashed", "killed")):
        raise DrillFailure(
            f"quarantine: consensus verdicts leaked into the "
            f"code-crash budget: {by_cause}")
    quarantine_resizes = [rz for rz in snap.get("resizes") or []
                          if rz.get("quarantined")]
    if not quarantine_resizes or \
            quarantine_resizes[0].get("dead_ranks") != [poison_rank]:
        raise DrillFailure(
            f"quarantine: no elastic downsize around rank "
            f"{poison_rank}: {snap.get('resizes')!r}")
    if snap.get("world") != world - 1:
        raise DrillFailure(
            f"quarantine: final world {snap.get('world')}, expected "
            f"{world - 1} (the suspect host left the pool)")


def _run_sdc_restore_leg(root, report, *, steps, bit, barrier_timeout,
                         gen_timeout, _log):
    """The restore scenario: clean run → poison_shard → manifest still
    verifies → full integrity refuses naming the leaf → resuming
    worker exits ``EXIT_SDC``."""
    ckpt_root = os.path.join(root, "ckpt")
    os.makedirs(ckpt_root, exist_ok=True)
    try:
        p = spawn_worker(0, 1, root=ckpt_root, total_steps=steps,
                         run_id=f"sdcr-{uuid.uuid4().hex[:6]}",
                         barrier_timeout=barrier_timeout,
                         log_path=_log("sdc_restore_g0.log"))
        rcs = _wait_fleet([p], gen_timeout)
        if rcs != [0]:
            raise DrillFailure(
                f"restore: clean generation exited {rcs}, expected [0]")
        latest = _latest_step(ckpt_root)
        if latest != steps:
            raise DrillFailure(
                f"restore: newest committed step {latest}, wanted "
                f"{steps}")
        d = os.path.join(ckpt_root, f"step_{int(latest):08d}")
        verify_checkpoint(d, integrity="full")  # clean before poison
        rel = poison_shard(d, bit=bit)
        report["poisoned_file"] = rel
        leaf = rel.split(os.sep)[1] if rel.count(os.sep) >= 2 else rel
        # the sealed manifest CRC passes — the corruption is silent at
        # the file level...
        verify_checkpoint(d, integrity="size")
        if read_leaf(d, leaf, integrity="size") is None:
            raise DrillFailure("restore: size-integrity read failed")
        # ...and only the content digest refuses, naming the leaf
        try:
            verify_checkpoint(d, integrity="full")
        except CheckpointCorruptError as e:
            msg = str(e)
            if "content digest" not in msg or f"'{leaf}'" not in msg:
                raise DrillFailure(
                    f"restore: refusal does not name the poisoned "
                    f"leaf {leaf!r} and its digest: {msg!r}") from e
            report["refusal"] = msg
        else:
            raise DrillFailure(
                f"restore: poisoned checkpoint (file {rel!r}) passed "
                f"full verification — the content digest caught "
                f"nothing")
        p = spawn_worker(0, 1, root=ckpt_root, total_steps=steps * 2,
                         run_id=f"sdcr-{uuid.uuid4().hex[:6]}",
                         barrier_timeout=barrier_timeout,
                         restore_integrity="full",
                         log_path=_log("sdc_restore_g1.log"))
        rc = _wait_fleet([p], gen_timeout)[0]
        report["resume_rc"] = rc
        if rc != EXIT_SDC:
            raise DrillFailure(
                f"restore: resuming worker exited {rc}, expected "
                f"EXIT_SDC ({EXIT_SDC}) — it must refuse to train on "
                f"bit-rotted state")
        latest2 = _latest_step(ckpt_root)
        if latest2 != steps:
            raise DrillFailure(
                f"restore: refused resume advanced the checkpoint to "
                f"{latest2} (was {steps}) — nothing may be written "
                f"past a refused restore")
    finally:
        reap_all()
    return report


def run_oom_drill(root, *, world=2, steps=8, oom_step=5, oom_rank=1,
                  mem_bytes=1_000_000, mem_threshold=None,
                  gen_timeout=120.0, log_dir=None):
    """OOM-postmortem drill: ``world`` REAL worker processes each
    train a captured MLP on CPU with the memory monitor armed;
    ``oom_rank`` swaps its compiled cache entry for a callable raising
    ``RESOURCE_EXHAUSTED`` at ``oom_step``, so the capture replay's
    intercept must book a flight dump whose reason pins
    ``oom:<program>:<buffer>`` with the buffer being a PARAMETER PATH
    (the drill model's first weight dominates every other live array
    by construction) and whose ``extra.memory`` payload carries the
    census, per-program footprints and watermark history.  The victim
    exits ``EXIT_OOM`` (23) cleanly after writing its report; clean
    ranks exit 0 with zero postmortems; every rank compiles exactly
    once (the armed failure is a cache HIT, never a retrace).

    Each rank also exports a rank-scaled synthetic watermark
    (``mem_bytes * (1 + rank)``) and dumps its /metrics exposition;
    the runner replays those dumps through a LOCAL
    :class:`~paddle_tpu.observability.aggregator.ClusterAggregator`
    (threshold ``mem_threshold``, default ``mem_bytes * world`` so the
    near-OOM trip fires exactly) and asserts the fleet view: skew
    gauge ``mem_bytes * (world - 1)``, per-rank bytes in /healthz, and
    the memory alarm flipping health to not-ok.  Storeless: no
    TCPStore master, no checkpoints.  Returns a report dict."""
    out_dir = os.path.join(root, "oom")
    flight_dir = os.path.join(root, "flight")
    os.makedirs(out_dir, exist_ok=True)
    run_id = f"oom-{uuid.uuid4().hex[:6]}"
    if mem_threshold is None:
        mem_threshold = mem_bytes * world
    spec = OomSpec(out_dir=out_dir, oom_step=oom_step,
                   oom_rank=oom_rank, mem_bytes=mem_bytes)
    report = {"run_id": run_id, "world": world, "steps": steps,
              "oom_step": oom_step, "oom_rank": oom_rank,
              "mem_bytes": mem_bytes, "mem_threshold": mem_threshold}
    try:
        procs = [
            spawn_worker(
                r, world, root=root, total_steps=steps, run_id=run_id,
                barrier_timeout=gen_timeout, oom=spec,
                flight_dir=flight_dir,
                log_path=(os.path.join(log_dir, f"oom_rank{r}.log")
                          if log_dir else None))
            for r in range(world)
        ]
        rcs = _wait_fleet(procs, gen_timeout)
        report["rcs"] = rcs
        for r, rc in enumerate(rcs):
            want = EXIT_OOM if r == oom_rank else 0
            if rc != want:
                raise DrillFailure(
                    f"oom rank {r} exited {rc}, expected {want}")

        ranks = {}
        for r in range(world):
            rep_path = oom_report_path(out_dir, r)
            try:
                with open(rep_path, "r", encoding="utf-8") as f:
                    rep = json.load(f)
            except (OSError, ValueError) as e:
                raise DrillFailure(
                    f"rank {r} wrote no parseable oom report at "
                    f"{rep_path}: {e}") from e
            ranks[r] = rep
            if rep.get("compiles") != 1:
                raise DrillFailure(
                    f"rank {r} compiled its captured step "
                    f"{rep.get('compiles')} times; the armed failure "
                    f"must replay a cache hit, never retrace")
            if rep.get("fallback"):
                raise DrillFailure(
                    f"rank {r} fell back to eager: "
                    f"{rep.get('fallback')!r}")
        report["ranks"] = ranks

        # --- the victim: postmortem booked, flight dump pins a param -
        rep = ranks[oom_rank]
        if not rep.get("caught") or rep.get("oom_events") != 1:
            raise DrillFailure(
                f"victim rank {oom_rank} booked "
                f"{rep.get('oom_events')} postmortems (caught="
                f"{rep.get('caught')!r}), expected exactly 1")
        fpath = rep.get("flight")
        try:
            with open(fpath, "r", encoding="utf-8") as f:
                flight = json.load(f)
        except (TypeError, OSError, ValueError) as e:
            raise DrillFailure(
                f"victim's flight dump unreadable at {fpath!r}: "
                f"{e}") from e
        reason = flight.get("reason") or ""
        named = reason.split(":", 2)[2] if reason.count(":") >= 2 \
            else ""
        if not reason.startswith("oom:") \
                or not named.startswith("param::"):
            raise DrillFailure(
                f"flight dump reason {reason!r} must pin the top live "
                f"buffer to a parameter path (param::...)")
        if flight.get("process_index") != oom_rank:
            raise DrillFailure(
                f"flight dump identity "
                f"{flight.get('process_index')!r} != victim rank "
                f"{oom_rank}")
        mem_doc = (flight.get("extra") or {}).get("memory") or {}
        census = mem_doc.get("census") or {}
        top = census.get("top") or []
        if mem_doc.get("top_buffer") != named or not top \
                or top[0].get("name") != named:
            raise DrillFailure(
                f"postmortem census top {top[:1]!r} disagrees with "
                f"the flight reason's buffer {named!r}")
        if not mem_doc.get("programs"):
            raise DrillFailure(
                "postmortem carries no per-program footprints; the "
                "compile-time harvest must ride into the flight dump")
        if not mem_doc.get("watermarks"):
            raise DrillFailure(
                "postmortem carries no watermark history; the "
                "synthetic samples must ride into the flight dump")
        report.update({"flight_reason": reason, "named_buffer": named,
                       "census_categories":
                           sorted(census.get("by_category") or {})})

        # --- clean ranks booked nothing ------------------------------
        for r in range(world):
            if r == oom_rank:
                continue
            if ranks[r].get("oom_events") or ranks[r].get("caught"):
                raise DrillFailure(
                    f"clean rank {r} booked an OOM postmortem: "
                    f"{ranks[r]!r}")

        # --- fleet view: replay the per-rank expositions through a
        # local aggregator and assert skew + the near-OOM trip --------
        from ...observability.aggregator import (ClusterAggregator,
                                                 parse_prometheus_text)
        agg = ClusterAggregator(
            endpoints={r: f"drill-rank-{r}" for r in range(world)},
            run_id=run_id, mem_threshold=mem_threshold)
        for r in range(world):
            mpath = oom_metrics_path(out_dir, r)
            try:
                with open(mpath, "r", encoding="utf-8") as f:
                    fams = parse_prometheus_text(f.read())
            except (OSError, ValueError) as e:
                raise DrillFailure(
                    f"rank {r} exposition dump unreadable at "
                    f"{mpath}: {e}") from e
            agg._scrapes[r] = {"ts": time.monotonic(),
                               "families": fams, "error": None}
        agg._render()
        fams = parse_prometheus_text(agg.prometheus_text())
        skew = _sample_value(fams, "pt_cluster_memory_skew_bytes")
        # the victim died before feeding a watermark only when the
        # injection step precedes its first sample; every surviving
        # rank r published mem_bytes * (1 + r)
        live = [r for r in range(world)
                if ranks[r].get("watermark_samples")]
        want_skew = float(mem_bytes * (max(live) - min(live)))
        if skew != want_skew:
            raise DrillFailure(
                f"fleet memory skew {skew!r}, expected {want_skew} "
                f"from ranks {live} at base {mem_bytes}")
        health = agg.healthz()
        hmem = health.get("memory") or {}
        want_alarm = mem_bytes * (1 + max(live)) >= mem_threshold
        if bool(hmem.get("mem_alarm")) != want_alarm \
                or health.get("ok") != (not want_alarm):
            raise DrillFailure(
                f"aggregator health {hmem!r} ok={health.get('ok')}; "
                f"expected mem_alarm={want_alarm} at threshold "
                f"{mem_threshold}")
        oom_total = _sample_value(fams, "pt_cluster_oom_events_total")
        if oom_total is None:
            oom_total = sum(
                ranks[r].get("oom_events", 0) for r in range(world))
        report.update({"fleet_skew_bytes": skew,
                       "mem_alarm": bool(hmem.get("mem_alarm")),
                       "healthz": health,
                       "oom_events_total": oom_total})
    finally:
        reap_all()
    return report


def _overlap_param_tree(layers, hidden):
    """Synthetic MLP parameter tree (registration order: first→last)
    plus per-name and total byte counts."""
    import numpy as np

    params = {}
    for i in range(layers):
        params[f"l{i}.weight"] = np.zeros((hidden, hidden), np.float32)
        params[f"l{i}.bias"] = np.zeros((hidden,), np.float32)
    nbytes = {k: v.size * v.dtype.itemsize for k, v in params.items()}
    return params, nbytes, sum(nbytes.values())


def _overlap_replay(params, nbytes, spans_fn, run_id,
                    compute_bytes_per_ns):
    """Replay one reduction mode's span timeline through the REAL
    tracer and return its snapshot.

    The backward is a per-param compute span, last-registered first
    (the order autodiff produces grads); ``spans_fn(tr, ready,
    bwd_end) -> coll_end`` records that mode's collective spans given
    each grad's ready time; the optimizer span starts after the last
    collective (it waits for every reduced grad)."""
    from ...observability.trace import get_tracer, reset_tracer

    total_bytes = sum(nbytes.values())
    reset_tracer()
    tr = get_tracer().enable(process_index=0, run_id=run_id)
    t, ready = 1_000_000, {}
    for name in reversed(params):
        dur = max(int(nbytes[name] / compute_bytes_per_ns), 1)
        tr.phase_record("backward", t, t + dur)
        t += dur
        ready[name] = t
    coll_end = max(spans_fn(tr, ready, t), t)
    opt_end = coll_end + max(int(total_bytes / compute_bytes_per_ns
                                 / 10), 1)
    tr.phase_record("optimizer", coll_end, opt_end)
    tr.on_step((opt_end - 1_000_000) / 1e9)
    snap = tr.snapshot()
    reset_tracer()
    return snap


def _write_overlap_report(root, name, report):
    os.makedirs(root, exist_ok=True)
    path = os.path.join(root, name)
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=2)
    os.replace(tmp, path)
    report["report_path"] = path
    return report


def run_overlap_drill(root, *, layers=8, hidden=256, bucket_kb=256,
                      comm_bytes_per_ns=2.0, compute_bytes_per_ns=1.0):
    """Compute↔collective overlap drill: prove the bucketed gradient
    reduction RAISES the measured overlap fraction vs the monolithic
    post-backward reduction — on the same synthetic model, through the
    REAL partitioner and the REAL tracer.

    The span timelines are the schedules the two reduction modes pin
    down (synthetic timestamps, no sleeping):

    - *unbucketed*: backward compute runs end-to-end, then ONE fused
      all-reduce of every gradient byte, then the optimizer — the
      collective sits alone on the critical path, overlap 0.
    - *bucketed*: ``partition_buckets`` groups the same parameters
      (reverse-backward order); each bucket's fused reduction is issued
      the moment its last member's grad is formed and runs while the
      REMAINING backward compute proceeds — exactly where autodiff
      places the ``bucket_reduce_marker`` pmean in the compiled step.
      Only the final bucket's reduction has no compute left to hide
      under.

    Both timelines feed the real ``Tracer`` (``phase_record`` /
    ``record_span`` → ``pt_compute_collective_overlap_fraction``); the
    drill asserts bucketed > unbucketed ≥ 0 and writes a report JSON.
    Returns the report dict.
    """
    from ..grad_buckets import partition_buckets

    params, nbytes, total_bytes = _overlap_param_tree(layers, hidden)
    plan = partition_buckets(params, int(bucket_kb) * 1024)
    if plan.n_buckets < 2:
        raise DrillFailure(
            f"bucket_kb={bucket_kb} yields {plan.n_buckets} bucket(s); "
            f"the drill needs >= 2 to show overlap")

    def unbucketed(tr, ready, bwd_end):
        dur = max(int(total_bytes / comm_bytes_per_ns), 1)
        tr.record_span("all_reduce", "collective", bwd_end,
                       bwd_end + dur)
        return bwd_end + dur

    def bucketed(tr, ready, bwd_end):
        coll_end = bwd_end
        for b in plan.buckets:
            t0 = max(ready[n] for n in b.names)
            dur = max(int(b.nbytes / comm_bytes_per_ns), 1)
            tr.record_span("all_reduce", "collective", t0, t0 + dur)
            coll_end = max(coll_end, t0 + dur)
        return coll_end

    snap_un = _overlap_replay(params, nbytes, unbucketed,
                              "overlap-unbucketed", compute_bytes_per_ns)
    snap_bk = _overlap_replay(params, nbytes, bucketed,
                              "overlap-bucketed", compute_bytes_per_ns)
    ov_un = snap_un.get("overlap_fraction")
    ov_bk = snap_bk.get("overlap_fraction")
    if ov_un is None or ov_bk is None:
        raise DrillFailure(
            f"tracer measured no overlap fraction (unbucketed={ov_un!r} "
            f"bucketed={ov_bk!r}) — collective spans missing?")
    if not ov_bk > ov_un:
        raise DrillFailure(
            f"bucketed overlap {ov_bk} not strictly above unbucketed "
            f"{ov_un}")
    if ov_bk <= 0.0:
        raise DrillFailure(f"bucketed overlap {ov_bk} not positive")
    report = {
        "n_buckets": plan.n_buckets,
        "bucket_bytes": [b.nbytes for b in plan.buckets],
        "total_bytes": total_bytes,
        "overlap_unbucketed": ov_un,
        "overlap_bucketed": ov_bk,
    }
    return _write_overlap_report(root, "overlap_report.json", report)


def run_sharded_overlap_drill(root, *, layers=8, hidden=256,
                              bucket_kb=256, n_dp=2, n_shard=4,
                              ici_bytes_per_ns=4.0, dcn_bytes_per_ns=1.0,
                              compute_bytes_per_ns=1.0):
    """Sharded-mesh (ZeRO dp×sharding) overlap drill.

    Same replay harness as :func:`run_overlap_drill`, but the two
    timelines are the ones the collective-schedule pass chooses
    between on a ZeRO mesh:

    - *unbucketed (GSPMD)*: backward runs end-to-end, then ONE
      monolithic reduction of every gradient byte over the product
      communicator — the full payload crosses the slow dp links and
      nothing hides it: overlap 0.
    - *bucketed + scheduled*: the REAL partitioner (with the params'
      ``place_axis`` scatter dims) and the REAL planner
      (:func:`~paddle_tpu.distributed.collective_schedule.
      plan_grad_reduction`) produce per-bucket
      ``reduce_scatter(sharding) → all_reduce(dp) → all_gather``
      chains, each issued at its bucket's grad-ready time.  The
      reduce-scatter/all-gather legs move at ICI speed and the dp leg
      carries only ``1/n_shard`` of the bytes at DCN speed, while the
      remaining backward hides all but the last bucket's chain.

    Asserts the scheduled overlap is strictly above the monolithic
    baseline AND above 0.5 — the bar ``dryrun_multichip`` reports for
    sharded configs.  Writes/returns the report dict.
    """
    from jax.sharding import PartitionSpec as P

    from ..auto_parallel.spec_layout import place_axis, spec_axes
    from ..collective_schedule import plan_grad_reduction
    from ..grad_buckets import partition_buckets

    params, nbytes, total_bytes = _overlap_param_tree(layers, hidden)
    scatter_dims = {}
    for k, v in params.items():
        zs = place_axis(P(), v.shape, n_shard, "sharding")
        scatter_dims[k] = next(
            (d for d, e in enumerate(zs) if "sharding" in spec_axes(e)),
            None)
    plan = partition_buckets(params, int(bucket_kb) * 1024,
                             scatter_dims=scatter_dims)
    sched = plan_grad_reduction({"dp": n_dp, "sharding": n_shard}, "os")
    if sched is None or not sched.scatters:
        raise DrillFailure(
            f"planner produced no scatter schedule for dp={n_dp} "
            f"sharding={n_shard}")
    if plan.n_buckets < 2:
        raise DrillFailure(
            f"bucket_kb={bucket_kb} yields {plan.n_buckets} bucket(s); "
            f"the drill needs >= 2 to show overlap")

    def unbucketed(tr, ready, bwd_end):
        # GSPMD's monolithic post-backward reduction: every byte over
        # the slow link, one op, nothing left to hide it under
        dur = max(int(total_bytes / dcn_bytes_per_ns), 1)
        tr.record_span("all_reduce", "collective", bwd_end,
                       bwd_end + dur)
        return bwd_end + dur

    def scheduled(tr, ready, bwd_end):
        coll_end = bwd_end
        for b in plan.buckets:
            t = max(ready[n] for n in b.names)
            for st in sched.stages:
                if b.kind != "reduce_scatter" and st.op != "all_reduce":
                    continue  # unscatterable buckets: plain dp pmean
                payload = b.nbytes
                if b.kind == "reduce_scatter" and st.op != "reduce_scatter":
                    payload = b.nbytes // sched.shard_size
                rate = (dcn_bytes_per_ns if st.axis == "dp"
                        else ici_bytes_per_ns)
                dur = max(int(payload / rate), 1)
                tr.record_span(st.op, "collective", t, t + dur)
                t += dur
            coll_end = max(coll_end, t)
        return coll_end

    snap_un = _overlap_replay(params, nbytes, unbucketed,
                              "sharded-overlap-unbucketed",
                              compute_bytes_per_ns)
    snap_bk = _overlap_replay(params, nbytes, scheduled,
                              "sharded-overlap-scheduled",
                              compute_bytes_per_ns)
    ov_un = snap_un.get("overlap_fraction")
    ov_bk = snap_bk.get("overlap_fraction")
    if ov_un is None or ov_bk is None:
        raise DrillFailure(
            f"tracer measured no overlap fraction (unbucketed={ov_un!r} "
            f"scheduled={ov_bk!r}) — collective spans missing?")
    if not ov_bk > ov_un:
        raise DrillFailure(
            f"scheduled overlap {ov_bk} not strictly above the "
            f"monolithic baseline {ov_un}")
    if not ov_bk > 0.5:
        raise DrillFailure(
            f"scheduled overlap {ov_bk} below the 0.5 bar")
    report = {
        "n_buckets": plan.n_buckets,
        "bucket_bytes": [b.nbytes for b in plan.buckets],
        "total_bytes": total_bytes,
        "schedule": sched.describe(),
        "mesh": {"dp": n_dp, "sharding": n_shard},
        "overlap_unbucketed": ov_un,
        "overlap_scheduled": ov_bk,
    }
    return _write_overlap_report(root, "sharded_overlap_report.json",
                                 report)


# -- serving chaos drill -----------------------------------------------------

def _http_post(url, obj, timeout=30.0):
    """Bounded JSON POST returning (status, body-text, headers); 4xx/5xx
    responses return their body instead of raising."""
    data = json.dumps(obj).encode("utf-8")
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read().decode("utf-8"), resp.headers
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode("utf-8"), e.headers


def spawn_serve_worker(*, root, name, spec, seed=0, request_timeout=60.0,
                       env_extra=None, log_path=None, spawn_timeout=240.0):
    """Launch the serving engine as a REAL subprocess
    (``python -m paddle_tpu.serving --spec ...``) and wait for it to
    build its AOT ladder and publish ``host:port`` into
    ``<root>/<name>.endpoint``.  Returns ``(Popen, (host, port))``;
    registered for :func:`reap_all`."""
    port_file = os.path.join(root, f"{name}.endpoint")
    try:
        os.unlink(port_file)
    except FileNotFoundError:
        pass
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(env_extra or {})
    cmd = [sys.executable, "-m", "paddle_tpu.serving",
           "--spec", json.dumps(spec), "--seed", str(seed),
           "--port-file", port_file,
           "--request-timeout", str(request_timeout)]
    if log_path:
        with open(log_path, "ab") as out:
            p = subprocess.Popen(cmd, env=env, stdout=out,
                                 stderr=subprocess.STDOUT)
    else:
        p = subprocess.Popen(cmd, env=env, stdout=subprocess.DEVNULL,
                             stderr=subprocess.DEVNULL)
    _LIVE.add(p)

    def _published():
        if p.poll() is not None:
            raise DrillFailure(
                f"serve worker {name} died during startup "
                f"(rc {p.poll()})")
        return read_endpoint_file(port_file)

    try:
        # the endpoint lands only AFTER the AOT ladder finished
        # compiling, so this wait covers the whole cold start
        ep = wait_until(_published, spawn_timeout,
                        desc=f"serve worker {name} to publish its "
                             f"endpoint")
    except TimeoutError as e:
        raise DrillFailure(f"serve worker {name} never came up: {e}") \
            from e
    logger.info("serve worker %s pid %d at %s:%d", name, p.pid,
                ep[0], ep[1])
    return p, ep


def run_serve_chaos_drill(root, *, max_new=8, storm_requests=6,
                          request_timeout=60.0, gen_timeout=240.0,
                          log_dir=None):
    """End-to-end serving resilience drill against REAL engine
    subprocesses (``python -m paddle_tpu.serving``), with an in-process
    solo-decode oracle built from the same ModelSpec + seed:

     1. **SIGKILL mid-decode** — generation 1 is killed while /healthz
        shows active sequences; nothing survives it but the OS.
     2. **Relaunch recovers** — generation 2 rebuilds the AOT ladder
        from scratch, reports a consistent empty page pool, serves
        every prompt with tokens bit-identical to the oracle's solo
        decode, and books ZERO request-path compiles.
     3. **Deadline storm sheds, never breaks** — after a warm request
        seeds the throughput EWMA, ``storm_requests`` infeasible
        deadlines (``deadline_ms=0.001``) must ALL be refused with 429
        + ``Retry-After`` (shed, not queued), while an interleaved
        generous request still returns bit-identical tokens; the shed
        counter accounts for every refusal and the pool ends the storm
        with zero used/reserved pages.
     4. **Disconnecting client** — a caller that drops its socket
        mid-request is cancelled (``cause="disconnect"``) and its
        pages come back.
     5. **SIGTERM graceful drain** — in-flight requests submitted just
        before SIGTERM all complete with FULL token counts (no partial
        responses), a request posted during the drain window is
        refused 503 ``draining``, and the process exits 143.

    Returns a report dict; raises :class:`DrillFailure` on any broken
    invariant.
    """
    import threading

    spec = {"vocab_size": 128, "hidden": 64, "layers": 4, "heads": 2,
            "max_seq_len": 64}
    seed = 7
    prompts = [[3, 1, 4, 1, 5], [2, 7, 18, 28], [31, 41, 5, 9, 26, 53]]
    env_serve = {
        "PT_SERVE_BUCKETS": "2,4",
        "PT_SERVE_PREFILL_BUCKETS": "16",
        "PT_SERVE_KV_PAGES": "64",
        "PT_SERVE_PAGE_SIZE": "8",
        "PT_SERVE_DRAIN_S": "20",
    }

    def _log(name):
        return os.path.join(log_dir, name) if log_dir else None

    # ---- the oracle: same spec + seed, solo decode in-process -------
    from ...serving import (ModelSpec, ServeConfig, ServingEngine,
                            init_params)
    mspec = ModelSpec.from_dict(spec)
    # the oracle honors PT_SERVE_PRECISION so the bit-identity legs
    # hold at every fixed precision (the engine subprocesses inherit
    # the same env): int8 oracle vs int8 workers, never cross-precision
    cfg = ServeConfig(decode_buckets=(2, 4), prefill_buckets=(16,),
                      kv_pages=64, page_size=8,
                      precision=os.environ.get("PT_SERVE_PRECISION")
                      or "fp32")
    oracle_engine = ServingEngine(mspec, init_params(mspec, seed), cfg)
    oracle = [oracle_engine.generate([p], max_new_tokens=max_new)[0]
              for p in prompts]
    oracle_engine.scheduler.stop()

    report = {"oracle_lens": [len(t) for t in oracle]}

    def _healthz(base):
        status, body = _http_get(base + "/healthz", timeout=5.0)
        return status, json.loads(body)

    # ---- leg 1: SIGKILL mid-decode ----------------------------------
    p1, (h1, port1) = spawn_serve_worker(
        root=root, name="serve_gen1", spec=spec, seed=seed,
        request_timeout=request_timeout, env_extra=env_serve,
        log_path=_log("serve_gen1.log"), spawn_timeout=gen_timeout)
    base1 = f"http://{h1}:{port1}"

    def _fire(base, body, out):
        try:
            out.append(_http_post(base + "/v1/generate", body,
                                  timeout=request_timeout))
        except OSError as e:       # the SIGKILL resets these sockets
            out.append(("conn-error", str(e), None))

    doomed = []
    threads = [
        threading.Thread(
            target=_fire, daemon=True,
            args=(base1,
                  {"tokens": prompts[i % len(prompts)],
                   "max_new_tokens": 48},
                  doomed))
        for i in range(6)
    ]
    for t in threads:
        t.start()

    def _busy():
        _status, health = _healthz(base1)
        snap = health.get("active_sequences", 0) or 0
        return True if snap > 0 else None

    wait_until(_busy, gen_timeout / 4,
               desc="generation 1 to show active decode sequences")
    p1.kill()
    rc1 = p1.wait(timeout=30)
    _LIVE.discard(p1)
    if rc1 != -signal.SIGKILL:
        raise DrillFailure(
            f"generation 1 exited {rc1}, expected SIGKILL (-9)")
    for t in threads:
        t.join(timeout=request_timeout)
    report["gen1_rc"] = rc1

    # ---- leg 2: relaunch recovers, zero request-path compiles -------
    p2, (h2, port2) = spawn_serve_worker(
        root=root, name="serve_gen2", spec=spec, seed=seed,
        request_timeout=request_timeout, env_extra=env_serve,
        log_path=_log("serve_gen2.log"), spawn_timeout=gen_timeout)
    base2 = f"http://{h2}:{port2}"
    try:
        status, health = _healthz(base2)
        if status != 200 or not health.get("ok"):
            raise DrillFailure(
                f"relaunched engine unhealthy: {status} {health}")
        kv = health.get("kv") or {}
        if kv.get("used_pages") or kv.get("reserved_pages") \
                or not health.get("kv_consistent"):
            raise DrillFailure(
                f"relaunched page pool not a clean slate: {kv}")
        for i, prompt in enumerate(prompts):
            status, body, _hdrs = _http_post(
                base2 + "/v1/generate",
                {"tokens": prompt, "max_new_tokens": max_new},
                timeout=request_timeout)
            if status != 200:
                raise DrillFailure(
                    f"relaunched engine refused prompt {i}: "
                    f"{status} {body}")
            tokens = json.loads(body)["tokens"]
            if tokens != oracle[i]:
                raise DrillFailure(
                    f"prompt {i} after relaunch decoded {tokens}, "
                    f"oracle solo decode says {oracle[i]} — "
                    f"recovery broke bit-identity")
        _status, health = _healthz(base2)
        if health.get("unexpected_compiles"):
            raise DrillFailure(
                f"{health['unexpected_compiles']} request-path "
                f"compiles after relaunch — the AOT ladder has a hole")
        report["gen2_recovered"] = True

        # ---- leg 3: deadline storm sheds, never breaks --------------
        shed_429 = 0
        for _ in range(storm_requests):
            status, body, hdrs = _http_post(
                base2 + "/v1/generate",
                {"tokens": prompts[0], "max_new_tokens": 32,
                 "deadline_ms": 0.001},
                timeout=request_timeout)
            if status != 429:
                raise DrillFailure(
                    f"infeasible deadline answered {status} {body}, "
                    f"expected 429 (shed)")
            if json.loads(body).get("reason") != "deadline_infeasible":
                raise DrillFailure(
                    f"shed reason {body}, expected deadline_infeasible")
            if int(hdrs.get("Retry-After", 0)) < 1:
                raise DrillFailure(
                    "429 without a usable Retry-After header")
            shed_429 += 1
        # a generous request rides through the storm untouched
        status, body, _hdrs = _http_post(
            base2 + "/v1/generate",
            {"tokens": prompts[1], "max_new_tokens": max_new},
            timeout=request_timeout)
        if status != 200 or json.loads(body)["tokens"] != oracle[1]:
            raise DrillFailure(
                f"generous request during the storm: {status} {body}")
        _status, mbody = _http_get(base2 + "/metrics", timeout=5.0)
        from ...observability.aggregator import parse_prometheus_text
        fams = parse_prometheus_text(mbody)
        shed_metric = _sample_value(fams, "pt_serve_shed_total",
                                    reason="deadline_infeasible")
        if not shed_metric or shed_metric < storm_requests:
            raise DrillFailure(
                f"pt_serve_shed_total{{deadline_infeasible}} is "
                f"{shed_metric!r}, expected >= {storm_requests}")
        report["storm_shed"] = shed_429

        # ---- leg 4: a disconnecting client is cancelled -------------
        # fill the decode batch with long well-behaved requests first,
        # so the disconnectors' requests are still in flight (queued
        # or decoding) when the handler's socket watch looks — a tiny
        # model can otherwise finish before the first check
        import socket as _socket
        blocked = []
        blockers = [
            threading.Thread(
                target=_fire, daemon=True,
                args=(base2,
                      {"tokens": prompts[i % len(prompts)],
                       "max_new_tokens": 48},
                      blocked))
            for i in range(4)
        ]
        for t in blockers:
            t.start()

        def _batch_busy():
            _s, health = _healthz(base2)
            return True if (health.get("active_sequences", 0) or 0) \
                >= 2 else None

        wait_until(_batch_busy, gen_timeout / 4,
                   desc="blocker requests to fill the decode batch")
        payload = json.dumps({"tokens": prompts[2],
                              "max_new_tokens": 48}).encode()
        for _ in range(3):          # three callers walk away mid-decode
            s = _socket.create_connection((h2, port2), timeout=5.0)
            s.sendall(b"POST /v1/generate HTTP/1.1\r\n"
                      b"Host: drill\r\n"
                      b"Content-Type: application/json\r\n"
                      + f"Content-Length: {len(payload)}\r\n\r\n"
                      .encode() + payload)
            s.close()
        for t in blockers:
            t.join(timeout=request_timeout)
        if any(status != 200 for status, _b, _h in blocked):
            raise DrillFailure(
                f"blocker requests failed during the disconnect leg: "
                f"{[(s, b) for s, b, _h in blocked]}")

        def _disconnect_seen():
            _s, mb = _http_get(base2 + "/metrics", timeout=5.0)
            v = _sample_value(parse_prometheus_text(mb),
                              "pt_serve_cancelled_total",
                              cause="disconnect")
            return True if v else None

        wait_until(_disconnect_seen, gen_timeout / 4,
                   desc="disconnected client to be cancelled")

        def _pool_quiet():
            _s, health = _healthz(base2)
            kv = health.get("kv") or {}
            if kv.get("used_pages") == 0 and \
                    kv.get("reserved_pages") == 0:
                return True
            return None

        wait_until(_pool_quiet, gen_timeout / 4,
                   desc="page pool to return to baseline after the "
                        "storm (zero leaks)")
        report["disconnect_cancelled"] = True

        # ---- leg 5: SIGTERM graceful drain (exit 143) ---------------
        inflight = []
        dthreads = [
            threading.Thread(
                target=_fire, daemon=True,
                args=(base2,
                      {"tokens": prompts[i], "max_new_tokens": max_new},
                      inflight))
            for i in range(len(prompts))
        ]
        for t in dthreads:
            t.start()

        def _admitted():
            # count responses that already landed as admitted too: on a
            # fast host a request can finish before the last one is even
            # submitted, so instantaneous depth alone never reaches the
            # target and the wait would time out on a healthy server
            _s, health = _healthz(base2)
            depth = (health.get("active_sequences", 0) or 0) + \
                (health.get("queue_depth", 0) or 0)
            return True if depth + len(inflight) >= len(dthreads) else None

        wait_until(_admitted, gen_timeout / 4,
                   desc="drain-leg requests to be admitted")
        p2.send_signal(signal.SIGTERM)
        # the drain window: admission must already be closed while the
        # listener is still up (settle_s keeps it serving 503s); the
        # handler needs a beat to flip the draining flag
        time.sleep(0.1)
        status, body, _hdrs = _http_post(
            base2 + "/v1/generate",
            {"tokens": prompts[0], "max_new_tokens": max_new},
            timeout=request_timeout)
        if status != 503:
            raise DrillFailure(
                f"request during drain answered {status} {body}, "
                f"expected 503 (admission closed)")
        for t in dthreads:
            t.join(timeout=request_timeout)
        if len(inflight) != len(dthreads):
            raise DrillFailure(
                f"only {len(inflight)}/{len(dthreads)} drain-leg "
                f"responses arrived")
        for status, body, _hdrs in inflight:
            if status != 200:
                raise DrillFailure(
                    f"in-flight request cut short by the drain: "
                    f"{status} {body} — partial/failed response")
        # full-length AND bit-identical to the solo oracle: the drain
        # finished these requests, it did not truncate or corrupt them
        got = sorted(tuple(json.loads(body)["tokens"])
                     for _status, body, _hdrs in inflight)
        want = sorted(tuple(t) for t in oracle)
        if got != want:
            raise DrillFailure(
                f"drained responses {got} disagree with the solo "
                f"oracle {want} — partial or corrupted responses")
        rc2 = p2.wait(timeout=60)
        _LIVE.discard(p2)
        if rc2 != 143:
            raise DrillFailure(
                f"drained process exited {rc2}, expected 143 "
                f"(128 + SIGTERM)")
        report["drain_rc"] = rc2
        report["drain_responses"] = len(inflight)
    finally:
        if p2.poll() is None:
            p2.kill()
            p2.wait(timeout=30)
        _LIVE.discard(p2)
    return report


def run_supervisor_drill(root, *, scenario="worker-kill", world=2,
                         total_steps=6, kill_step=3, crash_rank=1,
                         max_restarts=3, restart_window=300.0,
                         quarantine_threshold=2, barrier_timeout=6.0,
                         store_deadline=20.0, gen_timeout=180.0,
                         log_dir=None):
    """Prove the self-healing supervisor end to end, on CPU, with real
    subprocesses.  Three scenarios:

    - ``worker-kill``: generation 0 carries a scripted mid-barrier
      SIGKILL of rank ``crash_rank`` at step ``kill_step``; the
      supervisor must relaunch the fleet at a fresh run id and the
      final checkpoint at ``total_steps`` must verify bit-for-bit
      against the replayed oracle — restart-then-resume loses nothing.
    - ``store-kill``: the fleet runs clean while the runner SIGKILLs
      the TCPStore MASTER mid-run; the supervisor's
      :class:`~..supervisor.StandbyStoreGuard` must promote the
      WAL-tailing standby and republish the endpoint, the workers must
      ride through with ZERO exits (no restart budget spent), and the
      promoted store must advertise generation >= 2.
    - ``crash-loop``: rank ``crash_rank`` crashes deterministically at
      ``kill_step`` every generation; the supervisor must exhaust the
      restart budget and raise
      :class:`~..supervisor.RestartBudgetExhausted` naming the rank
      and — because every failure correlates with that rank's data
      shard — the quarantined shard.

    Returns a report dict (supervision snapshot, final rcs, newest
    step, promotions/generation, exhaustion details).
    """
    from ..supervisor import (RestartBudgetExhausted, StandbyStoreGuard,
                              Supervisor)

    if scenario not in ("worker-kill", "store-kill", "crash-loop"):
        raise ValueError(f"unknown supervisor drill scenario {scenario!r}")
    ckpt_root = os.path.join(root, "ckpt")
    store_root = os.path.join(root, "store")
    os.makedirs(ckpt_root, exist_ok=True)
    os.makedirs(store_root, exist_ok=True)

    def _log(name):
        return os.path.join(log_dir, name) if log_dir else None

    guard = StandbyStoreGuard(store_root, log_dir=log_dir,
                              track=_LIVE.add)
    guard.start()
    final_rcs = {}

    def spawn(rank, w, run_id, generation):
        kill = None
        fail = None
        if scenario == "worker-kill" and generation == 0:
            kill = KillSpec("mid-barrier", kill_step, rank=crash_rank)
        if scenario == "crash-loop" and rank == crash_rank:
            fail = (kill_step, 1)
        return spawn_worker(
            rank, w, root=ckpt_root, total_steps=total_steps,
            run_id=run_id, barrier_timeout=barrier_timeout,
            endpoint_file=guard.endpoint_file,
            store_deadline=store_deadline, kill=kill, fail=fail,
            data_shard=f"shard-{rank}",
            log_path=_log(f"sup_{scenario}_g{generation}_rank{rank}.log"))

    sup = Supervisor(
        spawn, world, max_restarts=max_restarts,
        restart_window=restart_window,
        shard_of=lambda r: f"shard-{r}",
        quarantine_threshold=quarantine_threshold,
        grace=3.0 * barrier_timeout, store_guard=guard,
        generation_timeout=gen_timeout,
        run_id_prefix=f"supdrill-{uuid.uuid4().hex[:6]}")

    report = {"scenario": scenario}
    killer = None
    try:
        if scenario == "store-kill":
            # SIGKILL the master once the fleet is provably mid-run
            # (at least one step committed); the supervisor's watch
            # loop must promote while workers keep training
            import threading as _threading

            def _assassinate():
                try:
                    wait_until(
                        lambda: (_latest_step(ckpt_root) or 0) >= 1,
                        gen_timeout / 2,
                        desc="first committed step before master kill")
                    logger.info("supervisor drill: SIGKILLing store "
                                "master pid %d", guard.master.pid)
                    guard.kill_master()
                except BaseException:
                    logger.exception("store assassin failed")

            killer = _threading.Thread(target=_assassinate, daemon=True)
            killer.start()

        try:
            snap = sup.run()
            report["supervision"] = snap
            final_rcs = snap.get("final_rcs") or {}
        except RestartBudgetExhausted as e:
            report["supervision"] = sup.snapshot()
            report["exhausted"] = {"message": str(e), "rank": e.rank,
                                   "shard": e.shard, "cause": e.cause}
            if scenario != "crash-loop":
                raise DrillFailure(
                    f"{scenario}: restart budget unexpectedly "
                    f"exhausted: {e}") from e

        if killer is not None:
            killer.join(timeout=gen_timeout)

        latest = _latest_step(ckpt_root)
        report["latest"] = latest
        snap = report["supervision"]

        if scenario == "worker-kill":
            if any(rc != 0 for rc in final_rcs.values()):
                raise DrillFailure(
                    f"worker-kill: final generation rcs {final_rcs}, "
                    f"expected all 0")
            if snap["restarts_total"] < 1 or \
                    snap["restarts_by_cause"].get("killed", 0) < 1:
                raise DrillFailure(
                    f"worker-kill: supervisor booked no 'killed' "
                    f"restart: {snap['restarts_by_cause']}")
            if latest != total_steps:
                raise DrillFailure(
                    f"worker-kill: newest committed step {latest}, "
                    f"wanted {total_steps}")
            _verify_bit_for_bit(ckpt_root, latest)
        elif scenario == "store-kill":
            if any(rc != 0 for rc in final_rcs.values()):
                raise DrillFailure(
                    f"store-kill: worker exits {final_rcs}, expected "
                    f"all 0 — workers must ride through a promotion")
            if snap["restarts_total"] != 0:
                raise DrillFailure(
                    f"store-kill: {snap['restarts_total']} restarts "
                    f"booked; promotion must not cost worker restarts")
            if snap["promotions"] < 1:
                raise DrillFailure("store-kill: no promotion happened")
            probe = ResilientStore(endpoint_file=guard.endpoint_file,
                                   deadline=store_deadline)
            try:
                probe.get("store/generation", wait=False)
                gen = probe.generation
            finally:
                probe.close()
            report["generation"] = gen
            if gen is None or gen < 2:
                raise DrillFailure(
                    f"store-kill: promoted master advertises "
                    f"generation {gen}, expected >= 2")
            if latest != total_steps:
                raise DrillFailure(
                    f"store-kill: newest committed step {latest}, "
                    f"wanted {total_steps}")
            _verify_bit_for_bit(ckpt_root, latest)
        else:  # crash-loop
            ex = report.get("exhausted")
            if ex is None:
                raise DrillFailure(
                    "crash-loop: supervisor did not exhaust the "
                    "restart budget")
            if ex["rank"] != crash_rank:
                raise DrillFailure(
                    f"crash-loop: exhaustion names rank {ex['rank']}, "
                    f"expected {crash_rank}")
            if ex["shard"] != f"shard-{crash_rank}":
                raise DrillFailure(
                    f"crash-loop: exhaustion names shard "
                    f"{ex['shard']!r}, expected "
                    f"'shard-{crash_rank}' (data-correlated loop)")
            if f"rank {crash_rank}" not in ex["message"] or \
                    f"shard-{crash_rank}" not in ex["message"]:
                raise DrillFailure(
                    f"crash-loop: diagnostic does not name the rank "
                    f"and shard: {ex['message']!r}")
    finally:
        guard.stop()
        reap_all()
    return report
