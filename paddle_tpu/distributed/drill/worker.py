"""Drill worker subprocess: a deterministic mini training loop under
CheckpointManager.

Run as ``python -m paddle_tpu.distributed.drill.worker`` with the
``DRILL_*`` environment contract (set by :mod:`.runner`):

 - ``DRILL_RANK`` / ``DRILL_WORLD``: this process's rank and the fleet
   size of THIS generation (may differ from the generation that wrote
   the checkpoint being resumed — that's the elastic drill).
 - ``DRILL_STORE_PORT``: TCPStore master (hosted by the runner) on
   127.0.0.1.
 - ``DRILL_CKPT``: CheckpointManager root directory.
 - ``DRILL_TOTAL_STEPS``: run until this step is committed, then exit 0.
 - ``DRILL_RUN_ID``: per-generation id isolating commit-barrier keys —
   a relaunch must never count a dead generation's barrier arrivals.
 - ``DRILL_BARRIER_TIMEOUT``: seconds before a commit barrier gives up.
 - ``DRILL_ELASTIC``: "1" → restore accepts partial marker sets.
 - ``DRILL_ORPHAN_AGE``: run the staging janitor on startup with this
   max age (seconds); unset → no sweep.
 - ``DRILL_KILL_*``: see :mod:`.injector`.

The "model" is a (12, 4) fp32 array row-partitioned across ranks via
:class:`~paddle_tpu.distributed.checkpoint.HostLocalShard` (12 divides
evenly for worlds 1/2/3/4/6) plus a replicated ``bias`` leaf whose
overlapping windows exercise the elastic any-one-covers-it rule.  Each
step applies the same elementwise fp32 update to every element, so the
state after step N is bit-identical for ANY partitioning and the runner
replays an exact oracle (:func:`advance`).

Exit codes: 0 = reached ``DRILL_TOTAL_STEPS``; 17 = a save failed
cleanly (barrier timeout after a peer died — the survivor's correct
move is to exit and await relaunch); SIGKILL death reports -9 to the
runner.
"""
from __future__ import annotations

import logging
import os
import sys

import numpy as np

ROWS, COLS = 12, 4
EXIT_SAVE_FAILED = 17

logger = logging.getLogger("paddle_tpu.drill.worker")


def window(rank, world):
    """This rank's row window [lo, hi) of the global (ROWS, COLS) state."""
    return rank * ROWS // world, (rank + 1) * ROWS // world


def init_state():
    """Step-0 global state: (w, bias)."""
    w = (np.arange(ROWS * COLS, dtype=np.float32) + 1.0).reshape(ROWS, COLS)
    bias = np.linspace(-1.0, 1.0, COLS, dtype=np.float32)
    return w, bias


def advance(w, bias, steps=1):
    """The per-step update — elementwise fp32, therefore bit-identical
    across any row partitioning (the oracle property every drill
    assertion rests on)."""
    for _ in range(steps):
        w = w * np.float32(1.01) + np.float32(0.125)
        bias = bias * np.float32(0.99) - np.float32(0.0625)
    return w, bias


def main():
    env = os.environ
    rank = int(env["DRILL_RANK"])
    world = int(env["DRILL_WORLD"])
    total = int(env["DRILL_TOTAL_STEPS"])
    root = env["DRILL_CKPT"]
    port = int(env["DRILL_STORE_PORT"])
    run_id = env.get("DRILL_RUN_ID", "0")
    barrier_timeout = float(env.get("DRILL_BARRIER_TIMEOUT", "10"))
    elastic = env.get("DRILL_ELASTIC", "1") == "1"
    orphan_age = env.get("DRILL_ORPHAN_AGE")

    logging.basicConfig(
        level=logging.INFO, stream=sys.stderr,
        format=f"[drill rank {rank}] %(levelname)s %(message)s")

    # arm the scripted kill BEFORE any checkpoint machinery runs
    from . import injector
    armed = injector.install_from_env()
    if armed:
        logger.info("armed kill: phase=%s step=%s",
                    env.get("DRILL_KILL_PHASE"),
                    env.get("DRILL_KILL_STEP"))

    from ...core import TCPStore
    from ..checkpoint import HostLocalShard, read_leaf
    from ..checkpoint_manager import CheckpointManager

    store = None
    if world > 1:
        store = TCPStore("127.0.0.1", port, is_master=False,
                         timeout=barrier_timeout + 30.0)
    mgr = CheckpointManager(
        root, keep_last_n=None, store=store, world_size=world,
        process_index=rank, durable=True, run_id=run_id,
        barrier_timeout=barrier_timeout, elastic=elastic,
        orphan_age=float(orphan_age) if orphan_age else None)

    lo, hi = window(rank, world)
    start = mgr.latest_step()
    if start is None:
        start = 0
        w_full, bias = init_state()
        w = w_full[lo:hi]
        logger.info("fresh start")
    else:
        # numpy-only window restore: re-shards whatever world size
        # wrote the checkpoint into THIS rank's rows
        d = mgr.step_dir(start)
        w = read_leaf(d, "w", window=[[lo, hi], [0, COLS]],
                      elastic=elastic)
        bias = read_leaf(d, "bias", elastic=elastic)
        logger.info("resumed from committed step %d", start)

    for step in range(start + 1, total + 1):
        w = w * np.float32(1.01) + np.float32(0.125)
        bias = bias * np.float32(0.99) - np.float32(0.0625)
        state = {
            "w": HostLocalShard(w, window=[[lo, hi], [0, COLS]],
                                global_shape=(ROWS, COLS)),
            "bias": HostLocalShard(bias),  # replicated: full window
        }
        try:
            mgr.save(step, state)
        except BaseException as e:
            # a dead peer shows up here as a barrier/promote timeout
            # naming the missing ranks; exiting cleanly IS the correct
            # survivor behavior — the relaunch resumes from the newest
            # committed step
            logger.error("save of step %d failed: %s", step, e)
            sys.exit(EXIT_SAVE_FAILED)
        logger.info("committed step %d", step)
    sys.exit(0)


if __name__ == "__main__":
    main()
