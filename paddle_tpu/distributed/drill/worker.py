"""Drill worker subprocess: a deterministic mini training loop under
CheckpointManager.

Run as ``python -m paddle_tpu.distributed.drill.worker`` with the
``DRILL_*`` environment contract (set by :mod:`.runner`):

 - ``DRILL_RANK`` / ``DRILL_WORLD``: this process's rank and the fleet
   size of THIS generation (may differ from the generation that wrote
   the checkpoint being resumed — that's the elastic drill).
 - ``DRILL_STORE_PORT``: TCPStore master (hosted by the runner) on
   127.0.0.1.
 - ``DRILL_CKPT``: CheckpointManager root directory.
 - ``DRILL_TOTAL_STEPS``: run until this step is committed, then exit 0.
 - ``DRILL_RUN_ID``: per-generation id isolating commit-barrier keys —
   a relaunch must never count a dead generation's barrier arrivals.
 - ``DRILL_BARRIER_TIMEOUT``: seconds before a commit barrier gives up.
 - ``DRILL_ELASTIC``: "1" → restore accepts partial marker sets.
 - ``DRILL_ORPHAN_AGE``: run the staging janitor on startup with this
   max age (seconds); unset → no sweep.
 - ``DRILL_KILL_*``: see :mod:`.injector`.
 - ``DRILL_ENDPOINT_FILE``: use a
   :class:`~paddle_tpu.distributed.resilient_store.ResilientStore`
   resolved through this endpoint file instead of a fixed-port raw
   TCPStore — the store-failover drills, where the master is SIGKILLed
   and respawned on a fresh port mid-run.
 - ``DRILL_STORE_DEADLINE``: ResilientStore per-op retry budget.
 - ``DRILL_STOREKILL_STEP`` / ``DRILL_STOREKILL_PHASE``
   (``pre-save`` | ``mid-barrier``) / ``DRILL_STOREKILL_TIMEOUT``: the
   master-kill rendezvous — at the scripted point every rank announces
   ``storekill/<run_id>/ready/<rank>`` then blocks on
   ``storekill/<run_id>/go``; the runner kills the master only after
   all ranks are provably in-flight, and sets ``go`` through the
   respawned one.
 - ``DRILL_TRACE=1``: step-tracing mode (:func:`_trace_main`) — no
   store, no checkpoints.  The worker enables the real step tracer,
   records a deterministic staggered compute/collective step profile
   (synthetic timestamps, no sleeping), exports its per-rank Chrome
   trace into ``DRILL_TRACE_DIR`` (virtual step length
   ``DRILL_TRACE_STEP_MS``), dumps a final flight record when
   ``PT_FLIGHT_RECORDER`` is set, and writes a report JSON with the
   tracer snapshot (overlap fraction, phase percentiles).
 - ``PT_FLIGHT_RECORDER`` (checkpoint mode): arms the flight recorder
   — the worker records real ``backward``/``checkpoint`` phase spans
   around its update/save so a SIGKILLed victim leaves a flight dump
   behind (written at arm time, refreshed by the span watchdog).
 - ``DRILL_OBS=1``: cluster-observability mode (:func:`_obs_main`) —
   no checkpoints at all.  The worker enables real telemetry with an
   ephemeral ``/metrics`` endpoint + JSONL sink
   (``DRILL_TELEMETRY_DIR``), publishes the endpoint into the store,
   records a rank-skewed synthetic step profile
   (``DRILL_OBS_STEP_BASE`` × (1 + rank) — nonzero cross-rank skew by
   construction, no sleeping) and optionally a genuine
   recompile-sentinel trip (``DRILL_OBS_STORM=1``), then announces
   ``obs/<run_id>/ready/<rank>`` and holds the endpoint open until the
   runner sets ``obs/<run_id>/release`` (bounded by
   ``DRILL_OBS_TIMEOUT``) — the window in which the aggregator
   scrapes, a victim is SIGKILLed, masters respawn.  Obs workers also
   expose a deterministic ``pt_goodput_fraction`` (0.8 by synthetic
   span construction), ``DRILL_OBS_ANOMALIES=n`` scripted numerics
   anomalies, and ``DRILL_OBS_SDC=n`` scripted SDC consensus verdicts
   (each fingering a fixed peer, halt disarmed), feeding the
   aggregator's fleet-goodput series, anomaly-storm alarm, and
   cluster SDC alarm.
 - ``DRILL_NUMERICS=1``: NaN-injection mode (:func:`_numerics_main`) —
   storeless.  Each rank trains a real captured MLP with the numerics
   monitor armed; ``DRILL_POISON_STEP``/``DRILL_POISON_RANK`` script
   the injection, ``DRILL_NUMERICS_CADENCE`` the read cadence,
   ``DRILL_NUMERICS_HALT=1`` the halt variant (clean exit 21), and the
   per-rank report lands in ``DRILL_NUMERICS_DIR``.
 - ``DRILL_SDC=1``: silent-data-corruption mode (:func:`_sdc_main`).
   Every rank trains the SAME captured MLP from the SAME seed — dp
   replicas are bit-identical by construction — with the SDC sentry
   armed (``DRILL_SDC_CADENCE``) and its fingerprint exchange wired to
   the drill store.  At ``DRILL_POISON_STEP`` the victim
   (``DRILL_POISON_RANK``; -1 = nobody) flips ONE mantissa bit
   (``DRILL_SDC_BIT``) of its first parameter in the captured state —
   a finite, silent corruption the numerics sentinel cannot see — and
   the consensus vote must finger exactly that rank within one cadence
   window; the victim exits ``EXIT_SDC`` after writing its report to
   ``DRILL_SDC_DIR``, clean ranks book the verdict and run to
   completion.
 - ``DRILL_RESTORE_INTEGRITY`` (checkpoint mode): integrity level for
   the resume-time ``read_leaf`` (default ``size``); ``full`` also
   recomputes the per-leaf content digests, and a digest refusal —
   corruption the file CRC was sealed over — exits ``EXIT_SDC``.
 - ``DRILL_OOM=1``: OOM-postmortem mode (:func:`_oom_main`) —
   storeless.  Each rank trains a real captured MLP with the memory
   monitor armed and feeds a rank-scaled synthetic allocator watermark
   (``DRILL_OOM_MEM_BYTES`` × (1 + rank) — CPU reports no allocator
   stats, so the watermark pipeline is driven through its public
   ``observe_sample`` seam); at ``DRILL_OOM_STEP`` the victim
   (``DRILL_OOM_RANK``) swaps its compiled entry for a callable
   raising ``RESOURCE_EXHAUSTED``, the capture replay's intercept
   books the memory postmortem into the flight recorder, and the
   worker exits ``EXIT_OOM`` (23) after writing its report + a
   ``/metrics`` exposition dump into ``DRILL_OOM_DIR`` (the runner
   feeds those to a local aggregator to assert the fleet-level
   memory-skew view).

The "model" is a (12, 4) fp32 array row-partitioned across ranks via
:class:`~paddle_tpu.distributed.checkpoint.HostLocalShard` (12 divides
evenly for worlds 1/2/3/4/6) plus a replicated ``bias`` leaf whose
overlapping windows exercise the elastic any-one-covers-it rule.  Each
step applies the same elementwise fp32 update to every element, so the
state after step N is bit-identical for ANY partitioning and the runner
replays an exact oracle (:func:`advance`).

Exit codes: 0 = reached ``DRILL_TOTAL_STEPS``; 17 = a save failed
cleanly (barrier timeout after a peer died — the survivor's correct
move is to exit and await relaunch); 19 = the store master stayed
unreachable or was generation-fenced (StoreUnavailableError — the
clean degradation the failover drills assert); 21 = the numerics
sentinel halted the run (PT_NUMERICS_HALT — the clean stop the NaN
drill asserts); 25 = replica consensus fingered this rank's state as
silently corrupt, or a restore-time content digest refused a
bit-rotted checkpoint (EXIT_SDC); SIGKILL death reports -9 to the
runner.
"""
from __future__ import annotations

import json
import logging
import os
import sys
import time

import numpy as np

from ..exit_codes import (EXIT_NUMERICS_HALT, EXIT_OOM,  # noqa: F401
                          EXIT_SAVE_FAILED, EXIT_SDC, EXIT_STORE_LOST)

ROWS, COLS = 12, 4

logger = logging.getLogger("paddle_tpu.drill.worker")


def window(rank, world):
    """This rank's row window [lo, hi) of the global (ROWS, COLS) state."""
    return rank * ROWS // world, (rank + 1) * ROWS // world


def init_state():
    """Step-0 global state: (w, bias)."""
    w = (np.arange(ROWS * COLS, dtype=np.float32) + 1.0).reshape(ROWS, COLS)
    bias = np.linspace(-1.0, 1.0, COLS, dtype=np.float32)
    return w, bias


def advance(w, bias, steps=1):
    """The per-step update — elementwise fp32, therefore bit-identical
    across any row partitioning (the oracle property every drill
    assertion rests on)."""
    for _ in range(steps):
        w = w * np.float32(1.01) + np.float32(0.125)
        bias = bias * np.float32(0.99) - np.float32(0.0625)
    return w, bias


def obs_ready_key(run_id, rank):
    """Rank announces 'endpoint published, profile recorded' here."""
    return f"obs/{run_id}/ready/{rank}"


def obs_release_key(run_id):
    """Runner sets this to let the obs fleet exit 0."""
    return f"obs/{run_id}/release"


def _obs_main(env, rank, world, total, run_id):
    """Cluster-observability drill mode (``DRILL_OBS=1``); see the
    module docstring for the env contract."""
    from ...observability import get_telemetry
    from ..resilient_store import ResilientStore, StoreUnavailableError

    hold = float(env.get("DRILL_OBS_TIMEOUT", "120"))
    store = ResilientStore(
        endpoint_file=env["DRILL_ENDPOINT_FILE"],
        deadline=float(env.get("DRILL_STORE_DEADLINE", "10")))
    tel = get_telemetry().enable(
        jsonl_dir=env.get("DRILL_TELEMETRY_DIR") or None,
        http_port=0, compile_watch=False)
    try:
        tel.publish_endpoint(store, world_size=world)
        base = float(env.get("DRILL_OBS_STEP_BASE", "0.01"))
        # goodput feed: a deterministic synthetic span profile — each
        # virtual step is 1/5 data_wait, 4/5 compute — so every rank
        # exposes pt_goodput_fraction == 0.8 exactly and the aggregator's
        # pt_cluster_goodput min/mean derivation is assertable
        from ...observability.goodput import get_goodput
        from ...observability.trace import get_tracer
        tr = get_tracer().enable(process_index=rank, run_id=run_id)
        gp = get_goodput().enable()
        step_ns = 10_000_000
        origin = time.perf_counter_ns()
        for s in range(total):
            t0 = origin + s * step_ns
            tr.phase_record("data_wait", t0, t0 + step_ns // 5)
            tr.phase_record("backward", t0 + step_ns // 5, t0 + step_ns)
        gp.refresh()
        mem_bytes = int(env.get("DRILL_OBS_MEM_BYTES", "0"))
        if mem_bytes:
            # rank-scaled synthetic allocator watermark (CPU exposes
            # no allocator stats, so the public observe_sample seam
            # drives the same export pipeline): rank r publishes
            # mem_bytes * (1 + r), making the aggregator's cross-rank
            # skew exactly mem_bytes * (world - 1) and its near-OOM
            # trip point mem_bytes * world
            from ...observability.memory import get_memory_monitor
            get_memory_monitor().enable().observe_sample({
                "bytes_in_use": mem_bytes * (1 + rank),
                "peak_bytes_in_use": mem_bytes * (1 + rank),
                "bytes_reserved": mem_bytes * (1 + rank)})
        n_anoms = int(env.get("DRILL_OBS_ANOMALIES", "0"))
        if n_anoms:
            # scripted numerics anomalies: feeds the aggregator's
            # anomaly-storm alarm the same way OBS_STORM feeds the
            # recompile alarm
            from ...observability.numerics import get_monitor
            mon = get_monitor().enable()
            for _ in range(n_anoms):
                mon.record_anomaly("drill", tensor="drill::w",
                                   halt_ok=False)
        n_sdc = int(env.get("DRILL_OBS_SDC", "0"))
        if n_sdc:
            # scripted SDC consensus verdicts: books the same
            # pt_sdc_divergence_total counter the fingerprint vote
            # books, fingering a fixed PEER (never self — no halt, no
            # flight dump), so the aggregator's cluster SDC alarm is
            # assertable without a real bit flip
            from ...observability.sdc import get_monitor as sdc_monitor
            smon = sdc_monitor().enable(rank=rank, halt=False)
            for k in range(n_sdc):
                smon.record_divergence((rank + 1) % max(world, 2),
                                       tensor="drill::w", step=k,
                                       world=world)
        n_shed = int(env.get("DRILL_OBS_SHED", "0"))
        n_served = int(env.get("DRILL_OBS_SERVED", "0"))
        if n_shed or n_served:
            # scripted serve admission profile: books the same
            # counters the serve scheduler's load shedder books, so
            # the aggregator's fleet shed ratio is assertable as
            # exactly shed / (shed + served)
            from ...observability.metrics import get_registry
            reg = get_registry()
            if n_shed:
                reg.counter(
                    "pt_serve_shed_total",
                    "Requests shed at admission, by reason",
                    labelnames=("reason",)).inc(
                        n_shed, reason="deadline_infeasible")
            if n_served:
                reg.counter(
                    "pt_serve_requests_total",
                    "Requests accepted by the serve scheduler",
                ).inc(n_served)
        for _ in range(total):
            # synthetic, rank-scaled durations: rank r's mean step is
            # base*(1+r), so cluster skew is exactly base*(world-1)>0
            # without any real sleeping
            tel.observe_step(base * (1.0 + rank), mode="train",
                             batch_size=8)
        if env.get("DRILL_OBS_STORM") == "1":
            # a genuine sentinel trip: threshold compiles of ONE
            # callable with threshold distinct signatures
            for k in range(tel.sentinel.threshold):
                tel.record_compile("drill_step_fn",
                                   f"(f32[{k + 2},8])")
        store.set(obs_ready_key(run_id, rank), b"1")
        logger.info("obs worker ready; holding endpoint open")
        store.get(obs_release_key(run_id), wait=True, timeout=hold)
    except (StoreUnavailableError, TimeoutError) as e:
        logger.error("obs drill: store lost while holding: %s", e)
        sys.exit(EXIT_STORE_LOST)
    finally:
        store.close()
    logger.info("obs worker released")
    sys.exit(0)


def trace_report_path(trace_dir, rank):
    """Per-rank trace-drill report (tracer snapshot JSON)."""
    return os.path.join(trace_dir, f"trace_report-{rank}.json")


def _trace_main(env, rank, world, total, run_id):
    """Step-tracing drill mode (``DRILL_TRACE=1``): storeless.

    Timestamps are synthetic offsets from one ``perf_counter`` origin —
    no sleeping — with a fixed stagger per virtual step: ``data_wait``
    covers [0, 0.1), the fused fwd+bwd ``backward`` span [0.1, 0.7),
    the ``collective`` [0.4, 0.9) and the ``optimizer`` [0.9, 1.0) of
    the step, so the compute∩collective overlap is exactly 0.3/0.5 =
    0.6 of collective time on every rank — the runner asserts the
    measured fraction is strictly positive.
    """
    from ...observability.trace import get_tracer

    trace_dir = env["DRILL_TRACE_DIR"]
    tr = get_tracer().enable(
        trace_dir=trace_dir,
        flight_dir=env.get("PT_FLIGHT_RECORDER") or None,
        process_index=rank, run_id=run_id)
    step_ns = int(float(env.get("DRILL_TRACE_STEP_MS", "10")) * 1e6)
    base = time.perf_counter_ns()
    for s in range(total):
        t0 = base + s * step_ns
        tr.phase_record("data_wait", t0, t0 + step_ns // 10)
        c0 = t0 + step_ns // 10
        tr.phase_record("backward", c0, c0 + (step_ns * 6) // 10)
        tr.phase_record("collective", c0 + (step_ns * 3) // 10,
                        c0 + (step_ns * 8) // 10)
        tr.phase_record("optimizer", c0 + (step_ns * 8) // 10,
                        t0 + step_ns)
        tr.on_step(step_ns / 1e9)
    out = tr.export_chrome()
    if out is None:
        logger.error("trace drill: chrome export failed")
        sys.exit(1)
    tr.flight_dump(reason="drill-exit")
    snap = tr.snapshot()
    report = trace_report_path(trace_dir, rank)
    tmp = f"{report}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(snap, f)
    os.replace(tmp, report)
    logger.info("trace drill: exported %s (overlap=%s)", out,
                snap["overlap_fraction"])
    sys.exit(0)


def numerics_report_path(out_dir, rank):
    """Per-rank numerics-drill report (detection evidence JSON)."""
    return os.path.join(out_dir, f"numerics_report-{rank}.json")


def _numerics_main(env, rank, world, total, run_id):
    """NaN-injection drill mode (``DRILL_NUMERICS=1``): storeless.

    Each rank trains a real captured MLP on CPU with the numerics
    monitor armed (cadence ``DRILL_NUMERICS_CADENCE``). At step
    ``DRILL_POISON_STEP`` the poison rank (``DRILL_POISON_RANK``)
    overwrites one input element with NaN — same shape and dtype, so
    the capture cache must NOT retrace — which poisons that step's
    loss, grads, and (through the momentum update) every parameter
    after it. The report records when the sentinel fired, what it
    named, and the flight-dump path; with ``DRILL_NUMERICS_HALT=1``
    the raise is caught and the worker exits ``EXIT_NUMERICS_HALT``
    cleanly after writing its report.
    """
    out_dir = env["DRILL_NUMERICS_DIR"]
    poison_step = int(env.get("DRILL_POISON_STEP", "-1"))
    poison_rank = int(env.get("DRILL_POISON_RANK", "0"))
    cadence = int(env.get("DRILL_NUMERICS_CADENCE", "4"))
    halt = env.get("DRILL_NUMERICS_HALT") == "1"

    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    from ...observability.numerics import get_monitor, NumericsHaltError
    from ...observability.trace import get_tracer

    mon = get_monitor().enable(cadence=cadence, halt=halt)
    tr = get_tracer()  # enabled iff the runner set PT_FLIGHT_RECORDER

    np.random.seed(rank)
    pt.seed(rank)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    opt = pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                parameters=model.parameters())
    mse = nn.MSELoss()

    @pt.jit.capture_step
    def step(x, y):
        loss = mse(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    x = np.random.randn(4, 8).astype(np.float32)
    y = pt.to_tensor(np.random.randn(4, 1).astype(np.float32))
    detected_step = None
    halted = False
    for s in range(1, total + 1):
        xb = x.copy()
        if rank == poison_rank and s == poison_step:
            xb[0, 0] = np.nan
            logger.info("poisoning input at step %d", s)
        try:
            step(pt.to_tensor(xb), y)
        except NumericsHaltError as e:
            logger.info("sentinel halt at step %d: %s", s, e)
            halted = True
            detected_step = s
            break
        if detected_step is None and mon.anomaly_count("nonfinite"):
            detected_step = s
    if detected_step is None:
        mon.flush()  # end-of-run read covers runs shorter than cadence
        if mon.anomaly_count("nonfinite"):
            detected_step = total
    snap = mon.snapshot()
    report = {
        "rank": rank,
        "world": world,
        "steps": total,
        "poison_step": poison_step if rank == poison_rank else None,
        "cadence": cadence,
        "halt": halt,
        "halted": halted,
        "detected_step": detected_step,
        "anomalies": snap["anomalies"],
        "tripped": snap["tripped"],
        "last_anomaly": snap["last_anomaly"],
        "reads": snap["reads"],
        "compiles": step.stats["compiles"],
        "fallback": step.stats["fallback"],
        "flight": tr.flight_path if tr.enabled else None,
    }
    path = numerics_report_path(out_dir, rank)
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(report, f)
    os.replace(tmp, path)
    logger.info("numerics drill: detected_step=%s anomalies=%s",
                detected_step, snap["anomalies"])
    sys.exit(EXIT_NUMERICS_HALT if halted else 0)


def flip_bit(array, bit=0, index=0):
    """Return a copy of ``array`` with exactly one bit flipped.

    ``index`` addresses a flat element, ``bit`` a bit inside that
    element's raw bytes (0 = LSB of its first byte, so ``bit`` ranges
    over ``itemsize * 8``).  Deterministic by construction — the same
    (bit, index) always flips the same physical bit — which is what
    the SDC drill needs to prove one-cadence-window detection latency.
    The input is never mutated; dtype, shape and every other bit are
    preserved exactly.  Canonical here (the drill worker is the one
    consumer that cannot import tests/); re-exported by
    tests/fault_injection.py for the digest and consensus unit tests.
    """
    a = np.ascontiguousarray(array)
    index = int(index) % max(a.size, 1)
    nbits = a.itemsize * 8
    bit = int(bit) % nbits
    raw = bytearray(a.tobytes())
    byte_off = index * a.itemsize + bit // 8
    raw[byte_off] ^= 1 << (bit % 8)
    return np.frombuffer(bytes(raw), dtype=a.dtype).reshape(a.shape)


def sdc_report_path(out_dir, rank):
    """Per-rank SDC-drill report (consensus evidence JSON)."""
    return os.path.join(out_dir, f"sdc_report-{rank}.json")


def _sdc_main(env, rank, world, total, run_id):
    """Silent-data-corruption drill mode (``DRILL_SDC=1``).

    Unlike the numerics drill — which seeds every rank DIFFERENTLY to
    prove per-rank isolation — this mode seeds every rank the SAME, so
    the fleet is a genuine set of dp replicas: bit-identical params,
    optimizer slots and inputs on every rank, every step.  The only
    divergence the drill can possibly produce is the one it injects:
    at ``DRILL_POISON_STEP`` the victim flips one low mantissa bit of
    its first parameter inside the captured state (:func:`flip_bit` on
    the live leaf — same shape and dtype, so the capture cache must
    NOT retrace), a corruption that is finite everywhere and invisible
    to the numerics sentinel.  The SDC fingerprints disagree from that
    step's packet on; the consensus vote (exchanged through the drill
    store) must finger exactly the victim within one cadence window,
    name the divergent tensor, pin a flight dump, and halt the victim
    into a clean ``EXIT_SDC`` — the exit the supervisor charges to
    hardware.  Clean ranks book the verdict, drop the exchange (the
    dead peer is the supervisor's department) and run to completion.
    """
    out_dir = env["DRILL_SDC_DIR"]
    poison_step = int(env.get("DRILL_POISON_STEP", "-1"))
    poison_rank = int(env.get("DRILL_POISON_RANK", "-1"))
    cadence = int(env.get("DRILL_SDC_CADENCE", "4"))
    bit = int(env.get("DRILL_SDC_BIT", "3"))
    exch_timeout = float(env.get("DRILL_SDC_EXCHANGE_TIMEOUT", "30"))

    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    from ...observability.sdc import (SdcHaltError, get_monitor,
                                      store_exchange)
    from ...observability.trace import get_tracer
    from ..resilient_store import ResilientStore

    endpoint_file = env.get("DRILL_ENDPOINT_FILE")
    if endpoint_file:
        store = ResilientStore(
            endpoint_file=endpoint_file,
            deadline=float(env.get("DRILL_STORE_DEADLINE",
                                   str(exch_timeout))))
    else:
        from ...core import TCPStore
        store = TCPStore("127.0.0.1",
                         int(env.get("DRILL_STORE_PORT", "0")),
                         is_master=False, timeout=exch_timeout + 30.0)

    mon = get_monitor().enable(
        cadence=cadence, halt=True, rank=rank,
        exchange=store_exchange(store, run_id, rank, world,
                                timeout=exch_timeout))
    tr = get_tracer()  # enabled iff the runner set PT_FLIGHT_RECORDER

    # IDENTICAL seeds everywhere: the replica-consensus precondition
    np.random.seed(0)
    pt.seed(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    opt = pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                parameters=model.parameters())
    mse = nn.MSELoss()

    @pt.jit.capture_step
    def step(x, y):
        loss = mse(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    x = pt.to_tensor(np.random.randn(4, 8).astype(np.float32))
    y = pt.to_tensor(np.random.randn(4, 1).astype(np.float32))
    detected_step = None
    poisoned_tensor = None
    halted = False
    for s in range(1, total + 1):
        if rank == poison_rank and s == poison_step \
                and step._state is not None:
            # flip one bit of the first captured parameter leaf — the
            # SDC model: corruption lands in device state, not in code
            st = step._state
            name = sorted(st.params)[0]
            st.params[name] = flip_bit(np.asarray(st.params[name]),
                                       bit=bit, index=0)
            poisoned_tensor = f"param::{name}"
            logger.info("flipped bit %d of %s before step %d",
                        bit, name, s)
        try:
            step(x, y)
        except SdcHaltError as e:
            logger.info("sdc halt at step %d: %s", s, e)
            halted = True
            detected_step = s
            break
        if detected_step is None and mon.divergence_count():
            # a clean rank's vote fingered the victim; stop exchanging
            # — the fingered rank is halting and will publish no more
            detected_step = s
            mon.exchange = None
    if detected_step is None:
        try:
            mon.flush()  # end-of-run vote covers runs under one cadence
        except SdcHaltError as e:
            logger.info("sdc halt at flush: %s", e)
            halted = True
            detected_step = total
        if detected_step is None and mon.divergence_count():
            detected_step = total
    try:
        store.close()
    except Exception as e:
        # the exchange may already have torn the connection down after
        # a halt — worth a breadcrumb, never worth failing the report
        logger.debug("sdc drill: store close after run: %s", e)
    snap = mon.snapshot()
    report = {
        "rank": rank,
        "world": world,
        "steps": total,
        "poison_step": poison_step if rank == poison_rank else None,
        "poison_bit": bit if rank == poison_rank else None,
        "poisoned_tensor": poisoned_tensor,
        "cadence": cadence,
        "halted": halted,
        "detected_step": detected_step,
        "divergences": snap["divergences"],
        "divergences_total": snap["divergences_total"],
        "last_divergence": snap["last_divergence"],
        "reads": snap["reads"],
        "votes": snap["votes"],
        "compiles": step.stats["compiles"],
        "fallback": step.stats["fallback"],
        "flight": tr.flight_path if tr.enabled else None,
    }
    path = sdc_report_path(out_dir, rank)
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(report, f)
    os.replace(tmp, path)
    logger.info("sdc drill: detected_step=%s divergences=%s",
                detected_step, snap["divergences"])
    sys.exit(EXIT_SDC if halted else 0)


def oom_report_path(out_dir, rank):
    """Per-rank OOM-drill report (postmortem evidence JSON)."""
    return os.path.join(out_dir, f"oom_report-{rank}.json")


def oom_metrics_path(out_dir, rank):
    """Per-rank /metrics exposition dump (the runner replays these
    through a local aggregator to assert the fleet memory-skew view)."""
    return os.path.join(out_dir, f"oom_metrics-{rank}.prom")


def _oom_main(env, rank, world, total, run_id):
    """OOM-postmortem drill mode (``DRILL_OOM=1``): storeless.

    Each rank trains a real captured MLP on CPU with the memory
    monitor armed.  The model's first weight (64×256 fp32, 64 KiB)
    dominates every other live buffer, so the census top entry is a
    parameter path by construction.  At ``DRILL_OOM_STEP`` the victim
    rank swaps its compiled cache entry for a callable that raises a
    ``RESOURCE_EXHAUSTED`` — exactly what a real allocator failure
    looks like to the replay — and the capture intercept must book a
    flight dump whose reason pins ``oom:<program>:<param path>``.
    Synthetic rank-scaled watermarks (CPU has no allocator stats) feed
    the exported ``pt_memory_watermark_bytes`` gauge each virtual
    step, giving the runner's aggregator a nonzero cross-rank skew.
    """
    out_dir = env["DRILL_OOM_DIR"]
    oom_step = int(env.get("DRILL_OOM_STEP", "-1"))
    oom_rank = int(env.get("DRILL_OOM_RANK", "0"))
    mem_bytes = int(env.get("DRILL_OOM_MEM_BYTES", "1000000"))

    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    from ...observability import memory as _memory
    from ...observability.metrics import get_registry
    from ...observability.trace import get_tracer

    mm = _memory.get_memory_monitor().enable()
    tr = get_tracer()  # enabled iff the runner set PT_FLIGHT_RECORDER

    np.random.seed(rank)
    pt.seed(rank)
    # SGD (stateless) keeps optimizer slots out of the census so the
    # 64 KiB first weight is the unambiguous top buffer
    model = nn.Sequential(nn.Linear(64, 256), nn.ReLU(),
                          nn.Linear(256, 1))
    opt = pt.optimizer.SGD(learning_rate=0.01,
                           parameters=model.parameters())
    mse = nn.MSELoss()

    @pt.jit.capture_step
    def step(x, y):
        loss = mse(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    x = pt.to_tensor(np.random.randn(8, 64).astype(np.float32))
    y = pt.to_tensor(np.random.randn(8, 1).astype(np.float32))
    caught = None
    for s in range(1, total + 1):
        if rank == oom_rank and s == oom_step and step._cache:
            entry = next(iter(step._cache.values()))

            def _exhausted(*a, **k):
                raise RuntimeError(
                    "RESOURCE_EXHAUSTED: Out of memory while trying "
                    "to allocate 1073741824 bytes.")

            entry.jitted = _exhausted
            logger.info("armed RESOURCE_EXHAUSTED at step %d", s)
        try:
            step(x, y)
        except RuntimeError as e:
            if not _memory.is_oom_error(e):
                raise
            caught = f"{type(e).__name__}: {e}"
            logger.info("allocator exhaustion surfaced at step %d", s)
            break
        # rank-scaled synthetic watermark: skew across the fleet is
        # mem_bytes * (world - 1) > 0 by construction
        mm.observe_sample({
            "bytes_in_use": mem_bytes * (1 + rank),
            "peak_bytes_in_use": mem_bytes * (1 + rank),
            "bytes_reserved": mem_bytes * (1 + rank) + mem_bytes // 8,
        })

    with open(oom_metrics_path(out_dir, rank) + f".tmp{os.getpid()}",
              "w") as f:
        f.write(get_registry().prometheus_text())
    os.replace(oom_metrics_path(out_dir, rank) + f".tmp{os.getpid()}",
               oom_metrics_path(out_dir, rank))

    snap = mm.snapshot()
    report = {
        "rank": rank,
        "world": world,
        "steps": total,
        "oom_step": oom_step if rank == oom_rank else None,
        "mem_bytes": mem_bytes,
        "caught": caught,
        "oom_events": snap["oom_events"],
        "last_oom": snap["last_oom"],
        "watermark_samples": snap["samples"],
        "programs": sorted(snap["programs"]),
        "compiles": step.stats["compiles"],
        "fallback": step.stats["fallback"],
        "flight": tr.flight_path if tr.enabled else None,
    }
    path = oom_report_path(out_dir, rank)
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(report, f)
    os.replace(tmp, path)
    logger.info("oom drill: caught=%s oom_events=%d", bool(caught),
                snap["oom_events"])
    sys.exit(EXIT_OOM if caught else 0)


def _arm_storekill(store, rank, run_id, step, phase, timeout):
    """Wire the master-kill rendezvous: returns ``(phase, rendezvous)``.

    ``rendezvous()`` announces this rank at
    ``storekill/<run_id>/ready/<rank>`` and blocks on
    ``storekill/<run_id>/go`` — the window in which the runner SIGKILLs
    the master, so the blocking ``get`` rides the ResilientStore
    reconnect path against the respawned (or absent, or amnesiac)
    master.  ``mid-barrier`` patches the ``_barrier_arrive`` seam so
    the rendezvous fires AFTER the real arrival (the arrival must land
    in the WAL for the respawned master to seal the barrier);
    ``pre-save`` fires from the worker loop before the save starts.
    Runs at most once — a retried arrival must not re-rendezvous.
    """
    from .. import checkpoint as _ckpt

    ready_key = f"storekill/{run_id}/ready/{rank}"
    go_key = f"storekill/{run_id}/go"
    fired = []

    def rendezvous():
        if fired:
            return
        fired.append(True)
        logger.info("storekill rendezvous: ready at %s, awaiting %s "
                    "(master kill window)", ready_key, go_key)
        store.set(ready_key, b"1")
        store.get(go_key, wait=True, timeout=timeout)
        logger.info("storekill rendezvous released (master "
                    "generation %s)", getattr(store, "generation", None))

    if phase == "mid-barrier":
        needle = f"step_{int(step):08d}"
        real_arrive = _ckpt._barrier_arrive

        def _arrive(store_, key, rank_=None):
            n = real_arrive(store_, key, rank_)
            if needle in key:
                rendezvous()
            return n

        _ckpt._barrier_arrive = _arrive
    return phase, rendezvous


def main():
    env = os.environ
    rank = int(env["DRILL_RANK"])
    world = int(env["DRILL_WORLD"])
    total = int(env["DRILL_TOTAL_STEPS"])
    root = env["DRILL_CKPT"]
    port = int(env.get("DRILL_STORE_PORT", "0"))
    run_id = env.get("DRILL_RUN_ID", "0")
    barrier_timeout = float(env.get("DRILL_BARRIER_TIMEOUT", "10"))
    elastic = env.get("DRILL_ELASTIC", "1") == "1"
    orphan_age = env.get("DRILL_ORPHAN_AGE")

    logging.basicConfig(
        level=logging.INFO, stream=sys.stderr,
        format=f"[drill rank {rank}] %(levelname)s %(message)s")

    if env.get("DRILL_TRACE") == "1":
        _trace_main(env, rank, world, total, run_id)
        return  # unreachable (_trace_main exits), defensive only
    if env.get("DRILL_OBS") == "1":
        _obs_main(env, rank, world, total, run_id)
        return  # unreachable (_obs_main exits), defensive only
    if env.get("DRILL_NUMERICS") == "1":
        _numerics_main(env, rank, world, total, run_id)
        return  # unreachable (_numerics_main exits), defensive only
    if env.get("DRILL_OOM") == "1":
        _oom_main(env, rank, world, total, run_id)
        return  # unreachable (_oom_main exits), defensive only
    if env.get("DRILL_SDC") == "1":
        _sdc_main(env, rank, world, total, run_id)
        return  # unreachable (_sdc_main exits), defensive only

    # arm the scripted kill BEFORE any checkpoint machinery runs
    from . import injector
    armed = injector.install_from_env()
    if armed:
        logger.info("armed kill: phase=%s step=%s",
                    env.get("DRILL_KILL_PHASE"),
                    env.get("DRILL_KILL_STEP"))

    # flight recorder: arm BEFORE the loop so the arm-time dump exists
    # no matter when the scripted SIGKILL lands (get_tracer() reads
    # PT_TRACE / PT_FLIGHT_RECORDER from the env the runner set)
    tracer = None
    if env.get("PT_FLIGHT_RECORDER") or env.get("PT_TRACE"):
        from ...observability.trace import get_tracer
        t = get_tracer()
        if t.enabled:
            tracer = t

    from ...core import TCPStore
    from ..checkpoint import (CheckpointCorruptError, HostLocalShard,
                              read_leaf)
    from ..checkpoint_manager import CheckpointManager
    from ..resilient_store import ResilientStore, StoreUnavailableError

    endpoint_file = env.get("DRILL_ENDPOINT_FILE")
    store = None
    if endpoint_file:
        store = ResilientStore(
            endpoint_file=endpoint_file,
            deadline=float(env.get("DRILL_STORE_DEADLINE",
                                   str(barrier_timeout))))
    elif world > 1:
        store = TCPStore("127.0.0.1", port, is_master=False,
                         timeout=barrier_timeout + 30.0)

    sk_phase = None
    sk_step = None
    storekill_rendezvous = None
    if env.get("DRILL_STOREKILL_STEP") is not None and store is not None:
        sk_step = int(env["DRILL_STOREKILL_STEP"])
        sk_phase, storekill_rendezvous = _arm_storekill(
            store, rank, run_id, sk_step,
            env.get("DRILL_STOREKILL_PHASE", "mid-barrier"),
            float(env.get("DRILL_STOREKILL_TIMEOUT", "60")))
        logger.info("armed storekill rendezvous: phase=%s step=%d",
                    sk_phase, sk_step)
    mgr = CheckpointManager(
        root, keep_last_n=None, store=store, world_size=world,
        process_index=rank, durable=True, run_id=run_id,
        barrier_timeout=barrier_timeout, elastic=elastic,
        orphan_age=float(orphan_age) if orphan_age else None)

    # scripted crash loop (supervisor drills): die with DRILL_FAIL_EXIT
    # the moment step DRILL_FAIL_STEP would run — every relaunch resumes
    # below the fail step and dies again, the deterministic crash loop a
    # restart budget must cut short.  PT_DATA_SHARD names the data shard
    # this rank was assigned, so the supervisor can correlate the loop
    # with one poisoned shard.
    fail_step = int(env.get("DRILL_FAIL_STEP", "-1"))
    fail_exit = int(env.get("DRILL_FAIL_EXIT", "1"))
    data_shard = env.get("PT_DATA_SHARD")

    lo, hi = window(rank, world)
    start = mgr.latest_step()
    if start is None:
        start = 0
        w_full, bias = init_state()
        w = w_full[lo:hi]
        logger.info("fresh start")
    else:
        # numpy-only window restore: re-shards whatever world size
        # wrote the checkpoint into THIS rank's rows
        d = mgr.step_dir(start)
        integrity = env.get("DRILL_RESTORE_INTEGRITY") or "size"
        try:
            w = read_leaf(d, "w", window=[[lo, hi], [0, COLS]],
                          elastic=elastic, integrity=integrity)
            bias = read_leaf(d, "bias", elastic=elastic,
                             integrity=integrity)
        except CheckpointCorruptError as e:
            # a content digest caught bit-rot the file CRC was sealed
            # over — refusing to resume from corrupt state IS the SDC
            # sentry's restore-side half
            logger.error("restore of step %d refused: %s", start, e)
            sys.exit(EXIT_SDC)
        logger.info("resumed from committed step %d", start)

    for step in range(start + 1, total + 1):
        if step == fail_step:
            logger.error("scripted crash at step %d (data shard %s)",
                         step, data_shard)
            sys.exit(fail_exit)
        t0 = time.perf_counter_ns()
        w = w * np.float32(1.01) + np.float32(0.125)
        bias = bias * np.float32(0.99) - np.float32(0.0625)
        if tracer is not None:
            tracer.phase_record("backward", t0, time.perf_counter_ns())
        state = {
            "w": HostLocalShard(w, window=[[lo, hi], [0, COLS]],
                                global_shape=(ROWS, COLS)),
            "bias": HostLocalShard(bias),  # replicated: full window
        }
        try:
            if sk_phase == "pre-save" and step == sk_step:
                storekill_rendezvous()
            if tracer is not None:
                with tracer.phase("checkpoint"):
                    mgr.save(step, state)
            else:
                mgr.save(step, state)
        except StoreUnavailableError as e:
            # the master stayed dead past the client deadline, or a
            # respawn was generation-fenced as amnesiac — clean
            # degradation, distinct from a peer-death save failure
            logger.error("store lost during save of step %d: %s",
                         step, e)
            sys.exit(EXIT_STORE_LOST)
        except BaseException as e:
            # a dead peer shows up here as a barrier/promote timeout
            # naming the missing ranks; exiting cleanly IS the correct
            # survivor behavior — the relaunch resumes from the newest
            # committed step
            logger.error("save of step %d failed: %s", step, e)
            sys.exit(EXIT_SAVE_FAILED)
        logger.info("committed step %d", step)
    sys.exit(0)


if __name__ == "__main__":
    main()
