"""``paddle_tpu.distributed`` (ref: ``python/paddle/distributed/``).

TPU-native distributed stack: a global ``jax.sharding.Mesh`` + GSPMD +
``shard_map`` collectives replace the reference's entire
ProcessGroup/NCCL/TCPStore machinery (SURVEY §2.3, §5). The public surface
mirrors ``paddle.distributed`` so reference training scripts port over.
"""
from .env import get_rank, get_world_size, ParallelEnv  # noqa: F401
from .mesh import (  # noqa: F401
    build_mesh, init_mesh, get_mesh, set_mesh, mesh_axis_size, HYBRID_AXES,
)
from .collective import (  # noqa: F401
    ReduceOp, Group, new_group, get_group, destroy_process_group,
    is_initialized, all_reduce, all_gather, gather, all_gather_object,
    broadcast,
    broadcast_object_list, reduce, scatter, scatter_object_list, alltoall,
    alltoall_single, all_to_all, reduce_scatter, send, recv, isend, irecv,
    barrier, P2POp, batch_isend_irecv, wait, get_backend,
)
from .parallel import init_parallel_env, DataParallel  # noqa: F401
from .topology import (  # noqa: F401
    CommunicateTopology, HybridCommunicateGroup, ParallelMode,
)
from . import fleet  # noqa: F401
from . import sharding  # noqa: F401
from . import communication  # noqa: F401
from .communication import stream  # noqa: F401
from .fleet.meta_parallel.mp_ops import split  # noqa: F401
from .auto_parallel_api import (  # noqa: F401
    ProcessMesh, shard_tensor, shard_layer, dtensor_from_fn, reshard,
    Shard, Replicate, Partial,
)
from . import auto_parallel  # noqa: F401
from .auto_parallel import Engine, to_static  # noqa: F401
from . import io  # noqa: F401
from . import passes  # noqa: F401
from .entry_attr import (  # noqa: F401
    CountFilterEntry, ProbabilityEntry, ShowClickEntry,
)
from .parallel_with_gloo import (  # noqa: F401
    gloo_barrier, gloo_init_parallel_env, gloo_release,
)
from .fleet.dataset import InMemoryDataset, QueueDataset  # noqa: F401
from . import rpc  # noqa: F401
from . import ps  # noqa: F401
from . import utils  # noqa: F401
from . import checkpoint  # noqa: F401
from .checkpoint import (  # noqa: F401
    save_sharded, load_sharded, save_state, load_state,
    CheckpointCorruptError, is_committed, verify_checkpoint, store_barrier,
    ReshardError, HostLocalShard, sweep_staging, read_leaf,
)
from .checkpoint_manager import (  # noqa: F401
    CheckpointManager, latest_checkpoint,
)
from .resilient_store import (  # noqa: F401
    ResilientStore, StoreUnavailableError, read_endpoint_file,
    write_endpoint_file,
)

# spawn-style launch (ref: python/paddle/distributed/spawn.py)
from .launch_api import spawn, launch  # noqa: F401


def is_available():
    """Whether the distributed package is usable (ref:
    ``python/paddle/distributed/collective.py:306``). Always true on
    this build: collectives ride XLA — no separate comm library to be
    compiled out."""
    return True
