"""``paddle_tpu.distributed`` (ref: ``python/paddle/distributed/``).

Grown incrementally: env/rank info first; mesh, collectives, fleet, and
hybrid parallelism land in their own modules.
"""
from .env import get_rank, get_world_size, ParallelEnv  # noqa: F401
