"""Process/rank environment (ref: ``python/paddle/distributed/parallel.py
ParallelEnv:646`` and the launcher env contract).

Under the TPU runtime, ranks come from ``jax.process_index()`` once
``jax.distributed`` is initialized; before that, from the launcher's env
vars (PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM — same names as the
reference so launch tooling carries over).
"""
from __future__ import annotations

import os

__all__ = ["get_rank", "get_world_size", "ParallelEnv"]


def _jax_initialized():
    import jax
    try:
        return jax.process_count() > 1
    except Exception:
        return False


def get_rank(group=None):
    if group is not None:
        return group.get_group_rank(get_rank())
    env = os.environ.get("PADDLE_TRAINER_ID")
    if env is not None:
        return int(env)
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    env = os.environ.get("PADDLE_TRAINERS_NUM")
    if env is not None:
        return int(env)
    try:
        import jax
        return jax.process_count()
    except Exception:
        return 1


class ParallelEnv:
    """ref: parallel.py:646 ParallelEnv."""

    def __init__(self):
        self._rank = get_rank()
        self._world_size = get_world_size()

    @property
    def rank(self):
        return self._rank

    @property
    def local_rank(self):
        return int(os.environ.get("PADDLE_LOCAL_RANK", self._rank))

    @property
    def world_size(self):
        return self._world_size

    @property
    def nranks(self):
        return self._world_size

    @property
    def dev_id(self):
        return self.local_rank

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:6170")

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else [self.current_endpoint]
