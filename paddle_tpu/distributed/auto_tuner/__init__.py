"""``paddle.distributed.auto_tuner`` — parallel-config search.

TPU-native re-design of the reference auto-tuner
(``python/paddle/distributed/auto_tuner/{tuner,search,prune,recorder}.py``):
grid/prune search over dp/mp(tp)/pp/sharding/micro-batch/recompute
candidates, a prune-rule registry, and a recorder of trial metrics. On TPU
the candidate axes map to mesh-shape choices (``dp × mp × pp × sharding``
must tile the chip count; GSPMD takes the chosen shape via
``paddle_tpu.distributed.mesh``), so the same tuner drives mesh-shape
search instead of launcher re-invocations.
"""
from .tuner import AutoTuner  # noqa: F401
from .search import GridSearch, SearchAlgo  # noqa: F401
from .prune import register_prune, prune_by_rules, PRUNE_RULES  # noqa: F401
from .recorder import HistoryRecorder  # noqa: F401

__all__ = ["AutoTuner", "GridSearch", "SearchAlgo", "register_prune",
           "prune_by_rules", "PRUNE_RULES", "HistoryRecorder"]
