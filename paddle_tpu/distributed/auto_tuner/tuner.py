"""AutoTuner driver (ref: ``auto_tuner/tuner.py:19`` AutoTuner)."""
from __future__ import annotations

from .recorder import HistoryRecorder
from .search import GridSearch

__all__ = ["AutoTuner"]


class AutoTuner:
    """Usage (same loop as the reference's launcher integration)::

        tuner = AutoTuner({"candidates": {...}, "num_chips": 8,
                           "global_batch_size": 64})
        while (cfg := tuner.search_once()) is not None:
            metric, status = run_trial(cfg)       # user-provided
            tuner.add_cfg(**cfg, throughput=metric, status=status)
        best, _ = tuner.get_best()

    Cost-model guidance (ref ``auto_parallel/static/cost/`` estimator +
    ``static/cluster.py``): pass ``model``
    ({n_params, num_layers, hidden_size, seq_len}) and optionally
    ``cluster`` (a :class:`Cluster` or its dict; auto-detected
    otherwise) in the tuner config. Candidates predicted to OOM are
    dropped before any trial runs, and the remaining grid is visited
    best-predicted-first, so the measured search converges in far fewer
    trials. Each returned cfg carries ``predicted_step_time`` /
    ``predicted_memory_bytes`` so the recorder's history shows
    predicted-vs-measured side by side.
    """

    def __init__(self, tuner_cfg):
        self.tuner_cfg = dict(tuner_cfg)
        algo = self.tuner_cfg.get("search_algo", "grid")
        if algo == "grid":
            self.algo = GridSearch(self.tuner_cfg)
        else:
            raise ValueError(f"unknown search_algo '{algo}'")
        self.recorder = HistoryRecorder(
            metric=self.tuner_cfg.get("metric", "throughput"),
            maximize=self.tuner_cfg.get("maximize", True))
        self.cur_task_id = 0
        self.cluster = None
        self.pruned_by_cost = 0
        model = self.tuner_cfg.get("model")
        if model is not None:
            self._apply_cost_model(model)

    def _apply_cost_model(self, model):
        from ...cost_model.parallel_cost import predict
        from ..auto_parallel.cluster import Cluster
        cluster = self.tuner_cfg.get("cluster")
        if cluster is None:
            cluster = Cluster.auto_detect()
        if isinstance(cluster, dict):
            cluster = Cluster(**cluster)
        self.cluster = cluster
        gbs = self.tuner_cfg.get("global_batch_size")
        # static prune rules first (invalid tilings etc.): costing them
        # would inflate pruned_by_cost with configs that could never
        # have been trialed anyway
        viable = [c for c in self.algo.all_cfgs
                  if not self.algo.prune(c, [])]
        ranked = []
        for cfg in viable:
            t, m, fits = predict(model, cfg, cluster,
                                 global_batch_size=gbs)
            if not fits:
                continue
            cfg = dict(cfg)
            cfg["predicted_step_time"] = round(t, 6)
            cfg["predicted_memory_bytes"] = int(m)
            ranked.append(cfg)
        ranked.sort(key=lambda c: c["predicted_step_time"])
        self.pruned_by_cost = len(viable) - len(ranked)
        if viable and not ranked:
            raise ValueError(
                f"cost model predicts every one of the {len(viable)} "
                f"viable configs exceeds {cluster.hbm_bytes / 2**30:.1f} "
                f"GiB HBM on {cluster.device_kind!r} — the model is too "
                f"big for this cluster/candidate grid, the search would "
                f"be empty")
        self.algo.all_cfgs = ranked
        self.algo.idx = 0

    def search_once(self):
        cfg = self.algo.search_once(self.recorder.history)
        if cfg is not None:
            self.cur_task_id += 1
        return cfg

    def add_cfg(self, **cfg):
        self.recorder.add_cfg(**cfg)

    def get_best(self):
        return self.recorder.get_best()

    def search_space_size(self):
        return len(self.algo.all_cfgs)
