"""AutoTuner driver (ref: ``auto_tuner/tuner.py:19`` AutoTuner)."""
from __future__ import annotations

from .recorder import HistoryRecorder
from .search import GridSearch

__all__ = ["AutoTuner"]


class AutoTuner:
    """Usage (same loop as the reference's launcher integration)::

        tuner = AutoTuner({"candidates": {...}, "num_chips": 8,
                           "global_batch_size": 64})
        while (cfg := tuner.search_once()) is not None:
            metric, status = run_trial(cfg)       # user-provided
            tuner.add_cfg(**cfg, throughput=metric, status=status)
        best, _ = tuner.get_best()
    """

    def __init__(self, tuner_cfg):
        self.tuner_cfg = dict(tuner_cfg)
        algo = self.tuner_cfg.get("search_algo", "grid")
        if algo == "grid":
            self.algo = GridSearch(self.tuner_cfg)
        else:
            raise ValueError(f"unknown search_algo '{algo}'")
        self.recorder = HistoryRecorder(
            metric=self.tuner_cfg.get("metric", "throughput"),
            maximize=self.tuner_cfg.get("maximize", True))
        self.cur_task_id = 0

    def search_once(self):
        cfg = self.algo.search_once(self.recorder.history)
        if cfg is not None:
            self.cur_task_id += 1
        return cfg

    def add_cfg(self, **cfg):
        self.recorder.add_cfg(**cfg)

    def get_best(self):
        return self.recorder.get_best()

    def search_space_size(self):
        return len(self.algo.all_cfgs)
