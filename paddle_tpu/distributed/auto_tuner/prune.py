"""Prune rules (ref: ``auto_tuner/prune.py`` _PRUNE_FUNC registry): each
rule gets (tuner_cfg, cur_cfg, history_cfgs) and returns True to prune."""
from __future__ import annotations

__all__ = ["register_prune", "prune_by_rules", "PRUNE_RULES"]

PRUNE_RULES = []


def register_prune(fn):
    PRUNE_RULES.append(fn)
    return fn


def prune_by_rules(tuner_cfg, cur_cfg, history_cfgs=None):
    history_cfgs = history_cfgs or []
    return any(rule(tuner_cfg, cur_cfg, history_cfgs)
               for rule in PRUNE_RULES)


@register_prune
def prune_by_num_chips(tuner_cfg, cur_cfg, history):
    """dp*mp*pp*sharding must exactly tile the chip count (mesh shape)."""
    n = tuner_cfg.get("num_gpus") or tuner_cfg.get("num_chips")
    if n is None:
        return False
    degree = 1
    for k in ("dp_degree", "mp_degree", "pp_degree", "sharding_degree"):
        v = cur_cfg.get(k)
        if v:
            degree *= v
    return degree != n

@register_prune
def prune_by_mp_bound(tuner_cfg, cur_cfg, history):
    """mp beyond one host's chips rides DCN, not ICI — prune unless
    explicitly allowed (ref prune_by_mp_degree)."""
    mp = cur_cfg.get("mp_degree")
    bound = tuner_cfg.get("max_mp_degree")
    return bound is not None and mp is not None and mp > bound


@register_prune
def prune_by_micro_batch(tuner_cfg, cur_cfg, history):
    """global batch must divide into dp*sharding*micro_batch."""
    gbs = tuner_cfg.get("global_batch_size")
    mbs = cur_cfg.get("micro_batch_size")
    if gbs is None or mbs is None:
        return False
    dp = (cur_cfg.get("dp_degree") or 1) * (cur_cfg.get("sharding_degree")
                                            or 1)
    if gbs % dp != 0:
        return True
    per = gbs // dp
    return per % mbs != 0


@register_prune
def prune_by_sharding_stage(tuner_cfg, cur_cfg, history):
    """stage>0 needs sharding_degree>1."""
    stage = cur_cfg.get("sharding_stage")
    deg = cur_cfg.get("sharding_degree") or 1
    return bool(stage) and stage > 0 and deg <= 1


@register_prune
def prune_by_recompute(tuner_cfg, cur_cfg, history):
    """granularity only meaningful when recompute is on."""
    use = cur_cfg.get("use_recompute")
    gran = cur_cfg.get("recompute_granularity")
    return use is False and gran not in (None, "none")


@register_prune
def prune_by_history_oom(tuner_cfg, cur_cfg, history):
    """a strictly-more-memory-hungry config than an OOM'd one is pruned
    (ref prune_by_mbs/memory heuristics)."""
    for h in history:
        if h.get("status") != "oom":
            continue
        cur_r = bool(cur_cfg.get("use_recompute", False))
        h_r = bool(h.get("use_recompute", False))
        # cur uses at least as much memory per chip as the OOM'd config:
        # bigger (or equal) micro-batch, no more splitting on ANY
        # memory-reducing axis (mp, pp, sharding), and no recompute
        # advantage over it
        if (cur_cfg.get("micro_batch_size") or 0) >= \
                (h.get("micro_batch_size") or 0) and \
                (cur_cfg.get("mp_degree") or 1) <= (h.get("mp_degree") or 1) \
                and (cur_cfg.get("pp_degree") or 1) <= \
                (h.get("pp_degree") or 1) \
                and (cur_cfg.get("sharding_degree") or 1) <= \
                (h.get("sharding_degree") or 1) \
                and ((not cur_r) or h_r):
            return True
    return False
