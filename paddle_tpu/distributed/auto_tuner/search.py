"""Search algorithms (ref: ``auto_tuner/search.py`` GridSearch +
``utils.py search_all``)."""
from __future__ import annotations

import itertools
from abc import ABC, abstractmethod

from .prune import prune_by_rules

__all__ = ["SearchAlgo", "GridSearch"]

# candidate axes in the reference's fixed order (utils.py:136)
AXES = ["dp_degree", "mp_degree", "pp_degree", "micro_batch_size",
        "sharding_degree", "sharding_stage", "use_recompute",
        "recompute_granularity"]


def search_all(tuner_cfg):
    """Cartesian product of all candidate axes (ref ``search_all``)."""
    candidates = tuner_cfg.get("candidates", {})
    pools = [candidates.get(a, [None]) for a in AXES]
    return [dict(zip(AXES, combo))
            for combo in itertools.product(*pools)]


class SearchAlgo(ABC):
    def __init__(self, tuner_cfg):
        self.tuner_cfg = tuner_cfg

    @abstractmethod
    def search_once(self, history_cfgs):
        ...

    def prune(self, cur_cfg, history_cfgs):
        return prune_by_rules(self.tuner_cfg, cur_cfg, history_cfgs)


class GridSearch(SearchAlgo):
    def __init__(self, tuner_cfg):
        super().__init__(tuner_cfg)
        self.all_cfgs = search_all(tuner_cfg)
        self.idx = 0

    def search_once(self, history_cfgs):
        while self.idx < len(self.all_cfgs):
            cfg = self.all_cfgs[self.idx]
            self.idx += 1
            if not self.prune(cfg, history_cfgs):
                return dict(cfg)
        return None  # search space exhausted
