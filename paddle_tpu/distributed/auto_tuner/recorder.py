"""Trial recorder (ref: ``auto_tuner/recorder.py`` History_recorder)."""
from __future__ import annotations

import csv
import json
import os

__all__ = ["HistoryRecorder"]


class HistoryRecorder:
    def __init__(self, metric="throughput", maximize=True):
        self.history = []
        self.metric = metric
        self.maximize = maximize

    def add_cfg(self, **cfg):
        self.history.append(dict(cfg))

    def sort_metric(self):
        def key(c):
            v = c.get(self.metric)
            if not isinstance(v, (int, float)):  # None / '' after CSV load
                return float("-inf") if self.maximize else float("inf")
            return v
        self.history.sort(key=key, reverse=self.maximize)

    def get_best(self):
        self.sort_metric()
        ok = [c for c in self.history
              if c.get("status", "ok") == "ok" and
              isinstance(c.get(self.metric), (int, float))]
        if not ok:
            return None, True
        return ok[0], False

    def store_history(self, path="./history.csv"):
        if not self.history:
            return
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        keys = sorted({k for c in self.history for k in c})
        with open(path, "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=keys)
            w.writeheader()
            for c in self.history:
                w.writerow(c)

    def load_history(self, path="./history.csv"):
        if not os.path.exists(path):
            return [], True
        with open(path) as f:
            rows = list(csv.DictReader(f))
        for r in rows:
            for k, v in list(r.items()):
                if v == "":  # CSV writes None as empty string
                    r[k] = None
                    continue
                try:
                    r[k] = json.loads(v)
                except ValueError:
                    pass  # not JSON: the raw CSV string is the value
        self.history = rows
        return rows, False
