"""Canonical process exit-code taxonomy for the self-healing job runtime.

A supervisor restarting workers can only act on what an exit status
tells it, so the codes are the contract between every process this
framework spawns (serving engines, preempted trainers, drill workers)
and the thing that relaunches them.  They were historically scattered
as magic numbers across ``serving/http.py`` (143), ``serving/
scheduler.py`` (70), ``fleet/elastic/preemption.py`` (75/143) and the
drill workers (17/19/21/23); this module is the one place they are
defined, and :func:`classify` is the supervisor's decision table.

Stdlib-only on purpose: the drill's path-loaded store master and the
supervisor must be importable without jax.

 ==================  =====  ==============================================
 name                code   meaning
 ==================  =====  ==============================================
 EXIT_OK                0   ran to completion
 EXIT_SAVE_FAILED      17   a checkpoint save failed cleanly (commit
                            barrier timed out after a peer died); the
                            survivor exited awaiting relaunch
 EXIT_STORE_LOST       19   the coordination store stayed unreachable
                            past the client deadline, or a respawned
                            master was generation-fenced as amnesiac
 EXIT_NUMERICS_HALT    21   the numerics sentinel halted the run
                            (PT_NUMERICS_HALT)
 EXIT_OOM              23   allocator exhaustion surfaced and the memory
                            postmortem was booked
 EXIT_SDC              25   cross-replica consensus fingered this rank's
                            state as silently corrupt (bit-level replica
                            divergence, no non-finite trip)
 EXIT_WATCHDOG         70   the serve hang watchdog force-exited a wedged
                            process (BSD EX_SOFTWARE)
 EXIT_TEMPFAIL         75   a preemption save FAILED; the relaunch falls
                            back to an older checkpoint (BSD EX_TEMPFAIL)
 EXIT_DRAIN           143   128+SIGTERM: asked to stop, stopped cleanly
                            (graceful drain / preemption save succeeded)
 ==================  =====  ==============================================

A negative status from ``Popen.poll()`` is death by signal
(``-9`` = SIGKILL): the process had no chance to report anything.
"""
from __future__ import annotations

__all__ = [
    "EXIT_OK", "EXIT_SAVE_FAILED", "EXIT_STORE_LOST",
    "EXIT_NUMERICS_HALT", "EXIT_OOM", "EXIT_SDC", "EXIT_WATCHDOG",
    "EXIT_TEMPFAIL", "EXIT_DRAIN", "classify", "describe",
    "RESTARTABLE_CAUSES",
]

EXIT_OK = 0
EXIT_SAVE_FAILED = 17
EXIT_STORE_LOST = 19
EXIT_NUMERICS_HALT = 21
EXIT_OOM = 23
EXIT_SDC = 25
EXIT_WATCHDOG = 70
EXIT_TEMPFAIL = 75
EXIT_DRAIN = 143

_CAUSES = {
    EXIT_OK: "ok",
    EXIT_SAVE_FAILED: "save_failed",
    EXIT_STORE_LOST: "store_lost",
    EXIT_NUMERICS_HALT: "numerics_halt",
    EXIT_OOM: "oom",
    EXIT_SDC: "sdc",
    EXIT_WATCHDOG: "watchdog",
    EXIT_TEMPFAIL: "tempfail",
    EXIT_DRAIN: "drain",
}

_DESCRIPTIONS = {
    "ok": "ran to completion",
    "save_failed": "checkpoint save failed cleanly (peer died at the "
                   "commit barrier); relaunch resumes from the newest "
                   "committed step",
    "store_lost": "coordination store unreachable past the client "
                  "deadline or generation-fenced as amnesiac",
    "numerics_halt": "numerics sentinel halted the run",
    "oom": "allocator exhaustion (memory postmortem booked)",
    "sdc": "cross-replica consensus fingered this rank's state as "
           "silently corrupt (bit-level divergence from the replica "
           "majority); suspect hardware, not code",
    "watchdog": "hang watchdog force-exited a wedged process",
    "tempfail": "preemption save failed (EX_TEMPFAIL); relaunch falls "
                "back to an older checkpoint",
    "drain": "asked to stop via SIGTERM, stopped cleanly",
    "killed": "killed by signal (no chance to report)",
    "crash": "unclassified non-zero exit",
}

#: causes a supervisor should relaunch (vs. fail the job on): every
#: taxonomy member is a *clean* degradation whose designed recovery is a
#: relaunch — including a raw signal kill, which is exactly what a
#: preemption without notice looks like.
RESTARTABLE_CAUSES = frozenset({
    "save_failed", "store_lost", "watchdog", "tempfail", "drain",
    "killed", "oom", "sdc",
})


def classify(returncode):
    """Map a ``Popen`` return code to its restart-ledger cause label."""
    if returncode is None:
        return "running"
    rc = int(returncode)
    if rc < 0:
        return "killed"
    return _CAUSES.get(rc, "crash")


def describe(returncode):
    """Human-readable one-liner for a return code (diagnostics/logs)."""
    cause = classify(returncode)
    base = _DESCRIPTIONS.get(cause, cause)
    if cause == "killed":
        return f"{base} (signal {-int(returncode)})"
    return f"{base} (exit {returncode})" if cause == "crash" else base
