"""Hybrid-parallel topology.

Re-design of ``python/paddle/distributed/fleet/base/topology.py``
(``CommunicateTopology :58``, ``HybridCommunicateGroup :144``): the
reference computes per-axis rank groups and creates one NCCL communicator
per group; here the same N-D rank arithmetic instead yields (a) Group
bookkeeping objects for the eager API and (b) THE global
``jax.sharding.Mesh`` whose axis names drive GSPMD sharding — no
communicators exist.

Axis order matches the reference: ``["dp", "pp", "sharding", "mp"]``
(plus ``sep``, our sequence-parallel extension).
"""
from __future__ import annotations

import itertools

import numpy as np

from . import mesh as _mesh_mod
from .collective import Group, new_group
from .env import get_rank

__all__ = ["CommunicateTopology", "HybridCommunicateGroup", "ParallelMode"]


class ParallelMode:
    """Parallel-mode enum (ref:
    ``python/paddle/distributed/fleet/base/topology.py:33``)."""

    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


class CommunicateTopology:
    """Pure rank arithmetic over the hybrid axes (ref: topology.py:58)."""

    def __init__(self, hybrid_group_names=("data", "pipe", "sharding", "sep",
                                           "model"),
                 dims=(1, 1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self._world_size = int(np.prod(self._dims))
        ranges = [range(d) for d in self._dims]
        all_coords = list(itertools.product(*ranges))
        self._coord2rank = {c: i for i, c in enumerate(all_coords)}
        self._rank2coord = {i: c for c, i in self._coord2rank.items()}

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world_size

    def get_rank(self, **args):
        coord = tuple(args[name] for name in self._parallel_names)
        return self._coord2rank[coord]

    def get_coord(self, rank):
        return self._rank2coord[rank]

    def get_axis_list(self, axis_name, index):
        """All ranks whose coordinate on `axis_name` equals `index`."""
        axis = self._parallel_names.index(axis_name)
        return sorted(r for c, r in self._coord2rank.items()
                      if c[axis] == index)

    def get_comm_list(self, axis_name):
        """Rank groups that communicate along `axis_name`: one list per
        combination of the other axes (ref: topology.py get_comm_list)."""
        axis = self._parallel_names.index(axis_name)
        other_ranges = [range(d) for i, d in enumerate(self._dims)
                        if i != axis]
        out = []
        for other in itertools.product(*other_ranges):
            group = []
            for v in range(self._dims[axis]):
                coord = list(other)
                coord.insert(axis, v)
                group.append(self._coord2rank[tuple(coord)])
            out.append(group)
        return out

    def get_rank_from_stage(self, global_rank, **kwargs):
        coord = list(self.get_coord(global_rank))
        for k, v in kwargs.items():
            coord[self._parallel_names.index(k)] = v
        return self._coord2rank[tuple(coord)]


# map reference group names → mesh axis names
_NAME2AXIS = {"data": "dp", "pipe": "pp", "sharding": "sharding",
              "sep": "sep", "model": "mp"}


class HybridCommunicateGroup:
    """ref: ``topology.py:144``. Exposes the same per-axis world-size /
    rank / group queries; additionally owns the global Mesh."""

    def __init__(self, topology: CommunicateTopology):
        self._topo = topology
        self.global_rank = get_rank()
        self._dp_degree = topology.get_dim("data")
        self._pp_degree = topology.get_dim("pipe")
        self._sharding_degree = topology.get_dim("sharding")
        self._sep_degree = topology.get_dim("sep") \
            if "sep" in topology.get_hybrid_group_names() else 1
        self._mp_degree = topology.get_dim("model")
        self.nranks = topology.world_size()

        # build the global mesh with matching axis sizes
        degrees = {"dp": self._dp_degree, "pp": self._pp_degree,
                   "sharding": self._sharding_degree,
                   "sep": self._sep_degree, "mp": self._mp_degree}
        import jax
        if self.nranks <= jax.device_count():
            self.mesh = _mesh_mod.init_mesh(degrees)
        else:  # more ranks than local devices (multi-host): mesh is global
            self.mesh = None

        rank = self.global_rank
        coord = topology.get_coord(rank % self.nranks)
        names = topology.get_hybrid_group_names()
        self._coord = dict(zip(names, coord))

        self._groups = {}
        for name in names:
            axis = _NAME2AXIS[name]
            for ranks in topology.get_comm_list(name):
                if rank % self.nranks in ranks:
                    self._groups[name] = new_group(ranks, axis_name=axis)
                    break

    # -- per-axis queries (reference API surface) -------------------------
    def get_parallel_mode(self):
        if self._pp_degree > 1:
            return "pipeline"
        if self._sharding_degree > 1:
            return "sharding_parallel"
        if self._mp_degree > 1:
            return "model"
        return "data"

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return self.global_rank

    # data parallel
    def get_data_parallel_rank(self):
        return self._coord["data"]

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_group(self) -> Group:
        return self._groups["data"]

    def get_data_parallel_group_src_rank(self):
        return self._groups["data"].ranks[0]

    # model (tensor) parallel
    def get_model_parallel_rank(self):
        return self._coord["model"]

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_group(self) -> Group:
        return self._groups["model"]

    def get_model_parallel_group_src_rank(self):
        return self._groups["model"].ranks[0]

    # pipeline parallel
    def get_stage_id(self):
        return self._coord["pipe"]

    def get_pipe_parallel_rank(self):
        return self._coord["pipe"]

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_pipe_parallel_group(self) -> Group:
        return self._groups["pipe"]

    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self._pp_degree - 1

    def get_p2p_groups(self):
        return None

    # sharding
    def get_sharding_parallel_rank(self):
        return self._coord["sharding"]

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_group(self) -> Group:
        return self._groups["sharding"]

    def get_sharding_parallel_group_src_rank(self):
        return self._groups["sharding"].ranks[0]

    # sep (sequence/context parallel — TPU-build extension)
    def get_sep_parallel_rank(self):
        return self._coord.get("sep", 0)

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_sep_parallel_group(self) -> Group:
        return self._groups.get("sep")

    # checks
    def get_check_parallel_group(self):
        return self._groups["model"]

    def get_rank_from_stage(self, stage_id, **kwargs):
        return self._topo.get_rank_from_stage(self.global_rank,
                                              pipe=stage_id, **kwargs)
