"""Multi-process / multi-node launcher — ``python -m
paddle_tpu.distributed.launch``.

TPU-native redesign of the reference launcher (``python/paddle/
distributed/launch/main.py:18`` + ``controllers/collective.py``): same
CLI contract and env injection (PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM
/ PADDLE_TRAINER_ENDPOINTS), but the process model is one controller
process per *host* (jax single-controller-per-host SPMD) instead of one
per GPU.  ``--nproc_per_node > 1`` is still supported for CPU-mesh
simulation tests: each local process gets a distinct rank and a virtual
device count via XLA_FLAGS, which is how the reference's
``test_parallel_dygraph_dataparallel.py TestMultipleGpus`` harness maps
to TPU-less CI.

Rendezvous: `--master host:port` selects jax.distributed's builtin
coordination service (the TCPStore equivalent,
``paddle/phi/core/distributed/store/tcp_store.h:120``); with no master,
a free local port is chosen and rank 0 hosts the coordinator.
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time

__all__ = ["main", "launch"]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="paddle_tpu distributed launcher")
    p.add_argument("--master", default=None,
                   help="rendezvous endpoint host:port (rank 0 hosts it "
                        "when unset)")
    p.add_argument("--rank", type=int, default=-1,
                   help="node rank; -1 = auto (single node → 0)")
    p.add_argument("--nnodes", default="1",
                   help="number of nodes (elastic ranges 'lo:hi' collapse "
                        "to lo)")
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--log_dir", default="log")
    p.add_argument("--log_level", default="INFO")
    p.add_argument("--job_id", default="default")
    p.add_argument("--devices", default=None,
                   help="virtual device count per proc for CPU simulation")
    p.add_argument("--run_mode", default="collective")
    p.add_argument("--max_restart", type=int, default=0)
    p.add_argument("--elastic", action="store_true",
                   help="supervise workers with the self-healing "
                        "supervisor: per-rank restart budgets "
                        "(PT_SUPERVISOR_MAX_RESTARTS over "
                        "PT_SUPERVISOR_RESTART_WINDOW), backoff "
                        "relaunch at a fresh run id per generation, "
                        "elastic downsize when a rank is dead past "
                        "its lease")
    p.add_argument("--with_store", action="store_true",
                   help="(elastic) run a WAL-durable TCPStore master "
                        "plus a hot standby that is auto-promoted if "
                        "the master dies; workers get "
                        "PT_STORE_ENDPOINT_FILE")
    p.add_argument("--min_world", type=int, default=1,
                   help="(elastic) smallest world size a lease-expiry "
                        "downsize may reach")
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _build_env(args, local_rank, nnodes):
    nproc = args.nproc_per_node
    world = nnodes * nproc
    node_rank = max(args.rank, 0)
    rank = node_rank * nproc + local_rank
    env = dict(os.environ)
    env.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_LOCAL_RANK": str(local_rank),
        "PADDLE_LOCAL_SIZE": str(nproc),
        "PADDLE_NNODES": str(nnodes),
        "PADDLE_JOB_ID": args.job_id,
        "MASTER_ADDR": args.master.split(":")[0] if args.master else
        "127.0.0.1",
        "MASTER_PORT": args.master.split(":")[1] if args.master else
        str(_free_port()),
    })
    endpoints = ",".join(
        f"{env['MASTER_ADDR']}:{int(env['MASTER_PORT']) + i}"
        for i in range(world))
    env["PADDLE_TRAINER_ENDPOINTS"] = endpoints
    env["PADDLE_CURRENT_ENDPOINT"] = endpoints.split(",")[rank]
    if args.devices:
        # CPU-mesh simulation: N virtual devices per process
        env["JAX_PLATFORMS"] = "cpu"
        flags = env.get("XLA_FLAGS", "")
        env["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{args.devices}").strip()
    return env


def _run_once(args, nnodes):
    os.makedirs(args.log_dir, exist_ok=True)
    procs, logs = [], []
    cmd = [sys.executable, "-u", args.training_script,
           *args.training_script_args]
    for lr in range(args.nproc_per_node):
        env = _build_env(args, lr, nnodes)
        rank = env["PADDLE_TRAINER_ID"]
        log_path = os.path.join(
            args.log_dir, f"workerlog.{rank}")
        logf = open(log_path, "w")
        logs.append(logf)
        procs.append(subprocess.Popen(cmd, env=env, stdout=logf,
                                      stderr=subprocess.STDOUT))

    def _kill_all(*_):
        for pr in procs:
            if pr.poll() is None:
                pr.terminate()

    old = signal.signal(signal.SIGTERM, _kill_all)
    try:
        fail = 0
        while True:
            codes = [pr.poll() for pr in procs]
            if any(c not in (None, 0) for c in codes):
                _kill_all()
                fail = next(c for c in codes if c not in (None, 0))
                break
            if all(c == 0 for c in codes):
                break
            # child-process poll, not store contention: fixed cadence is
            # fine here  # tpu-lint: disable=TPU009
            time.sleep(0.2)
    finally:
        signal.signal(signal.SIGTERM, old)
        for f in logs:
            f.close()
    return fail


def _run_supervised(args, nnodes):
    """``--elastic``: run the fleet under the self-healing supervisor
    (restart budgets, fresh run id per generation, standby-store
    promotion with ``--with_store``, lease-based downsize)."""
    from ..supervisor import (RestartBudgetExhausted, SpawnFailed,
                              StandbyStoreGuard, Supervisor)

    os.makedirs(args.log_dir, exist_ok=True)
    cmd = [sys.executable, "-u", args.training_script,
           *args.training_script_args]
    live = []

    def spawn(rank, world, run_id, generation):
        env = _build_env(args, rank % args.nproc_per_node, nnodes)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PT_RUN_ID": run_id,
            "PT_RESTART_GENERATION": str(generation),
            "PADDLE_ELASTIC": "1",
        })
        if guard is not None:
            env["PT_STORE_ENDPOINT_FILE"] = guard.endpoint_file
        log_path = os.path.join(args.log_dir,
                                f"workerlog.{rank}.g{generation}")
        try:
            logf = open(log_path, "w")
            proc = subprocess.Popen(cmd, env=env, stdout=logf,
                                    stderr=subprocess.STDOUT)
        except OSError as e:
            raise SpawnFailed(f"rank {rank}: {e}") from e
        logf.close()  # child holds its own fd
        live.append(proc)
        return proc

    guard = None
    if args.with_store:
        guard = StandbyStoreGuard(args.log_dir, log_dir=args.log_dir)
        guard.start()

    def _kill_all(*_):
        for pr in live:
            if pr.poll() is None:
                pr.terminate()

    sup = Supervisor(
        spawn, nnodes * args.nproc_per_node,
        max_restarts=args.max_restart if args.max_restart > 0 else None,
        min_world=args.min_world, store_guard=guard,
        run_id_prefix=args.job_id)
    old = signal.signal(signal.SIGTERM, _kill_all)
    try:
        report = sup.run()
    except RestartBudgetExhausted as e:
        where = "store master" if e.rank is None else f"rank {e.rank}"
        print(f"launch: giving up ({where}"
              + (f", quarantined shard {e.shard!r}" if e.shard else "")
              + f"): {e}", file=sys.stderr)
        return 1
    finally:
        signal.signal(signal.SIGTERM, old)
        _kill_all()
        if guard is not None:
            guard.stop()
    print(f"launch: done — supervision: {report}", file=sys.stderr)
    return 0


def main(argv=None):
    args = parse_args(argv)
    nnodes = int(str(args.nnodes).split(":")[0])
    if args.elastic:
        return _run_supervised(args, nnodes)
    restarts = 0
    while True:
        code = _run_once(args, nnodes)
        if code == 0:
            return 0
        restarts += 1
        if restarts > args.max_restart:
            tail = ""
            try:
                logs = sorted(os.listdir(args.log_dir))
                if logs:
                    with open(os.path.join(args.log_dir, logs[0])) as f:
                        tail = "".join(f.readlines()[-20:])
            except OSError:
                pass
            print(f"launch: worker exited with code {code}\n{tail}",
                  file=sys.stderr)
            return code
        print(f"launch: restarting ({restarts}/{args.max_restart})",
              file=sys.stderr)


def launch():
    sys.exit(main())
