"""Multi-process / multi-node launcher — ``python -m
paddle_tpu.distributed.launch``.

TPU-native redesign of the reference launcher (``python/paddle/
distributed/launch/main.py:18`` + ``controllers/collective.py``): same
CLI contract and env injection (PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM
/ PADDLE_TRAINER_ENDPOINTS), but the process model is one controller
process per *host* (jax single-controller-per-host SPMD) instead of one
per GPU.  ``--nproc_per_node > 1`` is still supported for CPU-mesh
simulation tests: each local process gets a distinct rank and a virtual
device count via XLA_FLAGS, which is how the reference's
``test_parallel_dygraph_dataparallel.py TestMultipleGpus`` harness maps
to TPU-less CI.

Rendezvous: `--master host:port` selects jax.distributed's builtin
coordination service (the TCPStore equivalent,
``paddle/phi/core/distributed/store/tcp_store.h:120``); with no master,
a free local port is chosen and rank 0 hosts the coordinator.
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time

__all__ = ["main", "launch"]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="paddle_tpu distributed launcher")
    p.add_argument("--master", default=None,
                   help="rendezvous endpoint host:port (rank 0 hosts it "
                        "when unset)")
    p.add_argument("--rank", type=int, default=-1,
                   help="node rank; -1 = auto (single node → 0)")
    p.add_argument("--nnodes", default="1",
                   help="number of nodes (elastic ranges 'lo:hi' collapse "
                        "to lo)")
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--log_dir", default="log")
    p.add_argument("--log_level", default="INFO")
    p.add_argument("--job_id", default="default")
    p.add_argument("--devices", default=None,
                   help="virtual device count per proc for CPU simulation")
    p.add_argument("--run_mode", default="collective")
    p.add_argument("--max_restart", type=int, default=0)
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _build_env(args, local_rank, nnodes):
    nproc = args.nproc_per_node
    world = nnodes * nproc
    node_rank = max(args.rank, 0)
    rank = node_rank * nproc + local_rank
    env = dict(os.environ)
    env.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_LOCAL_RANK": str(local_rank),
        "PADDLE_LOCAL_SIZE": str(nproc),
        "PADDLE_NNODES": str(nnodes),
        "PADDLE_JOB_ID": args.job_id,
        "MASTER_ADDR": args.master.split(":")[0] if args.master else
        "127.0.0.1",
        "MASTER_PORT": args.master.split(":")[1] if args.master else
        str(_free_port()),
    })
    endpoints = ",".join(
        f"{env['MASTER_ADDR']}:{int(env['MASTER_PORT']) + i}"
        for i in range(world))
    env["PADDLE_TRAINER_ENDPOINTS"] = endpoints
    env["PADDLE_CURRENT_ENDPOINT"] = endpoints.split(",")[rank]
    if args.devices:
        # CPU-mesh simulation: N virtual devices per process
        env["JAX_PLATFORMS"] = "cpu"
        flags = env.get("XLA_FLAGS", "")
        env["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{args.devices}").strip()
    return env


def _run_once(args, nnodes):
    os.makedirs(args.log_dir, exist_ok=True)
    procs, logs = [], []
    cmd = [sys.executable, "-u", args.training_script,
           *args.training_script_args]
    for lr in range(args.nproc_per_node):
        env = _build_env(args, lr, nnodes)
        rank = env["PADDLE_TRAINER_ID"]
        log_path = os.path.join(
            args.log_dir, f"workerlog.{rank}")
        logf = open(log_path, "w")
        logs.append(logf)
        procs.append(subprocess.Popen(cmd, env=env, stdout=logf,
                                      stderr=subprocess.STDOUT))

    def _kill_all(*_):
        for pr in procs:
            if pr.poll() is None:
                pr.terminate()

    old = signal.signal(signal.SIGTERM, _kill_all)
    try:
        fail = 0
        while True:
            codes = [pr.poll() for pr in procs]
            if any(c not in (None, 0) for c in codes):
                _kill_all()
                fail = next(c for c in codes if c not in (None, 0))
                break
            if all(c == 0 for c in codes):
                break
            # child-process poll, not store contention: fixed cadence is
            # fine here  # tpu-lint: disable=TPU009
            time.sleep(0.2)
    finally:
        signal.signal(signal.SIGTERM, old)
        for f in logs:
            f.close()
    return fail


def main(argv=None):
    args = parse_args(argv)
    nnodes = int(str(args.nnodes).split(":")[0])
    restarts = 0
    while True:
        code = _run_once(args, nnodes)
        if code == 0:
            return 0
        restarts += 1
        if restarts > args.max_restart:
            tail = ""
            try:
                logs = sorted(os.listdir(args.log_dir))
                if logs:
                    with open(os.path.join(args.log_dir, logs[0])) as f:
                        tail = "".join(f.readlines()[-20:])
            except OSError:
                pass
            print(f"launch: worker exited with code {code}\n{tail}",
                  file=sys.stderr)
            return code
        print(f"launch: restarting ({restarts}/{args.max_restart})",
              file=sys.stderr)


def launch():
    sys.exit(main())
