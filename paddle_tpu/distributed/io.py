"""``paddle.distributed.io`` (ref:
``python/paddle/distributed/io.py``): persistable-variable save/load
for distributed training jobs.

The reference splits persistables into local vs remote (PS-hosted)
pieces and pulls the remote ones over RPC before writing. Here ALL
program state lives in the executor scope (XLA arrays; PS tables are
host-side ShardedEmbedding state), so persistables round-trip through
the static save/load path in one place.
"""
from __future__ import annotations

import os

__all__ = ["save_persistables", "load_persistables", "is_persistable"]


def is_persistable(var):
    """ref ``io.py:355``: does this variable survive across steps
    (parameters / optimizer state), as opposed to per-batch temps."""
    return bool(getattr(var, "persistable", False))


def _resolve(main_program, dirname, filename):
    from ..static.graph import default_main_program
    prog = main_program if main_program is not None \
        else default_main_program()
    return prog, os.path.join(dirname, filename or "persistables")


def save_persistables(executor, dirname, main_program=None, filename=None):
    """Write every persistable of ``main_program`` under ``dirname``
    (ref ``io.py:386``)."""
    from ..static import io as static_io
    prog, path = _resolve(main_program, dirname, filename)
    os.makedirs(dirname, exist_ok=True)
    static_io.save(prog, path)
    return path


def load_persistables(executor, dirname, main_program=None, filename=None):
    """Restore what :func:`save_persistables` wrote (ref
    ``io.py:131``)."""
    from ..static import io as static_io
    prog, path = _resolve(main_program, dirname, filename)
    static_io.load(prog, path, executor)
    return path
