"""Built-in program-rewrite passes.

The train-step toggles (amp / recompute) exposed as inspectable,
composable passes over the static Program (ref:
``distributed/passes/auto_parallel_amp.py``,
``auto_parallel_recompute.py``). Sharding/ZeRO and pipeline scheduling
remain :func:`build_train_step` options — they shard STATE across a
mesh, which is an execution-placement concern, not a graph rewrite, in
the XLA model.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .pass_base import PassBase, PassType, register_pass

# ops worth running in low precision: the MXU-bound compute (matches the
# O1 white list in amp/auto_cast.py)
_AMP_WHITELIST = frozenset({
    "matmul", "mm", "bmm", "einsum", "conv2d", "conv3d",
    "conv2d_transpose", "flash_attention", "scaled_dot_product_attention",
    "linear", "addmm",
})


def _is_float(a):
    return hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)


@register_pass("auto_parallel_amp")
class AMPPass(PassBase):
    """Cast whitelisted compute nodes' float inputs to the AMP dtype
    (ref ``auto_parallel_amp.py``: cast-insertion around whitelist ops).
    attrs: ``dtype`` ("bfloat16" default), ``custom_white_list``."""

    def _check_self(self):
        return self.get_attr("dtype", "bfloat16") in ("bfloat16", "float16")

    def _check_conflict(self, other_pass):
        # applying amp twice is a no-op wrapped in a no-op; forbid it
        return other_pass.name != self.name

    def _type(self):
        return PassType.CALC_OPT

    def _apply_single_impl(self, main_program, startup_program, context):
        dtype = jnp.bfloat16 if self.get_attr(
            "dtype", "bfloat16") == "bfloat16" else jnp.float16
        white = _AMP_WHITELIST | frozenset(
            self.get_attr("custom_white_list", ()))
        n_rewritten = 0
        for node in main_program.nodes:
            if node.name not in white:
                continue
            inner = node.fn

            def amp_fn(*args, _inner=inner):
                cast = tuple(a.astype(dtype) if _is_float(a) else a
                             for a in args)
                return _inner(*cast)

            node.fn = amp_fn
            n_rewritten += 1
        context.set_attr("amp_nodes_rewritten",
                         context.get_attr("amp_nodes_rewritten", 0)
                         + n_rewritten)


@register_pass("auto_parallel_recompute")
class RecomputePass(PassBase):
    """Wrap compute nodes in ``jax.checkpoint`` so their activations are
    rematerialised in backward instead of stored (ref
    ``auto_parallel_recompute.py``: the segment-replay rewrite; XLA's
    remat is the TPU-native equivalent). attrs: ``segments`` — node
    names to wrap (default: every node with >= ``min_inputs`` tensor
    inputs, i.e. real compute, not metadata ops)."""

    def _check_self(self):
        return True

    def _check_conflict(self, other_pass):
        # double application would nest jax.checkpoint and silently
        # multiply backward recompute cost
        return other_pass.name != self.name

    def _type(self):
        return PassType.CALC_OPT

    def _apply_single_impl(self, main_program, startup_program, context):
        segments = self.get_attr("segments")
        min_inputs = int(self.get_attr("min_inputs", 2))
        n_rewritten = 0
        for node in main_program.nodes:
            if segments is not None:
                if node.name not in segments:
                    continue
            elif len(node.in_refs) < min_inputs:
                continue
            node.fn = jax.checkpoint(node.fn)
            n_rewritten += 1
        context.set_attr("recompute_nodes_rewritten",
                         context.get_attr("recompute_nodes_rewritten", 0)
                         + n_rewritten)
