"""``paddle.distributed.passes`` — user-extensible program-rewrite passes.

Re-design of the reference pass framework
(``python/paddle/distributed/passes/pass_base.py:25``: PassContext /
PassBase registry / register_pass / new_pass over ProgramDesc rewrites).
Here a pass rewrites the recorded :class:`paddle_tpu.static.graph.Program`
op DAG — each node is a pure jax fn, so rewrites compose as function
wrapping (AMP dtype policies, ``jax.checkpoint`` rematerialisation) or
node-list surgery, and the rewritten program still jit-compiles to one
XLA computation. The reference's CPP pass wrapper has no analog: XLA's
own pipeline owns low-level fusion.
"""
from .pass_base import (  # noqa: F401
    PassBase, PassContext, PassManager, PassType, new_pass, register_pass,
)
from . import builtin  # noqa: F401  (registers the built-in passes)

__all__ = ["PassBase", "PassContext", "PassManager", "PassType",
           "new_pass", "register_pass"]
