"""Pass framework core (ref: ``distributed/passes/pass_base.py``)."""
from __future__ import annotations

from abc import ABC, abstractmethod

__all__ = ["PassContext", "PassType", "PassBase", "PassManager",
           "register_pass", "new_pass"]


class PassContext:
    """Carries applied-pass history + shared attrs across a pipeline
    (ref: ``pass_base.py PassContext``)."""

    def __init__(self):
        self._applied_passes = []
        self._attrs = {}

    def set_attr(self, key, value):
        self._attrs[key] = value

    def get_attr(self, key, default=None):
        return self._attrs.get(key, default)

    @property
    def passes(self):
        return list(self._applied_passes)

    def _add_pass(self, pass_obj):
        self._applied_passes.append(pass_obj)


class PassType:
    UNKNOWN = 0
    COMM_OPT = 1
    CALC_OPT = 2
    PARALLEL_OPT = 3
    FUSION_OPT = 4


class PassBase(ABC):
    """A program-rewrite pass. Subclass and implement ``_check_self``,
    ``_check_conflict`` and ``_apply_single_impl(main, startup, ctx)``;
    register with :func:`register_pass`.

    ``apply`` mirrors the reference semantics: self-check, conflict
    check against every already-applied pass in the context (fusion
    passes must come last — the one common rule the reference installs
    that is meaningful here), then apply to each (main, startup) pair.
    """

    _REGISTERED_PASSES: dict = {}

    name: str | None = None

    @staticmethod
    def _register(pass_name, pass_class):
        assert issubclass(pass_class, PassBase)
        PassBase._REGISTERED_PASSES[pass_name] = pass_class

    def __init__(self):
        self._attrs = {}

    def set_attr(self, key, value):
        self._attrs[key] = value
        return self

    def get_attr(self, key, default=None):
        return self._attrs.get(key, default)

    @abstractmethod
    def _check_self(self):
        """Return False to skip (bad attrs / not applicable)."""

    @abstractmethod
    def _check_conflict(self, other_pass):
        """Return False if this pass cannot run after ``other_pass``."""

    def _type(self):
        return PassType.UNKNOWN

    def _check_conflict_including_common_rules(self, other_pass):
        # fusion passes last: anything else conflicts when applied
        # after a FUSION_OPT (ref pass_base.py _fusion_opt_last_rule)
        if (other_pass._type() == PassType.FUSION_OPT
                and self._type() != PassType.FUSION_OPT):
            return False
        return self._check_conflict(other_pass)

    def apply(self, main_programs, startup_programs, context=None):
        """Apply to lists of programs; returns the (possibly fresh)
        PassContext. A failed check leaves the programs untouched."""
        # validate the argument shape BEFORE the check gates: a failed
        # check must not mask misuse that would resurface later
        if not isinstance(main_programs, (list, tuple)) or \
                not isinstance(startup_programs, (list, tuple)):
            raise TypeError("apply() takes LISTS of programs; wrap the "
                            "single program in a list")
        if len(main_programs) != len(startup_programs):
            raise ValueError("main/startup program list length mismatch")
        if context is None:
            context = PassContext()
        if not self._check_self():
            return context
        if not all(self._check_conflict_including_common_rules(p)
                   for p in context.passes):
            return context
        for main, startup in zip(main_programs, startup_programs):
            self._apply_single_impl(main, startup, context)
            # a pass-authored mutation must invalidate the executor's
            # compile cache (keyed on program.version) even when the
            # pass only rewrote node.fn in place
            for prog in (main, startup):
                if hasattr(prog, "version"):
                    prog.version += 1
        context._add_pass(self)
        return context

    @abstractmethod
    def _apply_single_impl(self, main_program, startup_program, context):
        """Mutate one (main, startup) Program pair in place."""


def register_pass(name):
    """Decorator: ``@register_pass("my_pass") class MyPass(PassBase)``."""
    def impl(cls):
        PassBase._register(name, cls)
        cls.name = name
        return cls
    return impl


def new_pass(name, pass_attrs=None):
    """Instantiate a registered pass with attrs (ref ``new_pass``)."""
    pass_class = PassBase._REGISTERED_PASSES.get(name)
    if pass_class is None:
        known = sorted(PassBase._REGISTERED_PASSES)
        raise ValueError(f"Pass {name!r} is not registered; known: {known}")
    pass_obj = pass_class()
    for k, v in (pass_attrs or {}).items():
        pass_obj.set_attr(k, v)
    return pass_obj


class PassManager:
    """Apply an ordered list of passes (ref ``pass_base.py:349``).
    ``auto_solve_conflict`` reorders so FUSION_OPT passes run last (the
    one common rule with meaning here) and drops later duplicates that
    conflict with already-scheduled passes."""

    def __init__(self, passes, context=None, auto_solve_conflict=True):
        self._context = context if context is not None else PassContext()
        passes = list(passes)
        if auto_solve_conflict:
            ordered = ([p for p in passes
                        if p._type() != PassType.FUSION_OPT]
                       + [p for p in passes
                          if p._type() == PassType.FUSION_OPT])
            kept = []
            for p in ordered:
                if all(p._check_conflict_including_common_rules(q)
                       for q in kept):
                    kept.append(p)
            self._passes = kept
        else:
            self._passes = passes

    def apply(self, main_programs, startup_programs):
        for p in self._passes:
            self._context = p.apply(main_programs, startup_programs,
                                    self._context)
        return self._context

    @property
    def context(self):
        return self._context

    @property
    def names(self):
        return [p.name for p in self._passes]

    @property
    def passes(self):
        return tuple(self._passes)
