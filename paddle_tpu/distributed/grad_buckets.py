"""Bucketed data-parallel gradient reduction.

ref: the reference's ``EagerReducer`` (``python/paddle/distributed/
parallel.py``) and the ``fuse_grad_size_in_MB`` DistributedStrategy knob:
instead of one all-reduce per parameter (or one giant post-backward
reduction), gradients are grouped into size-targeted buckets in
reverse-registration order — the order backward produces them — and each
bucket goes out as ONE fused collective as soon as its members' grads
are complete, overlapping the remaining backward compute.

TPU-native realization: no hooks, no streams. Each bucket's parameters
are flat-concatenated through :func:`bucket_reduce_marker` — a
``custom_vjp`` identity whose backward performs a single ``lax.pmean``
over the ``dp`` mesh axis on the flat cotangent. Autodiff then *places*
that fused reduction at exactly the point in the backward stream where
the bucket's last member grad is formed (the transpose of the
concat/split plumbing), so XLA's latency-hiding scheduler can run it on
the ICI while the MXU continues with earlier layers' backward — the
compiled analog of the reference's reducer-hook + comm-stream overlap.

Used by :func:`distributed.train_step.build_train_step` on pure-dp
meshes (bucketed reduction is a data-parallel concept there too), and
unit-tested standalone on CPU meshes.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["Bucket", "BucketPlan", "partition_buckets",
            "default_bucket_bytes", "bucket_reduce_marker",
            "apply_bucketed_reduction"]

# mirrors the reference DistributedStrategy default (fuse_grad_size_in_MB)
_DEFAULT_BUCKET_MB = 32.0


def default_bucket_bytes(strategy_mb=None):
    """Bucket size target in bytes: ``PT_GRAD_BUCKET_MB`` env wins, then
    the strategy's ``fuse_grad_size_in_MB``, then the reference's 32 MB
    default."""
    mb = os.environ.get("PT_GRAD_BUCKET_MB")
    if mb is None:
        mb = strategy_mb if strategy_mb else _DEFAULT_BUCKET_MB
    return int(float(mb) * 1024 * 1024)


@dataclass
class Bucket:
    """One reduction bucket: parameter names (reverse-backward order),
    their flat sizes, one dtype, total payload bytes."""
    names: list = field(default_factory=list)
    sizes: list = field(default_factory=list)
    dtype: object = None
    nbytes: int = 0

    @property
    def numel(self):
        return int(sum(self.sizes))


@dataclass
class BucketPlan:
    buckets: list = field(default_factory=list)
    target_bytes: int = 0

    @property
    def n_buckets(self):
        return len(self.buckets)

    def record_metrics(self):
        """pt_grad_buckets_total / pt_grad_bucket_bytes, once per build
        (trace time) — the honest count: the fused reductions execute
        inside one compiled program thereafter."""
        from ..observability import get_telemetry
        tel = get_telemetry()
        for b in self.buckets:
            tel.grad_bucket(b.nbytes)


def partition_buckets(params, bucket_bytes, order=None):
    """Greedy size-targeted partition of ``params`` ({name: array-like})
    into :class:`Bucket` groups.

    Order is REVERSE registration order (``order`` overrides) — backward
    produces grads roughly last-layer-first, so reverse-order buckets
    fill early in the backward pass and their reductions ship early
    (ref ``EagerReducer`` builds groups the same way). A bucket closes
    when adding the next parameter would cross ``bucket_bytes`` (a
    single parameter larger than the target gets a bucket of its own)
    or when the dtype changes — buckets are flat-concatenated, so they
    are dtype-homogeneous rather than cast.
    """
    if bucket_bytes <= 0:
        raise ValueError(f"bucket_bytes must be positive, got {bucket_bytes}")
    names = list(order) if order is not None else list(reversed(params))
    plan = BucketPlan(target_bytes=int(bucket_bytes))
    cur = None
    for k in names:
        p = params[k]
        dt = jnp.dtype(p.dtype)
        size = int(np.prod(p.shape)) if p.shape else 1
        nb = size * dt.itemsize
        if (cur is None or cur.dtype != dt
                or (cur.nbytes and cur.nbytes + nb > plan.target_bytes)):
            cur = Bucket(dtype=dt)
            plan.buckets.append(cur)
        cur.names.append(k)
        cur.sizes.append(size)
        cur.nbytes += nb
    return plan


def _make_marker(axis_name, nbytes):
    """custom_vjp identity over one flat bucket: forward is the vector
    itself; backward is ONE fused mean-reduction of the cotangent over
    the data-parallel axis (grad of a dp-mean loss = pmean of local
    grads)."""

    @jax.custom_vjp
    def marker(flat):
        return flat

    def fwd(flat):
        return flat, None

    def bwd(_, ct):
        # trace-time byte accounting: the fused payload, not one sample
        # per original parameter (ISSUE: pt_collective_bytes honesty)
        from .collective import _observe
        _observe("all_reduce", ct)
        return (lax.pmean(ct, axis_name),)

    marker.defvjp(fwd, bwd)
    return marker


def bucket_reduce_marker(flat, axis_name="dp"):
    """Identity on ``flat`` whose backward pmean-reduces the cotangent
    over ``axis_name`` as one fused collective."""
    nbytes = int(flat.size) * flat.dtype.itemsize
    return _make_marker(axis_name, nbytes)(flat)


def apply_bucketed_reduction(params, plan, axis_name="dp"):
    """Thread every parameter through its bucket's reduction marker.

    Returns a new {name: array} where each bucket's members were
    flat-concatenated, passed through :func:`bucket_reduce_marker`, and
    split back to their original shapes. Forward math is unchanged
    (identity); under ``jax.grad`` each bucket's parameter cotangents
    accumulate into the flat vector (the split's transpose), are
    reduced by ONE ``pmean(axis_name)``, and slice back apart — the
    whole bucketed-overlapped reduction emerges from autodiff ordering.
    """
    out = dict(params)
    for b in plan.buckets:
        flat = jnp.concatenate([jnp.ravel(params[k]) for k in b.names])
        flat = bucket_reduce_marker(flat, axis_name)
        off = 0
        for k, size in zip(b.names, b.sizes):
            out[k] = lax.slice_in_dim(flat, off, off + size).reshape(
                params[k].shape)
            off += size
    return out
