"""Bucketed gradient reduction: fused dp all-reduce and ZeRO
reduce-scatter.

ref: the reference's ``EagerReducer`` (``python/paddle/distributed/
parallel.py``) and the ``fuse_grad_size_in_MB`` DistributedStrategy knob:
instead of one all-reduce per parameter (or one giant post-backward
reduction), gradients are grouped into size-targeted buckets in
reverse-registration order — the order backward produces them — and each
bucket goes out as ONE fused collective as soon as its members' grads
are complete, overlapping the remaining backward compute.

TPU-native realization: no hooks, no streams. Each bucket's parameters
are flat-concatenated through :func:`bucket_reduce_marker` — a
``custom_vjp`` identity whose backward performs the bucket's planned
collective stages on the flat cotangent. Autodiff then *places* that
fused reduction at exactly the point in the backward stream where the
bucket's last member grad is formed (the transpose of the concat/split
plumbing), so XLA's latency-hiding scheduler can run it on the ICI
while the MXU continues with earlier layers' backward — the compiled
analog of the reference's reducer-hook + comm-stream overlap.

Two bucket kinds:

- ``all_reduce`` (PR 10): one ``lax.pmean`` over ``dp`` per bucket.
- ``reduce_scatter`` (ZeRO stages 1–3, this PR): the bucket executes a
  planned :class:`~paddle_tpu.distributed.collective_schedule.
  CollectiveSchedule` — ``reduce_scatter(sharding)`` so each rank
  receives exactly its ``zero_spec`` window, ``all_reduce(dp)`` on the
  1/n scattered payload (the GC3 hierarchical win: only 1/n of the
  gradient bytes cross the slow dp links), then ``all_gather``.  The
  gather is required because a ``custom_vjp`` backward must return a
  cotangent of the primal's (full) shape; outside the step the ZeRO-2
  ``with_sharding_constraint`` re-slices, and XLA routinely cancels
  the adjacent gather/slice pair.

For scatter windows to BE the ``zero_spec`` windows, scatterable
buckets are packed **rank-major**: each member is reshaped so its
sharding-dim windows become the leading axis, members are concatenated
along axis 1 into ``(n_shard, numel/n_shard)``, and the flat vector is
the ravel of that — row ``r`` is rank ``r``'s windows of every member,
back to back.  ``psum_scatter`` over axis 0 of the ``(n, W)`` reshape
then hands rank ``r`` row ``r`` exactly.

Numerics: the batch is sharded over ``dp`` only, so along ``sharding``
every rank computes identical grads; the scatter contributes only rank
0's copy (adding zeros is exact, where summing ``n`` identical copies
and dividing by ``n`` rounds with the backend's psum order), and the
dp stage is the same pmean PR 10 proved bit-parity for — so the
bucketed sharded step is bit-identical to the unbucketed GSPMD step.

Used by :func:`distributed.train_step.build_train_step` on pure-dp and
dp×sharding ZeRO meshes, and unit-tested standalone on CPU meshes.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["Bucket", "BucketPlan", "partition_buckets",
            "default_bucket_bytes", "bucket_reduce_marker",
            "apply_bucketed_reduction"]

# mirrors the reference DistributedStrategy default (fuse_grad_size_in_MB)
_DEFAULT_BUCKET_MB = 32.0


def default_bucket_bytes(strategy_mb=None):
    """Bucket size target in bytes: ``PT_GRAD_BUCKET_MB`` env wins, then
    the strategy's ``fuse_grad_size_in_MB``, then the reference's 32 MB
    default."""
    mb = os.environ.get("PT_GRAD_BUCKET_MB")
    if mb is None:
        mb = strategy_mb if strategy_mb else _DEFAULT_BUCKET_MB
    return int(float(mb) * 1024 * 1024)


@dataclass
class Bucket:
    """One reduction bucket: parameter names (reverse-backward order),
    their flat sizes, one dtype, total payload bytes.  ``kind`` is the
    reduction this bucket's marker performs (``all_reduce`` |
    ``reduce_scatter``); for scatterable buckets ``dims`` holds each
    member's zero_spec scatter dim (parallel to ``names``)."""
    names: list = field(default_factory=list)
    sizes: list = field(default_factory=list)
    dtype: object = None
    nbytes: int = 0
    kind: str = "all_reduce"
    dims: list = field(default_factory=list)

    @property
    def numel(self):
        return int(sum(self.sizes))


@dataclass
class BucketPlan:
    buckets: list = field(default_factory=list)
    target_bytes: int = 0
    # CollectiveSchedule executed by reduce_scatter-kind buckets (None
    # on pure-dp plans, where every bucket is a dp pmean)
    schedule: object = None

    @property
    def n_buckets(self):
        return len(self.buckets)

    @property
    def mapped_axes(self):
        """Mesh axes the bucketed shard_map must run manual over."""
        if self.schedule is not None and self.schedule.shard_axis:
            return ("dp", self.schedule.shard_axis)
        return ("dp",)

    def record_metrics(self):
        """pt_grad_buckets_total / pt_grad_bucket_bytes, once per build
        (trace time) — the honest count: the fused reductions execute
        inside one compiled program thereafter."""
        from ..observability import get_telemetry
        tel = get_telemetry()
        for b in self.buckets:
            tel.grad_bucket(b.nbytes, kind=b.kind)


def partition_buckets(params, bucket_bytes, order=None, scatter_dims=None):
    """Greedy size-targeted partition of ``params`` ({name: array-like})
    into :class:`Bucket` groups.

    Order is REVERSE registration order (``order`` overrides) — backward
    produces grads roughly last-layer-first, so reverse-order buckets
    fill early in the backward pass and their reductions ship early
    (ref ``EagerReducer`` builds groups the same way). A bucket closes
    when adding the next parameter would cross ``bucket_bytes`` (a
    single parameter larger than the target gets a bucket of its own),
    when the dtype changes — buckets are flat-concatenated, so they
    are dtype-homogeneous rather than cast — or when the reduction
    kind changes.

    ``scatter_dims`` ({name: dim | None}) marks params whose grads are
    reduce-scattered over the sharding axis on ``dim`` (their
    ``zero_spec`` placement); unlisted/None params stay ``all_reduce``
    kind. Kinds never share a bucket: a fused collective is one op.
    """
    if bucket_bytes <= 0:
        raise ValueError(f"bucket_bytes must be positive, got {bucket_bytes}")
    names = list(order) if order is not None else list(reversed(params))
    scatter_dims = scatter_dims or {}
    plan = BucketPlan(target_bytes=int(bucket_bytes))
    cur = None
    for k in names:
        p = params[k]
        dt = jnp.dtype(p.dtype)
        size = int(np.prod(p.shape)) if p.shape else 1
        nb = size * dt.itemsize
        dim = scatter_dims.get(k)
        kind = "all_reduce" if dim is None else "reduce_scatter"
        if (cur is None or cur.dtype != dt or cur.kind != kind
                or (cur.nbytes and cur.nbytes + nb > plan.target_bytes)):
            cur = Bucket(dtype=dt, kind=kind)
            plan.buckets.append(cur)
        cur.names.append(k)
        cur.sizes.append(size)
        cur.dims.append(dim)
        cur.nbytes += nb
    return plan


def _make_marker(axis_name, nbytes):
    """custom_vjp identity over one flat bucket: forward is the vector
    itself; backward is ONE fused mean-reduction of the cotangent over
    the data-parallel axis (grad of a dp-mean loss = pmean of local
    grads)."""

    @jax.custom_vjp
    def marker(flat):
        return flat

    def fwd(flat):
        return flat, None

    def bwd(_, ct):
        # trace-time byte accounting: the fused payload, not one sample
        # per original parameter (ISSUE: pt_collective_bytes honesty)
        from .collective import _observe
        _observe("all_reduce", ct)
        return (lax.pmean(ct, axis_name),)

    marker.defvjp(fwd, bwd)
    return marker


def _make_schedule_marker(stages):
    """custom_vjp identity whose backward executes a planned collective
    stage list on the flat cotangent.  ``reduce_scatter`` reshapes the
    rank-major flat to ``(n, W)`` and psum-scatters row ``r`` to rank
    ``r`` (masked to rank 0's contribution — along the sharding axis
    the rows are ``n`` identical replicas, the batch being dp-sharded
    only); ``all_reduce`` pmeans the (now 1/n-sized) payload over dp;
    ``all_gather``
    reassembles to the primal's full flat shape, as custom_vjp
    requires."""

    @jax.custom_vjp
    def marker(flat):
        return flat

    def fwd(flat):
        return flat, None

    def bwd(_, ct):
        from .collective import _observe
        full_shape = ct.shape
        x = ct
        for st in stages:
            if st.op == "reduce_scatter":
                _observe("reduce_scatter", x)
                x = x.reshape(st.size, x.size // st.size)
                # grads are replica-identical along the sharding axis
                # (the batch is dp-sharded only), so the reduce is
                # "pick one": contribute rank 0's copy and let the
                # scatter sum zeros. Summing the n identical copies and
                # dividing by n instead rounds (the backend's psum
                # order isn't a pure tree), breaking bit-parity with
                # the unbucketed step; adding zeros is exact.
                keep = lax.axis_index(st.axis) == 0
                x = lax.psum_scatter(
                    jnp.where(keep, x, jnp.zeros_like(x)), st.axis,
                    scatter_dimension=0, tiled=False)
            elif st.op == "all_reduce":
                _observe("all_reduce", x)
                x = lax.pmean(x, st.axis)
            elif st.op == "all_gather":
                _observe("all_gather", x)
                x = lax.all_gather(x, st.axis, axis=0, tiled=False)
                x = x.reshape(full_shape)
            else:
                raise ValueError(f"unknown collective stage op: {st.op}")
        return (x,)

    marker.defvjp(fwd, bwd)
    return marker


def bucket_reduce_marker(flat, axis_name="dp", schedule=None):
    """Identity on ``flat`` whose backward reduces the cotangent as one
    fused collective: a pmean over ``axis_name``, or — when a
    :class:`CollectiveSchedule` is given — its planned stage list."""
    if schedule is not None:
        return _make_schedule_marker(schedule.stages)(flat)
    nbytes = int(flat.size) * flat.dtype.itemsize
    return _make_marker(axis_name, nbytes)(flat)


def _to_rank_major(arr, dim, n):
    """Reshape ``arr`` to ``(n, size/n)`` where row ``r`` is the ravel
    of ``arr``'s r-th window along ``dim`` — its zero_spec shard."""
    shape = arr.shape
    pre = int(np.prod(shape[:dim])) if dim else 1
    blk = shape[dim] // n
    post = int(np.prod(shape[dim + 1:])) if dim + 1 < len(shape) else 1
    x = arr.reshape(pre, n, blk, post)
    return jnp.transpose(x, (1, 0, 2, 3)).reshape(n, arr.size // n)


def _from_rank_major(x, shape, dim, n):
    """Inverse of :func:`_to_rank_major`."""
    pre = int(np.prod(shape[:dim])) if dim else 1
    blk = shape[dim] // n
    post = int(np.prod(shape[dim + 1:])) if dim + 1 < len(shape) else 1
    return jnp.transpose(x.reshape(n, pre, blk, post),
                         (1, 0, 2, 3)).reshape(shape)


def apply_bucketed_reduction(params, plan, axis_name="dp"):
    """Thread every parameter through its bucket's reduction marker.

    Returns a new {name: array} where each bucket's members were
    flat-concatenated, passed through :func:`bucket_reduce_marker`, and
    split back to their original shapes. Forward math is unchanged
    (identity); under ``jax.grad`` each bucket's parameter cotangents
    accumulate into the flat vector (the split's transpose), are
    reduced by the bucket's fused collective(s), and slice back apart —
    the whole bucketed-overlapped reduction emerges from autodiff
    ordering.

    ``reduce_scatter`` buckets pack **rank-major** (see module
    docstring): members are concatenated as ``(n_shard, W)`` columns so
    the scatter's per-rank rows are exactly the members' ``zero_spec``
    windows.
    """
    out = dict(params)
    n_sh = plan.schedule.shard_size if plan.schedule is not None else 1
    for b in plan.buckets:
        if b.kind == "reduce_scatter":
            stacked = jnp.concatenate(
                [_to_rank_major(params[k], d, n_sh)
                 for k, d in zip(b.names, b.dims)], axis=1)
            flat = bucket_reduce_marker(stacked.reshape(-1),
                                        schedule=plan.schedule)
            stacked = flat.reshape(n_sh, -1)
            off = 0
            for k, size, d in zip(b.names, b.sizes, b.dims):
                w = size // n_sh
                col = lax.slice_in_dim(stacked, off, off + w, axis=1)
                out[k] = _from_rank_major(col, params[k].shape, d, n_sh)
                off += w
        else:
            flat = jnp.concatenate([jnp.ravel(params[k]) for k in b.names])
            flat = bucket_reduce_marker(flat, axis_name)
            off = 0
            for k, size in zip(b.names, b.sizes):
                out[k] = lax.slice_in_dim(flat, off, off + size).reshape(
                    params[k].shape)
                off += size
    return out
