"""Collective communication API.

TPU-native replacement for the reference's entire ProcessGroup stack
(``paddle/fluid/distributed/collective/process_group.h:53`` with
NCCL/Gloo/BKCL/MPI/custom backends, TCPStore rendezvous
``paddle/phi/core/distributed/store/tcp_store.h:120``, and the Python
surface ``python/paddle/distributed/communication/``): collectives are XLA
collectives (``lax.psum / all_gather / all_to_all / ppermute``) compiled
into the program and routed over ICI/DCN by the compiler. There is no
communicator object to create, no stream ordering to manage, no store —
``Group`` is pure rank bookkeeping plus a named mesh axis.

Two execution modes, one API (mirroring ``paddle.distributed.all_reduce``
semantics for test parity, SURVEY §5):

 - **SPMD (traced) mode** — called inside ``shard_map``/``pjit`` where the
   group's axis name is in scope: ops lower directly to ``jax.lax``
   collectives. This is the real compute path used by TP/PP/EP layers.
 - **Eager mode** — called on concrete arrays in "rank-major layout": a
   per-rank value is axis 0 of a stacked array of shape ``[nranks, ...]``
   (the single-controller representation of "each rank holds a tensor").
   The op runs the SAME ``lax`` collective under a ``shard_map`` over the
   group's devices, so the XLA collective machinery is genuinely exercised
   (the analog of the reference's collective op tests,
   ``test/collective/collective_allreduce_api.py`` et al.).
"""
from __future__ import annotations

import functools
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..tensor import Tensor
from . import mesh as _mesh_mod

__all__ = [
    "ReduceOp", "Group", "new_group", "get_group", "destroy_process_group",
    "is_initialized", "all_reduce", "all_gather", "gather", "all_gather_object",
    "broadcast", "broadcast_object_list", "reduce", "scatter",
    "scatter_object_list", "alltoall", "alltoall_single", "all_to_all",
    "reduce_scatter", "send", "recv", "isend", "irecv", "barrier",
    "P2POp", "batch_isend_irecv", "wait", "get_backend",
]

_RANK_AXIS = "ranks"


class ReduceOp:
    """ref: ``python/paddle/distributed/communication/reduce.py ReduceOp``."""
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


_LAX_REDUCE = {
    ReduceOp.SUM: lax.psum,
    ReduceOp.MAX: lax.pmax,
    ReduceOp.MIN: lax.pmin,
    # product = sign * exp(sum(log|x|)); psum of the sign-parity keeps
    # negatives exact and zeros propagate as zeros.
    ReduceOp.PROD: lambda x, ax: (
        jnp.where(lax.psum((x == 0).astype(jnp.int32), ax) > 0, 0.0,
                  (1.0 - 2.0 * (lax.psum((x < 0).astype(jnp.int32), ax) % 2))
                  * jnp.exp(lax.psum(jnp.log(jnp.maximum(jnp.abs(x), 1e-38)),
                                     ax))).astype(x.dtype)),
    ReduceOp.AVG: lax.pmean,
}


class Group:
    """Rank bookkeeping + a device mesh slice (ref:
    ``python/paddle/distributed/communication/group.py:22``).

    ``axis_name`` is the mesh axis this group's collectives reduce over
    when used in SPMD mode; eager mode uses the group's own 1-D sub-mesh.
    """

    def __init__(self, rank, ranks, id=0, axis_name=None, devices=None):
        self._rank = rank            # this process's index within `ranks`
        self.ranks = list(ranks)
        self.id = id
        self.axis_name = axis_name or _RANK_AXIS
        if devices is None:
            devices = jax.devices()
        self._devices = [devices[r % len(devices)] for r in self.ranks]
        self._submesh = None

    # -- rank info ---------------------------------------------------------
    @property
    def rank(self):
        return self._rank

    @property
    def nranks(self):
        return len(self.ranks)

    world_size = nranks

    @property
    def process_group(self):
        return self

    @property
    def name(self):
        return f"_default_pg{self.id}"

    def is_member(self):
        return self._rank >= 0

    def get_group_rank(self, global_rank):
        return self.ranks.index(global_rank) if global_rank in self.ranks \
            else -1

    # -- eager-mode machinery ---------------------------------------------
    def submesh(self) -> Mesh:
        if self._submesh is None:
            self._submesh = Mesh(np.array(self._devices), (self.axis_name,))
        return self._submesh

    def _shard_eval(self, fn, args, in_specs, out_specs):
        """Run `fn` under shard_map over this group's devices."""
        m = self.submesh()
        # check_vma off: collective outputs (all_gather/psum results) ARE
        # replicated but the static varying-axes checker can't always
        # prove it through custom-vjp wrappers
        from ._jax_compat import shard_map
        return shard_map(fn, mesh=m, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)(*args)

    def __repr__(self):
        return f"Group(id={self.id}, ranks={self.ranks})"


_GROUP_MAP: dict[int, Group] = {}
_DEFAULT_GROUP: Group | None = None


def _default_group() -> Group:
    global _DEFAULT_GROUP
    if _DEFAULT_GROUP is None:
        n = jax.device_count()
        from .env import get_rank
        _DEFAULT_GROUP = Group(get_rank() % max(n, 1), list(range(n)), id=0)
        _GROUP_MAP[0] = _DEFAULT_GROUP
    return _DEFAULT_GROUP


def is_initialized():
    return _DEFAULT_GROUP is not None


def destroy_process_group(group=None):
    global _DEFAULT_GROUP
    if group is None or group.id == 0:
        _DEFAULT_GROUP = None
        _GROUP_MAP.clear()
    else:
        _GROUP_MAP.pop(group.id, None)


def get_group(id=0) -> Group:
    if id == 0:
        return _default_group()
    return _GROUP_MAP[id]


def new_group(ranks=None, backend=None, timeout=None, axis_name=None) -> Group:
    """ref: ``python/paddle/distributed/collective.py:178 new_group``.

    No communicator handshake happens (XLA owns transport); this is pure
    bookkeeping and is therefore cheap and deterministic across ranks.
    """
    default = _default_group()
    if ranks is None:
        ranks = list(default.ranks)
    gid = max(_GROUP_MAP) + 1 if _GROUP_MAP else 1
    from .env import get_rank
    me = get_rank()
    rank_in = ranks.index(me) if me in ranks else -1
    g = Group(rank_in, ranks, id=gid, axis_name=axis_name)
    _GROUP_MAP[gid] = g
    return g


def get_backend(group=None):
    return "xla"


def _group_of(group) -> Group:
    return group if isinstance(group, Group) else _default_group()


def _in_axis_scope(name: str) -> bool:
    """True when called under a trace with mesh axis `name` in scope.

    Under the old-jax compat ``shard_map`` (fully manual over every mesh
    axis) the physical axis env would say yes for ALL axes; honor the
    caller's ``axis_names`` declaration instead so an axis left automatic
    (operands replicated, not per-rank blocks) answers "no" exactly like
    new jax — mp_layers' dual-mode dispatch depends on this.
    """
    from ._jax_compat import declared_manual_axes
    declared = declared_manual_axes()
    if declared is not None and name not in declared:
        return False
    try:
        lax.axis_index(name)
        return True
    except Exception:
        return False


def _data(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _observe(op, x):
    """Per-op count + input-byte telemetry. Shape/dtype metadata only —
    works on tracers and device arrays alike, never syncs. In SPMD
    (traced) mode this runs once per trace, which is the honest count:
    the op executes inside ONE compiled program thereafter."""
    from ..observability import get_telemetry
    tel = get_telemetry()
    if not tel.enabled:
        return
    try:
        nbytes = int(x.size) * x.dtype.itemsize
    except Exception:
        nbytes = 0
    tel.collective_op(op, nbytes)


def _timed(op):
    """Per-op host-boundary latency: ``pt_collective_time_seconds{op}``
    around the whole public call (dispatch + the eager shard_map
    execution).  Recorded ONLY outside traces — inside a trace the
    wall clock would measure tracing, not transport, so a dirty trace
    state skips the observation (``_observe``'s count/bytes still fire
    once per trace).  Wall time around async dispatch is a lower
    bound; eager collectives here execute via ``Group._shard_eval``,
    which materializes, so the number is the honest host cost.  The
    same interval feeds the step-phase tracer as a "collective" span —
    the raw material of the compute↔collective overlap fraction."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            from ..observability import get_telemetry
            tel = get_telemetry()
            from ..observability.trace import get_tracer
            tr = get_tracer()
            if not (tel.enabled or tr.enabled):
                return fn(*args, **kwargs)
            try:
                tracing = not jax.core.trace_state_clean()
            except Exception:
                tracing = True  # unknown trace state: don't time
            if tracing:
                return fn(*args, **kwargs)
            t0 = time.perf_counter_ns()
            try:
                return fn(*args, **kwargs)
            finally:
                t1 = time.perf_counter_ns()
                tel.collective_time(op, (t1 - t0) / 1e9)
                if tr.enabled:
                    tr.phase_record("collective", t0, t1)
        return wrapper
    return deco


def _ret(x, like):
    if isinstance(like, Tensor):
        like._data = x
        return like
    return Tensor(x)


class _Task:
    """Completed-task handle (ref: ProcessGroup tasks
    ``process_group.h:61``). XLA ops are async by nature; wait() blocks."""

    def __init__(self, arrays=()):
        self._arrays = arrays

    def wait(self):
        for a in self._arrays:
            jax.block_until_ready(a)
        return True

    def is_completed(self):
        return True

    def synchronize(self):
        self.wait()


def wait(tensor, group=None, use_calc_stream=True):
    jax.block_until_ready(_data(tensor))


# ---------------------------------------------------------------------------
# collectives
# ---------------------------------------------------------------------------

@_timed("all_reduce")
def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """ref: ``communication/all_reduce.py`` → ``ProcessGroupNCCL::AllReduce``
    (``process_group_nccl.cc:160``). SPMD: ``lax.psum`` family. Eager:
    rank-major ``[nranks, ...]`` in/out; every rank slot gets the result."""
    g = _group_of(group)
    red = _LAX_REDUCE[op]
    x = _data(tensor)
    _observe("all_reduce", x)
    if _in_axis_scope(g.axis_name):
        return _ret(red(x, g.axis_name), tensor)

    ax = g.axis_name
    if x.shape[0] != g.nranks:
        raise ValueError(
            f"eager all_reduce expects rank-major layout [nranks={g.nranks},"
            f" ...], got shape {tuple(x.shape)}")

    def f(xs):  # xs: [1, ...] per device
        return red(xs, ax)

    out = g._shard_eval(f, (x,), in_specs=P(ax), out_specs=P(ax))
    res = _ret(out, tensor)
    if not sync_op:
        return _Task((out,))
    return res


@_timed("all_gather")
def all_gather(tensor_or_list, tensor=None, group=None, sync_op=True,
               axis=0):
    """ref: ``communication/all_gather.py``. Two call forms like the
    reference: ``all_gather(tensor_list, tensor)`` fills the list;
    ``all_gather(tensor)`` returns the gathered Tensor (stacked on axis 0
    in eager mode, concatenated on `axis` in SPMD mode)."""
    g = _group_of(group)
    out_list = None
    if isinstance(tensor_or_list, list):
        out_list = tensor_or_list
        src = tensor
    else:
        src = tensor_or_list
    x = _data(src)
    _observe("all_gather", x)

    if _in_axis_scope(g.axis_name):
        gathered = lax.all_gather(x, g.axis_name, axis=axis, tiled=True)
        if out_list is not None:
            parts = jnp.split(gathered, g.nranks, axis=axis)
            out_list.clear()
            out_list.extend(Tensor(p) for p in parts)
            return out_list
        return Tensor(gathered)

    ax = g.axis_name
    if x.shape[0] != g.nranks:
        raise ValueError(
            f"eager all_gather expects rank-major [nranks={g.nranks}, ...]")

    def f(xs):
        return lax.all_gather(xs, ax, axis=0, tiled=True)

    # every device computes the full gather; take the (identical) global view
    out = g._shard_eval(f, (x,), in_specs=P(ax), out_specs=P())
    if out_list is not None:
        out_list.clear()
        out_list.extend(Tensor(out[i]) for i in range(g.nranks))
        return out_list
    return Tensor(out)


@_timed("gather")
def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    """ref: ``communication/gather.py``: collect per-rank tensors into
    ``gather_list`` on ``dst``. Single-controller eager mode sees every
    rank slot, so the list is filled from the rank-major dim (the dst
    restriction is a multi-controller artifact)."""
    g = _group_of(group)
    x = _data(tensor)
    _observe("gather", x)
    if gather_list is None:
        gather_list = []
    if _in_axis_scope(g.axis_name):
        gathered = lax.all_gather(x, g.axis_name, axis=0, tiled=False)
        gather_list.clear()
        gather_list.extend(Tensor(gathered[i]) for i in range(g.nranks))
        return gather_list
    if x.shape[0] != g.nranks:
        raise ValueError(
            f"eager gather expects rank-major [nranks={g.nranks}, ...]")
    gather_list.clear()
    gather_list.extend(Tensor(x[i]) for i in range(g.nranks))
    return gather_list


def all_gather_object(object_list, obj, group=None):
    """Single-controller: every rank slot sees the same object store."""
    g = _group_of(group)
    object_list.clear()
    object_list.extend([obj] * g.nranks)
    return object_list


@_timed("broadcast")
def broadcast(tensor, src=0, group=None, sync_op=True):
    """ref: ``communication/broadcast.py``. SPMD: select src's value via
    all_gather+index (compiled to a broadcast over ICI)."""
    g = _group_of(group)
    x = _data(tensor)
    _observe("broadcast", x)
    if src not in g.ranks:
        raise ValueError(f"broadcast src={src} is not in group {g.ranks}")
    src_local = g.get_group_rank(src)
    if _in_axis_scope(g.axis_name):
        gathered = lax.all_gather(x, g.axis_name, axis=0)
        return _ret(gathered[src_local], tensor)

    ax = g.axis_name
    if x.shape[0] != g.nranks:
        raise ValueError(
            f"eager broadcast expects rank-major [nranks={g.nranks}, ...]")

    def f(xs):
        gathered = lax.all_gather(xs[0], ax, axis=0)
        return gathered[src_local][None]

    out = g._shard_eval(f, (x,), in_specs=P(ax), out_specs=P(ax))
    return _ret(out, tensor)


def broadcast_object_list(object_list, src=0, group=None):
    return object_list


@_timed("reduce")
def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    """ref: ``communication/reduce.py``: only dst's slot keeps the result,
    other slots keep their input (matching NCCL reduce semantics)."""
    g = _group_of(group)
    red = _LAX_REDUCE[op]
    x = _data(tensor)
    _observe("reduce", x)
    if dst not in g.ranks:
        raise ValueError(f"reduce dst={dst} is not in group {g.ranks}")
    dst_local = g.get_group_rank(dst)
    if _in_axis_scope(g.axis_name):
        r = red(x, g.axis_name)
        i = lax.axis_index(g.axis_name)
        return _ret(jnp.where(i == dst_local, r, x), tensor)

    ax = g.axis_name
    if x.shape[0] != g.nranks:
        raise ValueError(
            f"eager reduce expects rank-major [nranks={g.nranks}, ...]")

    def f(xs):
        r = red(xs, ax)
        i = lax.axis_index(ax)
        return jnp.where(i == dst_local, r, xs)

    out = g._shard_eval(f, (x,), in_specs=P(ax), out_specs=P(ax))
    return _ret(out, tensor)


@_timed("scatter")
def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    """ref: ``communication/scatter.py``: src rank's list is distributed,
    one element per rank."""
    g = _group_of(group)
    if tensor_list is not None:
        stacked = jnp.stack([_data(t) for t in tensor_list])
    else:
        stacked = _data(tensor)
        if stacked.shape[0] != g.nranks:
            raise ValueError("scatter needs tensor_list or rank-major input")
    _observe("scatter", stacked)
    if _in_axis_scope(g.axis_name):
        i = lax.axis_index(g.axis_name)
        return _ret(jnp.take(stacked, i, axis=0), tensor)

    ax = g.axis_name

    def f(xs):  # xs replicated [nranks, ...]
        i = lax.axis_index(ax)
        return jnp.take(xs, i, axis=0)[None]

    out = g._shard_eval(f, (stacked,), in_specs=P(),
                        out_specs=P(ax))
    return _ret(out, tensor)


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    g = _group_of(group)
    out_object_list.clear()
    if in_object_list:
        out_object_list.append(in_object_list[g.rank % len(in_object_list)])
    return out_object_list


@_timed("alltoall")
def alltoall(out_tensor_list, in_tensor_list=None, group=None, sync_op=True):
    """ref: ``communication/all_to_all.py``. Eager rank-major form: input
    ``[nranks, nranks, ...]`` (slot [i, j] = rank i's tensor for rank j)
    → output [i, j] = what rank i received from rank j."""
    g = _group_of(group)
    if in_tensor_list is None and not isinstance(out_tensor_list, list):
        x = _data(out_tensor_list)
        as_list = False
    else:
        x = jnp.stack([_data(t) for t in in_tensor_list])
        as_list = True
    _observe("alltoall", x)

    if _in_axis_scope(g.axis_name):
        # x: [nranks, ...] per rank; swap rank axis with the group axis
        out = lax.all_to_all(x, g.axis_name, split_axis=0, concat_axis=0,
                             tiled=True)
        if as_list:
            parts = jnp.split(out, g.nranks, axis=0)
            out_tensor_list.clear()
            out_tensor_list.extend(Tensor(p[0] if p.shape[0] == 1 else p)
                                   for p in parts)
            return out_tensor_list
        return Tensor(out)

    ax = g.axis_name
    if x.shape[0] != g.nranks:
        raise ValueError(
            f"eager alltoall expects [nranks={g.nranks}, nranks, ...]")

    def f(xs):  # xs: [1, nranks, ...] → [1, nranks, ...], slot j from rank j
        return lax.all_to_all(xs, ax, split_axis=1, concat_axis=1)

    out = g._shard_eval(f, (x,), in_specs=P(ax), out_specs=P(ax))
    # out[i, j] = x[j, i] — transpose over ranks, which IS alltoall
    if as_list:
        me = max(g.rank, 0)
        out_tensor_list.clear()
        out_tensor_list.extend(Tensor(out[me, j]) for j in range(g.nranks))
        return out_tensor_list
    return Tensor(out)


all_to_all = alltoall


@_timed("alltoall_single")
def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    """Even-split all_to_all on one tensor (ref:
    ``communication/all_to_all.py alltoall_single``)."""
    g = _group_of(group)
    x = _data(in_tensor)
    _observe("alltoall_single", x)
    if _in_axis_scope(g.axis_name):
        out = lax.all_to_all(x, g.axis_name, split_axis=0, concat_axis=0,
                             tiled=True)
        if out_tensor is not None:
            return _ret(out, out_tensor)
        return Tensor(out)
    ax = g.axis_name
    if x.shape[0] != g.nranks or x.shape[1] % g.nranks:
        raise ValueError(
            "eager alltoall_single expects rank-major [nranks, nranks*chunk,"
            f" ...], got {tuple(x.shape)} for nranks={g.nranks}")

    def f(xs):  # xs: [1, nranks, chunk, ...] per device
        return lax.all_to_all(xs, ax, split_axis=1, concat_axis=1)

    chunked = x.reshape(g.nranks, g.nranks, x.shape[1] // g.nranks,
                        *x.shape[2:])
    out = g._shard_eval(f, (chunked,), in_specs=P(ax), out_specs=P(ax))
    out = out.reshape(x.shape)
    if out_tensor is not None:
        return _ret(out, out_tensor)
    return Tensor(out)


@_timed("reduce_scatter")
def reduce_scatter(tensor, tensor_list=None, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    """ref: ``communication/reduce_scatter.py``: each rank's input is the
    concat of per-destination chunks; output is the reduced chunk owned by
    this rank. SPMD: ``lax.psum_scatter``."""
    g = _group_of(group)
    if tensor_list is not None:
        x = jnp.concatenate([_data(t) for t in tensor_list], axis=0)
    else:
        x = _data(tensor)
    _observe("reduce_scatter", x)
    if _in_axis_scope(g.axis_name):
        out = lax.psum_scatter(x, g.axis_name, scatter_dimension=0,
                               tiled=True)
        if op == ReduceOp.AVG:
            out = out / g.nranks
        return _ret(out, tensor)

    ax = g.axis_name
    if x.shape[0] != g.nranks:
        raise ValueError("eager reduce_scatter expects rank-major "
                         f"[nranks={g.nranks}, nranks*chunk, ...]")

    def f(xs):  # xs: [1, nranks*chunk, ...]
        out = lax.psum_scatter(xs[0], ax, scatter_dimension=0, tiled=True)
        return out[None]

    out = g._shard_eval(f, (x,), in_specs=P(ax), out_specs=P(ax))
    if op == ReduceOp.AVG:
        out = out / g.nranks
    return _ret(out, tensor)


# ---------------------------------------------------------------------------
# point-to-point
# ---------------------------------------------------------------------------
# SPMD mode: ppermute (the ICI-native p2p — used by the pipeline schedule).
# Eager single-controller mode: a rank-slot mailbox; a send is visible to
# the matching recv immediately (one process owns all slots). Multi-process
# p2p rides the compiled pipeline path instead (SURVEY §5: ProcessGroup
# send/recv → ppermute inside the pipeline program).

_MAILBOX: dict[tuple, list] = {}


@_timed("send")
def send(tensor, dst=0, group=None, sync_op=True):
    g = _group_of(group)
    if _in_axis_scope(g.axis_name):
        raise RuntimeError(
            "Inside shard_map use paddle_tpu.distributed.p2p helpers "
            "(ppermute) — a lone send has no SPMD meaning")
    x = _data(tensor)
    _observe("send", x)
    _MAILBOX.setdefault((g.id, g.rank, dst), []).append(x)
    return _Task()


@_timed("recv")
def recv(tensor, src=0, group=None, sync_op=True):
    g = _group_of(group)
    box = _MAILBOX.get((g.id, src, max(g.rank, 0)), None)
    if not box:
        raise RuntimeError(f"recv: no message pending from rank {src}")
    _observe("recv", box[0])
    return _ret(box.pop(0), tensor)


isend = send
irecv = recv


class P2POp:
    """ref: ``communication/batch_isend_irecv.py P2POp``."""

    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    tasks = []
    for p in p2p_op_list:
        tasks.append(p.op(p.tensor, p.peer, p.group))
    return tasks


@_timed("barrier")
def barrier(group=None):
    """All ranks sync. XLA programs are bulk-synchronous; eager barrier is a
    tiny psum across the group's devices."""
    g = _group_of(group)
    ax = g.axis_name
    one = jnp.ones((g.nranks,), jnp.int32)
    _observe("barrier", one)

    def f(x):
        return lax.psum(x, ax)

    out = g._shard_eval(f, (one,), in_specs=P(ax), out_specs=P(ax))
    jax.block_until_ready(out)
