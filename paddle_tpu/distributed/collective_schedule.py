"""Mesh-aware collective schedule planning (GC3 mold).

XLA's default lowering of a gradient reduction on a hybrid mesh is a
single fused collective over the product communicator — correct, but
blind to topology: it moves the FULL gradient payload across the
slowest link and gives the scheduler one monolithic op to overlap.
GC3-style planning instead composes the reduction from per-axis stages
ordered fast-link-first:

    reduce_scatter(ici axis)   # full payload, but over fast in-node ICI
    all_reduce(dcn axes)       # only 1/n of the payload crosses DCN
    all_gather(ici axis)       # reassemble over ICI

The payload crossing the slow data-parallel links shrinks by the
sharding-axis size, and each stage is a separately schedulable op the
latency-hiding scheduler can overlap with backward compute.

This module is the *planner*: pure metadata, no jax imports, safe to
call at trace time.  Execution lives in the per-bucket ``custom_vjp``
markers in :mod:`paddle_tpu.distributed.grad_buckets`, which interpret
a :class:`CollectiveSchedule` stage list inside their transpose.

Topology heuristic: TPU mesh axes are ICI (in-slice) unless named in
``PT_DCN_AXES`` (comma-separated; default ``dp,pp`` — data and
pipeline parallelism are the axes conventionally mapped across slices
/ hosts).  ``PT_COLLECTIVE_SCHEDULE=0`` is the kill switch: planning
returns ``None`` and callers fall back to the pre-PR-11 behavior
(pure-dp bucketing only; GSPMD owns sharded-mesh reductions).
"""
from __future__ import annotations

import dataclasses
import os

__all__ = [
    "Stage", "CollectiveSchedule", "schedule_enabled", "dcn_axes",
    "plan_grad_reduction",
]

_DEFAULT_DCN_AXES = ("dp", "pp")


@dataclasses.dataclass(frozen=True)
class Stage:
    """One collective in a planned reduction: ``op`` over mesh ``axis``.

    ``op`` ∈ {"reduce_scatter", "all_reduce", "all_gather"}.  ``size``
    is the axis size the plan was made for (recorded so executors can
    sanity-check against the mesh they run on).
    """
    op: str
    axis: str
    size: int = 1


@dataclasses.dataclass(frozen=True)
class CollectiveSchedule:
    """An ordered stage list for one logical gradient reduction, plus
    the bookkeeping executors need: ``shard_axis``/``shard_size`` name
    the axis whose reduce-scatter windows are the ZeRO optimizer-state
    shards (None when the plan is a plain all-reduce)."""

    stages: tuple = ()
    shard_axis: str | None = None
    shard_size: int = 1

    @property
    def scatters(self) -> bool:
        return any(s.op == "reduce_scatter" for s in self.stages)

    @property
    def kind(self) -> str:
        """Reduction kind label for telemetry (`pt_grad_buckets_total`)."""
        return "reduce_scatter" if self.scatters else "all_reduce"

    def describe(self) -> str:
        return " -> ".join(f"{s.op}({s.axis}:{s.size})"
                           for s in self.stages) or "noop"


def schedule_enabled(flag=None) -> bool:
    """Is collective-schedule planning on?  ``flag`` (a strategy-level
    override, e.g. ``sharding_configs.comm_overlap``) can force it off;
    the ``PT_COLLECTIVE_SCHEDULE`` env var (default on) is the global
    kill switch and wins over everything."""
    if os.environ.get("PT_COLLECTIVE_SCHEDULE", "1") in ("0", "false",
                                                         "False"):
        return False
    if flag is not None and not flag:
        return False
    return True


def dcn_axes() -> tuple:
    """Mesh axes assumed to cross slow (DCN / cross-host) links.
    ``PT_DCN_AXES`` overrides the ``dp,pp`` default, e.g.
    ``PT_DCN_AXES=dp`` on a single-pod multi-slice job."""
    raw = os.environ.get("PT_DCN_AXES")
    if raw is None:
        return _DEFAULT_DCN_AXES
    return tuple(a.strip() for a in raw.split(",") if a.strip())


def plan_grad_reduction(axis_sizes, zero=None, enabled=None):
    """Plan the per-bucket gradient reduction for a mesh.

    ``axis_sizes`` maps mesh axis name -> size (only dp/sharding
    participate in grad reduction; mp/sep/ep gradients are handled by
    GSPMD inside the model and make the mesh ineligible upstream).
    ``zero`` is the repo's ZeRO level marker ("os", "os_g", or None).

    Returns ``None`` when planning is disabled or there is nothing to
    plan (single device).  Otherwise a :class:`CollectiveSchedule`:

    - dp only, no ZeRO:       all_reduce(dp)           (PR 10 plan)
    - dp × sharding + ZeRO:   reduce_scatter(sharding) -> all_reduce(dp)
                              -> all_gather(sharding)  (hierarchical)
    - sharding only + ZeRO:   reduce_scatter -> all_gather
    """
    if not schedule_enabled(enabled):
        return None
    n_dp = int(axis_sizes.get("dp", 1))
    n_sh = int(axis_sizes.get("sharding", 1))
    if zero is not None and n_sh > 1:
        stages = [Stage("reduce_scatter", "sharding", n_sh)]
        if n_dp > 1:
            stages.append(Stage("all_reduce", "dp", n_dp))
        stages.append(Stage("all_gather", "sharding", n_sh))
        return CollectiveSchedule(tuple(stages), shard_axis="sharding",
                                  shard_size=n_sh)
    if n_dp > 1 and n_sh <= 1 and zero is None:
        return CollectiveSchedule((Stage("all_reduce", "dp", n_dp),))
    # remaining shapes (single device; ZeRO without a sharding axis;
    # sharded mesh without ZeRO) keep their pre-existing reduction path
    return None
