"""Auto-parallel user API: ProcessMesh + shard annotations.

ref: the auto_parallel surface (``python/paddle/distributed/auto_parallel/``,
``DistTensor`` C++ ``paddle/phi/core/distributed/auto_parallel/
dist_tensor.h:27``, ``process_mesh.cc``, reshard ``static/reshard.py``).

The reference implements completion (dist-attr propagation, 1,932 LoC),
partitioner and reshard (3,073 LoC) by hand; under XLA those three ARE
GSPMD sharding propagation (SURVEY §7: "completion/partition/reshard →
GSPMD, free"). What survives is the user-facing annotation API:
``ProcessMesh`` (wraps ``jax.sharding.Mesh``), placements
(Shard/Replicate/Partial), ``shard_tensor`` (device_put with a
NamedSharding), ``reshard`` (device_put to a new spec = the compiler's
resharding collectives).
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..tensor import Tensor

__all__ = ["ProcessMesh", "Shard", "Replicate", "Partial", "shard_tensor",
           "shard_layer", "dtensor_from_fn", "reshard"]


class Shard:
    """Placement: shard over tensor dim `dim` (ref: Shard placement)."""

    def __init__(self, dim):
        self.dim = dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim


class Replicate:
    def __repr__(self):
        return "Replicate()"

    def is_shard(self, dim=None):
        return False


class Partial:
    """Pending-reduction placement. XLA tracks partial sums internally;
    at the API level we treat it as Replicate after an immediate psum."""

    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def __repr__(self):
        return f"Partial({self.reduce_type})"

    def is_shard(self, dim=None):
        return False


class ProcessMesh:
    """ref: ``process_mesh.cc`` / python ProcessMesh: an N-D array of
    process ids with named dims; backs onto a jax Mesh over devices."""

    def __init__(self, mesh, dim_names=None, process_ids=None):
        arr = np.asarray(mesh)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        self._shape = list(arr.shape)
        self._dim_names = list(dim_names)
        self._process_ids = sorted(np.asarray(arr).flatten().tolist())
        devs = jax.devices()
        sel = np.asarray([devs[p % len(devs)] for p in
                          np.asarray(arr).flatten()]).reshape(arr.shape)
        self._jax_mesh = Mesh(sel, tuple(self._dim_names))

    @property
    def shape(self):
        return self._shape

    @property
    def dim_names(self):
        return self._dim_names

    @property
    def process_ids(self):
        return self._process_ids

    @property
    def mesh(self):
        return self._jax_mesh

    @property
    def ndim(self):
        return len(self._shape)

    def get_dim_size(self, name):
        return self._shape[self._dim_names.index(name)]

    def __eq__(self, other):
        return isinstance(other, ProcessMesh) and \
            self._shape == other._shape and \
            self._dim_names == other._dim_names

    def __repr__(self):
        return f"ProcessMesh(shape={self._shape}, dims={self._dim_names})"


def _placements_to_spec(placements, ndim, mesh: ProcessMesh):
    axes = [None] * ndim
    for mesh_dim, pl in enumerate(placements):
        if isinstance(pl, Shard):
            d = pl.dim % ndim
            name = mesh.dim_names[mesh_dim]
            if axes[d] is None:
                axes[d] = name
            elif isinstance(axes[d], tuple):
                axes[d] = axes[d] + (name,)
            else:
                axes[d] = (axes[d], name)
    return P(*axes)


def shard_tensor(data, mesh: ProcessMesh, placements, dtype=None,
                 place=None, stop_gradient=None):
    """ref: ``paddle.distributed.shard_tensor`` — annotate + place a tensor
    on the mesh. Partial placements are reduced immediately."""
    t = data if isinstance(data, Tensor) else Tensor(data, dtype=dtype)
    spec = _placements_to_spec(placements, t.ndim, mesh)
    if not isinstance(t._data, jax.core.Tracer):
        t._data = jax.device_put(t._data, NamedSharding(mesh.mesh, spec))
    t._spec = spec
    if stop_gradient is not None:
        t.stop_gradient = stop_gradient
    return t


def shard_layer(layer, process_mesh: ProcessMesh, shard_fn=None,
                input_fn=None, output_fn=None):
    """ref: ``paddle.distributed.shard_layer``: apply shard_fn(name, layer,
    mesh) to every sublayer (default: replicate params on the mesh)."""
    if shard_fn is None:
        def shard_fn(name, sublayer, mesh):
            for _, p in sublayer.named_parameters(include_sublayers=False):
                if not isinstance(p._data, jax.core.Tracer):
                    p._data = jax.device_put(
                        p._data, NamedSharding(mesh.mesh, P()))
    for name, sub in layer.named_sublayers(include_self=True):
        shard_fn(name, sub, process_mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda lyr, inp: input_fn(inp, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda lyr, inp, out: output_fn(out, process_mesh))
    return layer


def dtensor_from_fn(fn, mesh: ProcessMesh, placements, *args, **kwargs):
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def reshard(tensor, mesh: ProcessMesh, placements):
    """ref: ``auto_parallel/static/reshard.py`` (3,073 LoC of manual
    collective insertion) → one device_put: XLA emits the transfer
    collectives."""
    t = tensor if isinstance(tensor, Tensor) else Tensor(tensor)
    spec = _placements_to_spec(placements, t.ndim, mesh)
    out = Tensor(jax.device_put(t._data, NamedSharding(mesh.mesh, spec)),
                 stop_gradient=t.stop_gradient)
    out._spec = spec
    return out
