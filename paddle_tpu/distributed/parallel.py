"""Parallel bootstrap + DataParallel.

Re-design of ``python/paddle/distributed/parallel.py`` (``init_parallel_env
:67``, ``DataParallel :190``) and the C++ ``EagerReducer``
(``paddle/fluid/distributed/collective/reducer.cc``):

 - ``init_parallel_env`` → ``jax.distributed.initialize`` (the TCPStore /
   rendezvous equivalent) when launched multi-process, plus default-group
   and mesh construction. On a single host it is a cheap no-op setup.
 - ``DataParallel`` → **no reducer exists**. Gradient bucketing, backward
   hooks and fused allreduce overlap (reducer.cc:533,741,914) are what NCCL
   needed; under GSPMD the batch is sharded over the ``dp`` mesh axis and
   XLA inserts (and overlaps) the gradient all-reduce during the compiled
   backward. The wrapper therefore only (a) shards inputs onto the mesh,
   (b) keeps the reference's API surface (scale_loss/no_sync/state_dict).
"""
from __future__ import annotations

import os

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..tensor import Tensor
from ..nn.layer.layers import Layer
from . import mesh as _mesh_mod
from .collective import _default_group
from .env import get_rank, get_world_size, ParallelEnv

__all__ = ["init_parallel_env", "DataParallel", "get_rank", "get_world_size",
           "ParallelEnv"]

_initialized = False


def init_parallel_env():
    """ref: ``parallel.py:67``. Multi-process: rendezvous through
    ``jax.distributed.initialize`` using the launcher's env contract
    (MASTER_ADDR/PORT or PADDLE_TRAINER_ENDPOINTS). Single-process: build
    the default group over local devices."""
    global _initialized
    if _initialized:
        return ParallelEnv()
    nnodes = int(os.environ.get("PADDLE_NNODES", "1"))
    world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if nnodes > 1 or (world > 1 and os.environ.get("MASTER_ADDR")):
        addr = os.environ.get("MASTER_ADDR")
        port = os.environ.get("MASTER_PORT", "6170")
        if addr is None:
            eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
            addr, port = (eps[0].split(":") + ["6170"])[:2]
        jax.distributed.initialize(
            coordinator_address=f"{addr}:{port}",
            num_processes=world,
            process_id=int(os.environ.get("PADDLE_TRAINER_ID", "0")))
    _default_group()
    _mesh_mod.get_mesh()
    _initialized = True
    return ParallelEnv()


def shard_batch_inputs(mesh, inputs, kwargs):
    """Shard concrete batch-leading tensors over the dp mesh axis (shared
    by DataParallel/TensorParallel wrappers)."""
    sharding = NamedSharding(mesh, P("dp"))

    def shard_in(x):
        if isinstance(x, Tensor) and x.ndim >= 1 and \
                not isinstance(x._data, jax.core.Tracer) and \
                x.shape[0] % mesh.shape["dp"] == 0:
            x._data = jax.device_put(x._data, sharding)
        return x

    return (tuple(shard_in(x) for x in inputs),
            {k: shard_in(v) for k, v in kwargs.items()})


class DataParallel(Layer):
    """ref: ``parallel.py:190``. Shards the batch over the ``dp`` axis;
    gradient sync is compiled into the backward by GSPMD (psum over dp),
    replacing EagerReducer's bucketed allreduce. ``comm_buffer_size`` /
    ``last_comm_buffer_size`` are accepted for API parity and ignored —
    XLA owns fusion sizes."""

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self._group = group
        self.find_unused_parameters = find_unused_parameters
        self._grad_sync_enabled = True

    def forward(self, *inputs, **kwargs):
        mesh = _mesh_mod.get_mesh()
        if mesh is not None and mesh.shape.get("dp", 1) > 1:
            inputs, kwargs = shard_batch_inputs(mesh, inputs, kwargs)
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        """Identity: the dp gradient reduction is a mean (pmean) inside the
        compiled program, so no host-side loss re-scaling is needed
        (the reference scales only for its fused allreduce-sum path)."""
        return loss

    def no_sync(self):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            self._grad_sync_enabled = False
            try:
                yield
            finally:
                self._grad_sync_enabled = True
        return ctx()

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    load_dict = set_state_dict
    set_dict = set_state_dict

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, *args, **kwargs):
        return self._layers.named_parameters(*args, **kwargs)
