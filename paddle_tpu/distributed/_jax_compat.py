"""Shims over jax API moves/renames so one tree runs on old and new jax.

The distributed stack is written against the current jax surface
(``jax.shard_map``, ``jax.set_mesh``); older installs (< 0.5) expose the
same machinery as ``jax.experimental.shard_map.shard_map`` (with
``check_rep``/``auto`` instead of ``check_vma``/``axis_names``) and use
the ``Mesh`` object itself as the ambient-mesh context manager.  These
helpers pick whichever exists — a robustness requirement, not a
convenience: the fault-tolerance drills must run on the jax the
container actually has.
"""
from __future__ import annotations

import contextvars
import functools

import jax

__all__ = ["shard_map", "use_mesh", "axis_size", "declared_manual_axes"]

# Manual-axes declaration for the old-jax shard_map path. New jax honors
# ``axis_names`` (undeclared mesh axes stay automatic, so
# ``lax.axis_index`` on them fails and axis-scope probes answer "no").
# Old jax runs fully manual over EVERY mesh axis, which makes physical
# axis-env probes lie: an axis the caller left automatic still resolves,
# flipping dual-mode layers (mp_layers) into their manual path while
# their operands arrived replicated. We record the caller's declared set
# here so ``collective._in_axis_scope`` can answer like new jax does.
# ``None`` = no declaration active (plain traces, or shard_maps that
# passed no axis_names and really do own every axis, e.g. the eager
# collective submesh evaluator).
_MANUAL_AXES: contextvars.ContextVar = contextvars.ContextVar(
    "pt_manual_axes", default=None)


def declared_manual_axes():
    """The axis_names set of the innermost compat shard_map, or None."""
    return _MANUAL_AXES.get()


def in_compat_manual_region():
    """True while tracing the body of an old-jax compat ``shard_map``.

    There EVERY mesh axis is physically manual, so named sharding
    constraints on mesh axes fail at lowering ("axis also found in
    manual_axes") — hint emitters must skip rather than rely on
    trace-time exception guards. Never True on new jax (the wrapper is
    only installed on the experimental path)."""
    return _MANUAL_AXES.get() is not None


def _with_declared_axes(fn, axes):
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        token = _MANUAL_AXES.set(frozenset(axes))
        try:
            return fn(*args, **kwargs)
        finally:
            _MANUAL_AXES.reset(token)
    return wrapped


def shard_map(fn, mesh, in_specs, out_specs, axis_names=None,
              check_vma=False):
    """``jax.shard_map`` when present, else the experimental spelling.

    ``axis_names`` (new API: the axes manual inside the body) maps to the
    old API's complement ``auto`` (the axes left automatic); ``check_vma``
    maps to ``check_rep``.
    """
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_vma=check_vma)
        if axis_names is not None:
            kwargs["axis_names"] = set(axis_names)
        return sm(fn, **kwargs)
    from jax.experimental.shard_map import shard_map as _sm
    # Old jax has no ``axis_names``; its ``auto`` complement triggers an
    # unsupported PartitionId lowering under SPMD partitioning (notably on
    # CPU), so run fully manual instead: axes the caller left automatic are
    # simply unmentioned in the specs, i.e. replicated — correct, if less
    # parallel, which is the right trade for a compatibility path.  The
    # declaration context keeps axis-scope probes honest inside the body:
    # without it, replicated-in operands would hit manual-mode layer paths
    # (wrong math), the exact failure the dual-mode TP layers guard on.
    if axis_names is not None:
        fn = _with_declared_axes(fn, axis_names)
    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def axis_size(axis_name):
    """``lax.axis_size`` where it exists; older jax derives it from the
    ambient axis environment (same mechanism, pre-rename spelling)."""
    from jax import lax
    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return lax.psum(1, axis_name)


def use_mesh(mesh):
    """Ambient-mesh context: ``jax.set_mesh`` where it exists; on older
    jax a ``Mesh`` is itself the context manager."""
    set_mesh = getattr(jax, "set_mesh", None)
    return set_mesh(mesh) if set_mesh is not None else mesh
