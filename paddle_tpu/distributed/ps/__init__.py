"""Large-sparse-embedding training — the TPU-native parameter-server story.

ref: ``paddle/fluid/distributed/ps/`` (~32K LoC of C++ PS tables/servers)
+ ``python/paddle/distributed/ps/`` + ``fleet.utils`` PS entry points. The
reference reaches "trillion-parameter" scale by holding huge embedding
tables on parameter servers and exchanging SPARSE gradients
asynchronously over RPC (``ps/table/common_sparse_table.cc``,
``ps/service/brpc_ps_server.cc``).

**Design decision (explicit descope + replacement).** An asynchronous
push/pull PS is an anti-pattern on TPU pods: every chip is connected by
ICI to every table shard, XLA compiles gather/scatter over sharded
operands into exactly the all-to-all exchanges the PS does by hand, and
synchronous SPMD steps remove the staleness/consistency machinery
entirely. The capability the PS provides — tables far larger than one
accelerator's memory, touched sparsely — maps to:

 - :class:`ShardedEmbedding`: the table's VOCAB dim sharded over the data
   axes (``dp × sharding`` — the PS "server shard" analog; ``mp`` also
   honored). Per-device bytes shrink 1/N; a 10M-vocab × 512 fp32 table
   (20 GB) fits a v5e-256 pod at 80 MB/chip.
 - lookups: XLA gather over the sharded table (the compiler inserts the
   id-routed collective — the "pull");
 - gradients: inside a jitted train step the gather's transpose is a
   scatter-add routed to the owning shard (the "push"); combined with
   ZeRO (``group_sharded_parallel``) the optimizer state shards the same
   way, so the dense-update cost is O(vocab/N) per chip per step.
 - :func:`row_sparse_apply` + :class:`RowSparseAdagrad`: the eager-mode
   analog of the reference's lazy sparse tables — only TOUCHED rows are
   read/updated, never a dense [vocab, dim] buffer.

What is deliberately NOT built: brpc servers, async optimizers
(``DownpourSGD``), staleness control, CPU-side SSD table spill
(``ps/table/ssd_sparse_table.cc``). On TPU they have no hardware to win
on; their scale target is covered by the sharded table above. This note
is the SURVEY §2 "parameter server" line's resolution.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...tensor import Tensor
from ...nn.layer.layers import Layer
from ...nn import functional as F
from .. import mesh as _mesh_mod

__all__ = ["ShardedEmbedding", "row_sparse_apply", "RowSparseAdagrad"]


class ShardedEmbedding(Layer):
    """Embedding whose vocab dim is sharded over the mesh's data axes.

    The TPU replacement for a PS sparse table
    (ref ``ps/table/common_sparse_table.cc``): ``axes`` (default
    ``("dp", "sharding", "mp")``, intersected with the live mesh and
    filtered to sizes that divide ``num_embeddings``) shard dim 0 of the
    weight. Under a jitted train step XLA routes lookups/grads to the
    owning shard over ICI.
    """

    def __init__(self, num_embeddings, embedding_dim,
                 axes=("dp", "sharding", "mp"), padding_idx=None,
                 weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx
        live = []
        size = 1
        for a in axes:
            n = _mesh_mod.mesh_axis_size(a)
            if n > 1 and num_embeddings % (size * n) == 0:
                live.append(a)
                size *= n
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr)
        self.weight._spec = P(tuple(live) if live else None, None)
        mesh = _mesh_mod.get_mesh(create_default=False)
        if mesh is not None and live and not isinstance(
                self.weight._data, jax.core.Tracer):
            self.weight._data = jax.device_put(
                self.weight._data,
                NamedSharding(mesh, self.weight._spec))
        self._shard_axes = tuple(live)

    def forward(self, ids):
        return F.embedding(ids, self.weight, padding_idx=self._padding_idx)


def row_sparse_apply(weight, ids, row_grads, update_fn):
    """Apply an update to only the TOUCHED rows of ``weight``.

    The eager analog of the reference's lazy sparse-table update
    (``ps/table/sparse_sgd_rule.cc``): duplicate ids are pre-summed with a
    segment-sum over the unique set, then one scatter updates the rows —
    no dense [vocab, dim] gradient is ever materialized.

    weight: [V, D] array. ids: int array (any shape). row_grads:
    ids.shape + [D] per-occurrence gradients. update_fn(rows, grads) ->
    new_rows over the deduplicated [U, D] slices.
    Returns (new_weight, unique_ids).
    """
    flat_ids = ids.reshape(-1)
    flat_g = row_grads.reshape(-1, row_grads.shape[-1])
    # pad slots point OUT of range: their scatter updates are dropped by
    # XLA's OOB-scatter rule, so they can never clobber a real row
    uniq, inv = jnp.unique(flat_ids, return_inverse=True,
                           size=flat_ids.shape[0],
                           fill_value=weight.shape[0])
    summed = jax.ops.segment_sum(flat_g, inv.reshape(-1),
                                 num_segments=uniq.shape[0])
    rows = weight[uniq]
    new_rows = update_fn(rows, summed)
    return weight.at[uniq].set(new_rows), uniq


class RowSparseAdagrad:
    """Row-lazy Adagrad for :class:`ShardedEmbedding`-style tables (ref
    ``ps/table/sparse_sgd_rule.cc`` SparseAdaGradSGDRule): accumulator
    rows update only for touched ids; untouched rows cost nothing."""

    def __init__(self, table: Tensor, learning_rate=0.01, epsilon=1e-8):
        self._table = table
        self._lr = learning_rate
        self._eps = epsilon
        self._acc = jnp.zeros((table.shape[0],), jnp.float32)

    def step_rows(self, ids, row_grads):
        """ids: occurrences; row_grads: matching [..., D] grads (e.g.
        ``out.grad`` rows from an embedding lookup)."""
        ids = ids._data if isinstance(ids, Tensor) else jnp.asarray(ids)
        g = row_grads._data if isinstance(row_grads, Tensor) \
            else jnp.asarray(row_grads)
        w = self._table._data
        flat_ids = ids.reshape(-1)
        flat_g = g.reshape(-1, g.shape[-1]).astype(jnp.float32)
        uniq, inv = jnp.unique(flat_ids, return_inverse=True,
                               size=flat_ids.shape[0],
                               fill_value=w.shape[0])
        summed = jax.ops.segment_sum(flat_g, inv.reshape(-1),
                                     num_segments=uniq.shape[0])
        rows = w[uniq].astype(jnp.float32)
        acc_rows = self._acc[uniq] + (summed * summed).mean(-1)
        new_rows = rows - self._lr * summed / (
            jnp.sqrt(acc_rows)[:, None] + self._eps)
        self._table._data = w.at[uniq].set(new_rows.astype(w.dtype))
        self._acc = self._acc.at[uniq].set(acc_rows)
        return uniq
