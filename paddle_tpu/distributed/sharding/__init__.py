"""Group-sharded (ZeRO) API (ref:
``python/paddle/distributed/sharding/group_sharded.py``).

``group_sharded_parallel(model, optimizer, level)`` with level
``os`` (stage 1: optimizer state), ``os_g`` (stage 2: + grads), ``p_g_os``
(stage 3: + params). TPU-native: all three stages are the SAME mechanism —
``PartitionSpec`` annotations over the ``sharding`` mesh axis; what varies
is which trees get the annotation. XLA then stores each shard on its
owner; stage-3's gather-on-use is the compiler's all-gather placement
(SURVEY §7 hard part (c): fsdp sharding + remat rather than literal
stage 3).
"""
from __future__ import annotations

from ..fleet.meta_parallel.sharding_parallel import annotate_fsdp_specs
from ..fleet.meta_parallel.tensor_parallel import place_parameters_on_mesh

__all__ = ["group_sharded_parallel", "save_group_sharded_model"]


def group_sharded_parallel(model, optimizer, level="os_g", scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False, dp_group=None, exclude_layer=None):
    """Returns (model, optimizer, scaler) like the reference."""
    if level not in ("os", "os_g", "p_g_os"):
        raise ValueError(f"level must be os|os_g|p_g_os, got {level!r}")
    if level == "p_g_os":
        annotate_fsdp_specs(model, axis="sharding")
        place_parameters_on_mesh(model)
    # os / os_g: build_train_step reads this level and partitions the
    # optimizer slot/master trees over the `sharding` mesh axis
    # independently of the (replicated) param specs — per-device state
    # bytes shrink ~1/N (train_step.zero_spec). os_g additionally
    # constrains grads to the same partition, turning the dp grad
    # all-reduce into reduce-scatter (stage-2 semantics).
    setattr(optimizer, "_group_sharded_level", level)
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    import os
    from ...framework.io_state import save
    os.makedirs(output, exist_ok=True)
    save(model.state_dict(), os.path.join(output, "model.pdparams"))
    if optimizer is not None and hasattr(optimizer, "state_dict"):
        save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))
