"""CheckpointManager: step-numbered, crash-consistent checkpoint rotation.

The resume workflow on a preemptible TPU fleet (SURVEY §5):

    mgr = CheckpointManager(root, keep_last_n=3)
    state, step = mgr.restore_latest(template=state)   # relaunch path
    for i in range(step or 0, total_steps):
        loss, state = train_step(state, ...)
        mgr.save(i + 1, state)                         # atomic commit
    on_preemption(lambda: mgr.save(current_step, state))

Each ``save(step, state)`` lands in ``<root>/step_<n>`` through the
atomic-commit protocol of :mod:`.checkpoint` (stage + fsync + COMMIT
manifest + rename), so a SIGKILL at any instant leaves either the
previous committed checkpoint or the new one — never a half-written
directory that loads as garbage.  ``restore_latest`` walks steps newest
first, skipping uncommitted or corrupt directories (CRC/coverage), and
keep-last-N garbage collection never deletes the only valid checkpoint.

Async mode (``async_save=True``): the device→host copy happens on the
caller (so donated/overwritten buffers can't corrupt an in-flight
snapshot), while serialization + fsync + commit run on one background
writer thread; a write failure is re-raised on the NEXT manager call —
a checkpoint error must surface, not vanish with a daemon thread.
"""
from __future__ import annotations

import itertools
import json
import logging
import os
import re
import shutil
import threading
import time

import jax

from . import checkpoint as _ckpt
from .checkpoint import CheckpointCorruptError
from ..observability import get_telemetry

__all__ = ["CheckpointManager", "latest_checkpoint"]

logger = logging.getLogger(__name__)

_STEP_RE = re.compile(r"^step_(\d+)$")
_TMP_RE = re.compile(r"^step_(\d+)\.(tmp|old)\.")


def _step_dirname(step):
    return f"step_{int(step):08d}"


class CheckpointManager:
    """Rotating step-numbered checkpoints with resume-from-latest.

    Args:
        root: directory holding ``step_<n>`` checkpoint subdirectories.
        keep_last_n: committed checkpoints to retain (None = keep all).
        async_save: commit on a background writer thread (see module doc).
        store / world_size / process_index: multi-host commit plumbing,
            forwarded to :func:`checkpoint.save_sharded`.
        integrity: verification level for restores — "full" (CRC32),
            "size", or "off" (markers only).
        durable: fsync every write (disable only in tests).
        run_id: isolates multi-host commit-barrier keys across
            relaunches of the same job (defaults to ``$PT_RUN_ID``) —
            a relaunched fleet must never count a dead generation's
            barrier arrivals.
        barrier_timeout: seconds each process waits at the multi-host
            commit barrier before the timeout names the missing ranks.
        elastic: accept checkpoints written at a DIFFERENT world size
            (including partial marker sets after losing hosts) on
            restore, re-sharding from the committed ranks' windows;
            a leaf with a coverage hole makes that step invalid
            (``ReshardError``) and restore falls back.
        orphan_age: on construction, sweep staging/partial-commit
            debris older than this many seconds from ``root``
            (:func:`checkpoint.sweep_staging`); None disables the
            janitor.
    """

    def __init__(self, root, keep_last_n=3, async_save=False, store=None,
                 world_size=None, process_index=None, integrity="full",
                 durable=True, run_id=None, barrier_timeout=300.0,
                 elastic=False, orphan_age=3600.0):
        if keep_last_n is not None and keep_last_n < 1:
            raise ValueError(f"keep_last_n must be >= 1, got {keep_last_n}")
        self.root = root
        self.keep_last_n = keep_last_n
        self.async_save = async_save
        self.store = store
        self.world_size = world_size
        self.process_index = process_index
        self.integrity = integrity
        self.durable = durable
        self.run_id = run_id if run_id is not None \
            else os.environ.get("PT_RUN_ID")
        self.barrier_timeout = barrier_timeout
        self.elastic = elastic
        os.makedirs(root, exist_ok=True)
        if orphan_age is not None:
            _ckpt.sweep_staging(root, max_age=orphan_age)
        self._bad: set[int] = set()     # steps that failed a full verify
        self._err: BaseException | None = None
        self._lock = threading.Lock()
        self._inflight: threading.Thread | None = None

    # -- enumeration --------------------------------------------------------
    def _step_dirs(self):
        out = {}
        try:
            names = os.listdir(self.root)
        except FileNotFoundError:
            return out
        for n in names:
            m = _STEP_RE.match(n)
            if m:
                out[int(m.group(1))] = os.path.join(self.root, n)
        return out

    def step_dir(self, step):
        return os.path.join(self.root, _step_dirname(step))

    def all_steps(self):
        """Every step directory present, committed or not, ascending."""
        return sorted(self._step_dirs())

    def valid_steps(self):
        """Steps whose directory is committed and passes the cheap
        size-level manifest scan (catches truncation without reading
        data), minus any step a restore proved corrupt, ascending."""
        out = []
        for step, d in sorted(self._step_dirs().items()):
            if step in self._bad:
                continue
            try:
                _ckpt.verify_checkpoint(d, integrity="size",
                                        elastic=self.elastic)
            except (CheckpointCorruptError, FileNotFoundError,
                    ValueError) as e:
                logger.debug("checkpoint %s not valid: %s", d, e)
                continue
            out.append(step)
        return out

    def latest_step(self):
        """Newest valid (committed, size-verified, not known-corrupt)
        step, or None."""
        steps = self.valid_steps()
        return steps[-1] if steps else None

    # -- save ---------------------------------------------------------------
    def _raise_pending(self):
        with self._lock:
            err, self._err = self._err, None
        if err is not None:
            raise err

    def _data_state_records(self, proc, data_state):
        """Prepend the input-pipeline cursor (``DataLoader.state_dict``)
        as a per-process JSON record: it rides the same atomic commit as
        the params, so a restored step always carries the matching
        mid-epoch data position — never a half-step drift between the
        two."""
        if data_state is None:
            return ()
        blob = json.dumps(data_state, sort_keys=True).encode("utf-8")
        return ((f"data_state.{proc}.json", blob),)

    def save(self, step, state, block=False, data_state=None):
        """Commit ``state`` as step ``step``.

        Sync mode writes + commits before returning.  Async mode copies
        the shards to host now, queues the write, and returns; a failure
        of the background commit is raised by the NEXT save()/wait().
        ``block=True`` forces a synchronous commit even in async mode
        (preemption handlers must not race process exit).
        ``data_state`` (a JSON-able dict, typically
        ``DataLoader.state_dict()``) is committed atomically beside the
        params and read back with :meth:`load_data_state` for mid-epoch
        input-pipeline resume.
        """
        self._raise_pending()
        proc = (jax.process_index() if self.process_index is None
                else self.process_index)
        world = (jax.process_count() if self.world_size is None
                 else self.world_size)
        path = self.step_dir(step)
        extra = self._data_state_records(proc, data_state)
        tel = get_telemetry()
        if not self.async_save or block:
            self.wait()
            t0 = time.perf_counter()
            try:
                _ckpt._save_records(
                    itertools.chain(extra,
                                    _ckpt._shard_records(state, proc)),
                    path, proc, world, store=self.store,
                    durable=self.durable,
                    run_id=self.run_id,
                    barrier_timeout=self.barrier_timeout)
            except BaseException:
                tel.record_checkpoint_save(time.perf_counter() - t0,
                                           step=step, mode="sync",
                                           ok=False)
                raise
            tel.record_checkpoint_save(time.perf_counter() - t0,
                                       step=step, mode="sync")
            self._gc()
            return
        # device->host copy on the caller: the training loop may donate
        # or overwrite these buffers the moment we return
        records = list(itertools.chain(
            extra, _ckpt._shard_records(state, proc)))
        self.wait()  # one writer at a time; serializes step order

        def _write():
            t0 = time.perf_counter()
            try:
                _ckpt._save_records(records, path, proc, world,
                                    store=self.store, durable=self.durable,
                                    run_id=self.run_id,
                                    barrier_timeout=self.barrier_timeout)
                tel.record_checkpoint_save(time.perf_counter() - t0,
                                           step=step, mode="async")
                self._gc()
            except BaseException as e:  # surfaced on the next call
                tel.record_async_save_failure(step, e)
                with self._lock:
                    self._err = e

        t = threading.Thread(target=_write, daemon=True,
                             name=f"ckpt-save-{step}")
        self._inflight = t
        t.start()

    def wait(self):
        """Drain any in-flight async save; re-raises its failure."""
        t, self._inflight = self._inflight, None
        while t is not None and t.is_alive():
            t.join(timeout=60.0)
            if t.is_alive():
                logging.getLogger("paddle_tpu.checkpoint").warning(
                    "async save %s still writing after 60s; waiting",
                    t.name)
        self._raise_pending()

    # -- restore ------------------------------------------------------------
    def restore_latest(self, template=None, mesh=None, shardings=None):
        """Load the newest valid checkpoint, falling back past
        uncommitted/corrupt directories to the most recent one that
        verifies clean.

        Returns ``(state, step)``; ``(template, None)`` when no valid
        checkpoint exists (fresh start).  Directories that fail the full
        integrity check are remembered so :meth:`latest_step` reports
        the fallback step afterwards.
        """
        self.wait()
        tel = get_telemetry()
        for step in reversed(self.valid_steps()):
            d = self.step_dir(step)
            t0 = time.perf_counter()
            try:
                state = _ckpt.load_sharded(d, mesh=mesh,
                                           shardings=shardings,
                                           template=template,
                                           integrity=self.integrity,
                                           elastic=self.elastic)
                tel.record_checkpoint_restore(time.perf_counter() - t0,
                                              step=step)
                return state, step
            except (CheckpointCorruptError, FileNotFoundError,
                    ValueError) as e:
                tel.record_checkpoint_restore(time.perf_counter() - t0,
                                              step=step, ok=False)
                logger.warning(
                    "checkpoint step %d at %s failed verification (%s); "
                    "falling back to an earlier step", step, d, e)
                self._bad.add(step)
        return template, None

    def load_data_state(self, step=None, process_index=None):
        """Read back the ``data_state`` committed with ``save(...,
        data_state=...)`` for ``step`` (default: the newest valid step).
        Returns None when that step carries no data state (older
        checkpoints stay loadable)."""
        self.wait()
        if step is None:
            step = self.latest_step()
            if step is None:
                return None
        proc = process_index if process_index is not None else (
            jax.process_index() if self.process_index is None
            else self.process_index)
        path = os.path.join(self.step_dir(step), f"data_state.{proc}.json")
        try:
            with open(path, "r", encoding="utf-8") as f:
                return json.load(f)
        except FileNotFoundError:
            return None

    # -- retention ----------------------------------------------------------
    def _gc(self):
        """Keep the newest ``keep_last_n`` valid checkpoints.

        Deletes (a) older committed checkpoints beyond the window,
        (b) uncommitted/corrupt step dirs older than the newest valid one
        (debris of crashed saves — a NEWER uncommitted dir may be a
        concurrent in-flight save and is left alone), and (c) stale
        ``.tmp``/``.old`` staging dirs.  By construction the newest valid
        checkpoint — in particular the only one — is never deleted.
        """
        if self.keep_last_n is None:
            return
        valid = self.valid_steps()
        if not valid:
            return
        newest = valid[-1]
        keep = set(valid[-self.keep_last_n:])
        deleted = 0
        for step, d in sorted(self._step_dirs().items()):
            if step in keep or step >= newest:
                continue
            shutil.rmtree(d, ignore_errors=True)
            deleted += 1
        for n in os.listdir(self.root):
            m = _TMP_RE.match(n)
            if m and int(m.group(1)) <= newest:
                shutil.rmtree(os.path.join(self.root, n),
                              ignore_errors=True)
                deleted += 1
        get_telemetry().record_checkpoint_gc(deleted)

    def close(self):
        self.wait()


def latest_checkpoint(root):
    """Path of the newest valid ``step_<n>`` checkpoint under ``root``,
    or None — also None when ``root`` does not exist or holds no step
    subdirectories (so callers can use it to sniff whether a directory
    is a manager root at all)."""
    if not os.path.isdir(root):
        return None
    # read-only probe: no janitor sweep from a mere path lookup
    mgr = CheckpointManager(root, keep_last_n=None, orphan_age=None)
    step = mgr.latest_step()
    return None if step is None else mgr.step_dir(step)
