"""CPU-side rendezvous without a device runtime (ref:
``python/paddle/distributed/parallel_with_gloo.py``: gloo-backed
init/barrier/release for data-pipeline and PS processes that never
touch an accelerator).

TPU-native: the native TCPStore (``core/native/store.cc``) is the
transport — the same store the comm bootstrap and RPC rendezvous ride —
so no second comm library exists just for CPU barriers.
"""
from __future__ import annotations

import logging

from ..utils.retry import wait_until

__all__ = ["gloo_init_parallel_env", "gloo_barrier", "gloo_release"]

logger = logging.getLogger(__name__)

_gloo = {"store": None, "rank": 0, "world": 1, "round": 0}


def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """Rendezvous ``rank_num`` CPU processes on ``server_endpoint``
    ("ip:port"; rank 0 hosts the store) — ref
    ``parallel_with_gloo.py:42``."""
    if rank_num <= 1:
        _gloo.update(store=None, rank=0, world=1, round=0)
        return
    from .. import core
    host, port = server_endpoint.rsplit(":", 1)
    store = core.TCPStore(host, int(port), is_master=(rank_id == 0),
                          timeout=120.0)
    _gloo.update(store=store, rank=rank_id, world=rank_num, round=0)
    gloo_barrier()  # everyone waits until the full world arrived


def gloo_barrier(timeout=900.0):
    """Block until every initialized rank reaches the same barrier round
    (ref ``parallel_with_gloo.py:139``). Raises TimeoutError after
    ``timeout`` seconds — a dead peer must not hang the job silently."""
    store, world = _gloo["store"], _gloo["world"]
    if store is None or world <= 1:
        return
    _gloo["round"] += 1
    key = f"gloo/barrier/{_gloo['round']}"
    store.add(key, 1)
    try:
        wait_until(lambda: store.add(key, 0) >= world, timeout,
                   base=0.01, max_delay=0.25, desc="gloo barrier")
    except TimeoutError:
        raise TimeoutError(
            f"gloo_barrier: only {store.add(key, 0)}/{world} ranks "
            f"arrived within {timeout}s — a peer likely died")


def gloo_release():
    """Tear down the rendezvous state (ref
    ``parallel_with_gloo.py:197``)."""
    store = _gloo["store"]
    if store is not None:
        try:
            store.close()
        except Exception as e:
            # release must not raise, but a close failure usually means
            # peers are still blocked on this store — leave a trace
            logger.warning("gloo_release: store close failed: %s", e)
    _gloo.update(store=None, rank=0, world=1, round=0)
