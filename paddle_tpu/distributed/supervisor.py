"""Self-healing training-job supervisor.

A preemptible TPU fleet fails in three distinct ways, and each needs a
different reflex, not an operator page:

 - a **worker** dies (SIGKILL, OOM, watchdog, drain): the survivors
   notice at the next commit barrier and exit; the supervisor relaunches
   the whole fleet as a fresh *generation* (new run id) and training
   resumes from the last committed checkpoint.  Relaunches are metered
   by a per-rank restart budget over a rolling window
   (``PT_SUPERVISOR_MAX_RESTARTS`` / ``PT_SUPERVISOR_RESTART_WINDOW``)
   so a crash-looping rank fails the job *deterministically*, naming
   the rank — and, when the crashes correlate with one data shard, the
   quarantined shard.
 - the **store master** dies: :class:`StandbyStoreGuard` runs a hot
   standby (:class:`~paddle_tpu.core.store_server.StoreFollower`
   tailing the master's WAL), promotes it, and atomically republishes
   the endpoint file; :class:`~.resilient_store.ResilientStore` clients
   re-resolve and ride through with the generation fence intact —
   **zero worker exits**, no restart budget spent.
 - a rank is **dead past its lease** (its host is gone — spawn keeps
   failing): the supervisor relaunches the survivors at a smaller world
   size; the workers' ``elastic=True`` checkpoint reshard absorbs the
   new partitioning.

Restart granularity is the *fleet generation*, not the single rank:
checkpoint commit-barrier keys include the run id, so every rank of a
step must share one — a per-rank respawn into an old generation would
wedge at the first barrier.  The root-cause rank is whichever exited
with a non-:data:`~.exit_codes.EXIT_SAVE_FAILED` failure first
(survivors of a peer death exit ``EXIT_SAVE_FAILED`` as a
*consequence*), and only the root cause is charged against the budget.

Everything here is subprocess-level and stdlib-only at import time
(observability is imported lazily), so the supervisor itself never
touches jax and survives any worker-side crash.  Proven end-to-end on
CPU by ``paddle_tpu.distributed.drill.run_supervisor_drill``.
"""
from __future__ import annotations

import collections
import logging
import os
import subprocess
import sys
import time

from ..utils.retry import backoff_delays, wait_until
from .exit_codes import EXIT_SAVE_FAILED, classify, describe
from .resilient_store import read_endpoint_file

__all__ = [
    "RestartBudgetExhausted",
    "SpawnFailed",
    "StandbyStoreGuard",
    "Supervisor",
    "supervision_snapshot",
]

logger = logging.getLogger(__name__)

#: restart budget: relaunches allowed per root-cause rank (and for the
#: store) inside one rolling window before the job fails loudly
DEFAULT_MAX_RESTARTS = 5
#: rolling-window length (seconds) for the restart budget
DEFAULT_RESTART_WINDOW = 300.0
#: hardware budget: EXIT_SDC verdicts are charged to a SEPARATE
#: per-rank ledger — a chip flipping bits is not a code crash, and one
#: must not eat the other's budget
DEFAULT_SDC_MAX_RESTARTS = 3
#: consensus verdicts against one rank before it is quarantined and the
#: fleet downsizes around it
DEFAULT_SDC_QUARANTINE_THRESHOLD = 2

_STORE_MASTER_SCRIPT = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "drill", "store_master.py")

# most recent Supervisor in this process; supervision_snapshot() reads it
_LAST_SUPERVISOR = None


class SpawnFailed(RuntimeError):
    """Raised by a spawn callable when a rank cannot be (re)launched.

    The supervisor retries the spawn with backoff until the rank's
    lease expires, then relaunches the survivors at a smaller world.
    """


class RestartBudgetExhausted(RuntimeError):
    """The restart budget ran out; ``rank``/``shard``/``cause`` name
    the root cause (``rank is None`` for store-side exhaustion,
    ``shard`` only when the crash loop correlated with one data
    shard)."""

    def __init__(self, message, *, rank=None, shard=None, cause=None):
        super().__init__(message)
        self.rank = rank
        self.shard = shard
        self.cause = cause


class _ResizeNeeded(Exception):
    """Internal: a rank's spawn lease expired; relaunch smaller."""

    def __init__(self, new_world, dead_ranks):
        super().__init__(f"downsize to world={new_world}")
        self.new_world = new_world
        self.dead_ranks = dead_ranks


def _inc_counter(name, help_, cause=None):
    """Book a metric, tolerating a stripped-down environment: the
    supervisor must keep restarting jobs even if observability is
    broken."""
    try:
        from ..observability.metrics import get_registry
        if cause is None:
            get_registry().counter(name, help_).inc(1)
        else:
            get_registry().counter(name, help_,
                                   labelnames=("cause",)).inc(1, cause=cause)
    except Exception:  # pragma: no cover - observability must not kill us
        logger.exception("metrics booking failed for %s", name)


def _record_replay_badput(seconds):
    """Feed the goodput ledger's ``restart_replay`` badput bucket with
    the wall time a restart cost (drain + backoff + respawn): the best
    process-level proxy for re-executed work the supervisor can
    measure."""
    try:
        from ..observability.goodput import get_goodput
        gp = get_goodput()
        if not gp.enabled:
            gp.enable()
        gp.record_restart_replay(float(seconds))
    except Exception:  # pragma: no cover
        logger.exception("goodput booking failed")


class StandbyStoreGuard:
    """Run a durable store master plus a hot standby; promote on death.

    The master (``drill/store_master.py``, path-loaded and stdlib-only
    so a respawn costs one interpreter start) serves with a WAL; the
    standby tails that WAL with a
    :class:`~paddle_tpu.core.store_server.StoreFollower`.  When
    :meth:`poll` finds the master dead it *unlinks the endpoint file
    first* (clients must not reconnect to the corpse's port), touches
    the standby's promote-trigger file, and waits for the promoted
    server to republish the endpoint — at a bumped generation, so the
    :class:`~.resilient_store.ResilientStore` fence stays intact.  A
    fresh standby is then spawned behind the new master.

    ``track``, when given, observes every child ``Popen`` (the drill
    runner registers them for leak-proof reaping).
    """

    def __init__(self, root, *, host="127.0.0.1", port=0,
                 endpoint_file=None, wal_path=None, log_dir=None,
                 poll_interval=0.05, spawn_timeout=30.0,
                 promote_timeout=30.0, track=None):
        self.root = str(root)
        self.host = host
        self.port = int(port)
        self.endpoint_file = endpoint_file or os.path.join(
            self.root, "store.endpoint")
        self.wal_path = wal_path or os.path.join(self.root, "store.wal")
        self.log_dir = log_dir
        self.poll_interval = float(poll_interval)
        self.spawn_timeout = float(spawn_timeout)
        self.promote_timeout = float(promote_timeout)
        self._track = track
        self.master = None
        self.standby = None
        self.promotions = 0
        self._seq = 0  # unique promote-trigger per standby incarnation
        self._logs = []

    # -- child management ---------------------------------------------------

    def _popen(self, cmd, tag):
        stderr = None
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
            f = open(os.path.join(self.log_dir, f"{tag}.log"), "ab")
            self._logs.append(f)
            stderr = f
        proc = subprocess.Popen(cmd, stdin=subprocess.DEVNULL,
                                stdout=stderr, stderr=stderr)
        if self._track is not None:
            self._track(proc)
        return proc

    def _spawn_master(self):
        # stale endpoint from a previous life must not satisfy the
        # "published" wait below
        try:
            os.unlink(self.endpoint_file)
        except FileNotFoundError:
            pass
        cmd = [sys.executable, _STORE_MASTER_SCRIPT,
               "--host", self.host, "--port", str(self.port),
               "--endpoint-file", self.endpoint_file,
               "--wal", self.wal_path]
        proc = self._popen(cmd, f"store-master.{self._seq}")
        wait_until(lambda: read_endpoint_file(self.endpoint_file),
                   timeout=self.spawn_timeout,
                   desc=f"store master publish to {self.endpoint_file}")
        return proc

    def _spawn_standby(self):
        self._seq += 1
        trigger = os.path.join(self.root, f"store.promote.{self._seq}")
        try:
            os.unlink(trigger)
        except FileNotFoundError:
            pass
        cmd = [sys.executable, _STORE_MASTER_SCRIPT,
               "--host", self.host, "--port", str(self.port),
               "--endpoint-file", self.endpoint_file,
               "--wal", self.wal_path,
               "--standby", "--promote-file", trigger,
               "--poll-interval", str(self.poll_interval)]
        proc = self._popen(cmd, f"store-standby.{self._seq}")
        proc.promote_trigger = trigger
        return proc

    # -- lifecycle ----------------------------------------------------------

    def start(self):
        """Spawn master + standby; returns ``(host, port)``."""
        self.master = self._spawn_master()
        self.standby = self._spawn_standby()
        ep = read_endpoint_file(self.endpoint_file)
        logger.info("store guard up: master pid=%d standby pid=%d at %s:%d",
                    self.master.pid, self.standby.pid, ep[0], ep[1])
        return ep

    def poll(self):
        """One health probe; returns True iff a promotion happened."""
        if self.master is None:
            return False
        if self.master.poll() is None:
            # master healthy; resurrect a crashed standby quietly
            if self.standby is not None and self.standby.poll() is not None:
                logger.warning("store standby died (rc=%s); respawning",
                               self.standby.returncode)
                self.standby = self._spawn_standby()
            return False
        self.promote()
        return True

    def promote(self):
        """Master is dead: promote the standby and republish."""
        rc = self.master.returncode
        logger.warning("store master pid=%d dead (rc=%s); promoting standby",
                       self.master.pid, rc)
        if self.standby is None or self.standby.poll() is not None:
            raise RuntimeError(
                "store master died and no live standby to promote "
                f"(master rc={rc})")
        # clients re-resolving must block on the *new* endpoint, never
        # race onto the corpse's port
        try:
            os.unlink(self.endpoint_file)
        except FileNotFoundError:
            pass
        trigger = self.standby.promote_trigger
        with open(trigger, "w", encoding="ascii") as f:
            f.write("promote\n")
        wait_until(lambda: read_endpoint_file(self.endpoint_file),
                   timeout=self.promote_timeout,
                   desc="promoted standby endpoint republish",
                   diag=lambda: (f"standby rc={self.standby.poll()}"))
        self.master, self.standby = self.standby, None
        self.promotions += 1
        _inc_counter("pt_store_promotions_total",
                     "Hot-standby store promotions")
        ep = read_endpoint_file(self.endpoint_file)
        logger.warning("standby promoted: new master pid=%d at %s:%d",
                       self.master.pid, ep[0], ep[1])
        # re-arm: the new master needs its own understudy
        self.standby = self._spawn_standby()
        return ep

    def kill_master(self):
        """Chaos hook: SIGKILL the current master (drills use this)."""
        self.master.kill()

    def stop(self):
        for proc in (self.master, self.standby):
            if proc is not None and proc.poll() is None:
                proc.kill()
        for proc in (self.master, self.standby):
            if proc is not None:
                try:
                    proc.wait(timeout=10.0)
                except subprocess.TimeoutExpired:
                    logger.warning("store child pid %d did not exit "
                                   "after SIGKILL", proc.pid)
        for f in self._logs:
            try:
                f.close()
            except OSError:
                pass
        self._logs.clear()


class Supervisor:
    """Relaunch a worker fleet under a restart budget.

    ``spawn(rank, world, run_id, generation)`` must return a started
    ``subprocess.Popen`` (or raise :class:`SpawnFailed`).  The run id
    is fresh per generation — checkpoint commit barriers key on it, so
    a generation either commits a step together or not at all.

    ``shard_of(rank)`` maps a rank to its data-shard name for
    crash-loop correlation; when every budget-charged failure inside
    the window lands on one shard and that shard reaches
    ``quarantine_threshold`` failures, the shard is quarantined (named
    diagnostic, surfaced on :class:`RestartBudgetExhausted` and in
    :meth:`snapshot`) so the operator knows it is a *data* problem,
    not a host problem.

    ``EXIT_SDC`` verdicts get the mirror-image treatment on the
    *hardware* side: they are charged to a separate per-rank ledger
    (``sdc_max_restarts``, never mixed with code-crash charges), and a
    rank fingered ``sdc_quarantine_threshold`` times by replica
    consensus is quarantined — a named ``RankQuarantine`` diagnostic,
    after which the next generation elastically downsizes around the
    suspect host exactly like an expired spawn lease.
    """

    def __init__(self, spawn, world, *,
                 max_restarts=None, restart_window=None,
                 min_world=1, spawn_lease=5.0,
                 shard_of=None, quarantine_threshold=3,
                 sdc_max_restarts=None, sdc_quarantine_threshold=None,
                 grace=20.0, kill_grace=10.0, generation_timeout=None,
                 store_guard=None, poll_interval=0.1,
                 backoff_base=0.05, backoff_factor=2.0, backoff_max=1.0,
                 run_id_prefix="sup", clock=time.monotonic,
                 sleep=time.sleep):
        if max_restarts is None:
            max_restarts = int(os.environ.get(
                "PT_SUPERVISOR_MAX_RESTARTS", str(DEFAULT_MAX_RESTARTS)))
        if restart_window is None:
            restart_window = float(os.environ.get(
                "PT_SUPERVISOR_RESTART_WINDOW", str(DEFAULT_RESTART_WINDOW)))
        if sdc_max_restarts is None:
            sdc_max_restarts = int(os.environ.get(
                "PT_SUPERVISOR_SDC_MAX_RESTARTS",
                str(DEFAULT_SDC_MAX_RESTARTS)))
        if sdc_quarantine_threshold is None:
            sdc_quarantine_threshold = int(os.environ.get(
                "PT_SUPERVISOR_SDC_QUARANTINE_THRESHOLD",
                str(DEFAULT_SDC_QUARANTINE_THRESHOLD)))
        self._spawn = spawn
        self.world = int(world)
        self.max_restarts = int(max_restarts)
        self.restart_window = float(restart_window)
        self.min_world = int(min_world)
        self.spawn_lease = float(spawn_lease)
        self.shard_of = shard_of if shard_of is not None else str
        self.quarantine_threshold = int(quarantine_threshold)
        self.sdc_max_restarts = int(sdc_max_restarts)
        self.sdc_quarantine_threshold = int(sdc_quarantine_threshold)
        self.grace = float(grace)
        self.kill_grace = float(kill_grace)
        self.generation_timeout = generation_timeout
        self.store_guard = store_guard
        self.poll_interval = float(poll_interval)
        self.run_id_prefix = run_id_prefix
        self._clock = clock
        self._sleep = sleep
        self._delays = backoff_delays(base=backoff_base,
                                      factor=backoff_factor,
                                      max_delay=backoff_max,
                                      clock=clock)
        # budget ledgers: key is a rank (int), "store", or "sdc:<rank>"
        # (the hardware ledger — EXIT_SDC charges never share a key
        # with code-crash charges)
        self._failures = collections.defaultdict(collections.deque)
        self._shard_failures = collections.Counter()
        self._sdc_failures = collections.Counter()  # rank -> verdicts
        self.quarantined_shards = set()
        self.quarantined_ranks = set()
        self.restarts = collections.Counter()  # cause -> count
        self.resizes = []
        self.generation = 0
        self.replay_seconds = 0.0
        global _LAST_SUPERVISOR
        _LAST_SUPERVISOR = self

    # -- spawning -----------------------------------------------------------

    def _spawn_rank(self, rank, world, run_id):
        last = None
        delays = backoff_delays(base=0.05, factor=2.0, max_delay=0.5,
                                deadline=self.spawn_lease,
                                clock=self._clock)
        while True:
            try:
                return self._spawn(rank, world, run_id, self.generation)
            except SpawnFailed as e:
                last = e
                d = next(delays, None)
                if d is None:
                    raise SpawnFailed(
                        f"rank {rank} dead past its {self.spawn_lease}s "
                        f"lease: {last}") from last
                self._sleep(d)

    def _spawn_generation(self, world, run_id):
        procs = {}
        dead = []
        for rank in range(world):
            try:
                procs[rank] = self._spawn_rank(rank, world, run_id)
            except SpawnFailed as e:
                logger.error("generation %d: %s", self.generation, e)
                dead.append(rank)
        if dead:
            # a partial fleet would wedge at the first commit barrier —
            # abort it and relaunch everyone at the smaller world
            self._drain(procs)
            new_world = world - len(dead)
            raise _ResizeNeeded(new_world, dead)
        return procs

    # -- watching -----------------------------------------------------------

    def _drain(self, procs, *, term_first=True):
        running = [p for p in procs.values() if p.poll() is None]
        if term_first:
            for p in running:
                try:
                    p.terminate()
                except OSError:
                    pass
            deadline = self._clock() + self.kill_grace
            wait_until(lambda: (all(p.poll() is not None for p in running)
                                or self._clock() >= deadline),
                       timeout=None, sleep=self._sleep, clock=self._clock,
                       max_delay=self.poll_interval)
        for p in running:
            if p.poll() is None:
                try:
                    p.kill()
                except OSError:
                    pass
        for p in running:
            try:
                p.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                logger.warning("worker pid %d did not exit after "
                               "SIGKILL", p.pid)

    def _watch(self, procs):
        """Block until every worker of this generation exited; escalate
        SIGTERM→SIGKILL on stragglers once a peer failed, and keep the
        store guard's promote reflex ticking the whole time.  Returns
        ``{rank: returncode}``."""
        state = {"first_fail": None, "termed": None}

        def settled():
            if self.store_guard is not None:
                self.store_guard.poll()
            rcs = {r: p.poll() for r, p in procs.items()}
            if all(rc is not None for rc in rcs.values()):
                return rcs
            now = self._clock()
            if state["first_fail"] is None and any(
                    rc not in (None, 0) for rc in rcs.values()):
                state["first_fail"] = now
            if state["first_fail"] is not None:
                if state["termed"] is None and (
                        now - state["first_fail"] > self.grace):
                    logger.warning(
                        "generation %d: draining stragglers %s after "
                        "%.1fs grace", self.generation,
                        [r for r, rc in rcs.items() if rc is None],
                        self.grace)
                    for r, rc in rcs.items():
                        if rc is None:
                            try:
                                procs[r].terminate()
                            except OSError:
                                pass
                    state["termed"] = now
                elif state["termed"] is not None and (
                        now - state["termed"] > self.kill_grace):
                    for r, rc in rcs.items():
                        if rc is None:
                            try:
                                procs[r].kill()
                            except OSError:
                                pass
            return False

        return wait_until(
            settled, timeout=self.generation_timeout,
            desc=f"generation {self.generation} fleet exit",
            diag=lambda: "rcs=%r" % {r: p.poll() for r, p in procs.items()},
            max_delay=self.poll_interval, sleep=self._sleep,
            clock=self._clock)

    # -- diagnosis / budget -------------------------------------------------

    @staticmethod
    def _diagnose(rcs):
        """Root-cause rank and cause for a failed generation: the first
        rank (by id) whose exit is NOT the save-failed consequence code;
        all-save-failed falls back to the first nonzero rank."""
        root = [(r, rc) for r, rc in sorted(rcs.items())
                if rc not in (0, EXIT_SAVE_FAILED)]
        if not root:
            root = [(r, rc) for r, rc in sorted(rcs.items()) if rc != 0]
        rank, rc = root[0]
        return rank, rc, classify(rc)

    def _charge(self, rank, rc, cause):
        """Charge one failure against the budget; raises
        :class:`RestartBudgetExhausted` when the rolling window
        overflows.  Returns the rank to quarantine when this charge
        crossed the SDC consensus threshold (else ``None``)."""
        if cause == "sdc":
            return self._charge_sdc(rank, rc)
        key = "store" if cause == "store_lost" else rank
        now = self._clock()
        dq = self._failures[key]
        dq.append(now)
        while dq and now - dq[0] > self.restart_window:
            dq.popleft()
        shard = None
        if isinstance(key, int):
            shard = self.shard_of(key)
            self._shard_failures[shard] += 1
            correlated = all(n == 0 for s, n in self._shard_failures.items()
                             if s != shard)
            if (correlated and shard not in self.quarantined_shards
                    and self._shard_failures[shard]
                    >= self.quarantine_threshold):
                self.quarantined_shards.add(shard)
                logger.error(
                    "ShardQuarantine: data shard %r quarantined — %d "
                    "consecutive failures, all on rank %d reading this "
                    "shard; the crash loop is data-correlated (poisoned "
                    "input?), not a host fault", shard,
                    self._shard_failures[shard], rank)
        if len(dq) > self.max_restarts:
            where = (f"rank {rank}" if key != "store" else "store master")
            quarantined = shard if shard in self.quarantined_shards else None
            msg = (f"restart budget exhausted: {where} failed "
                   f"{len(dq)} times inside {self.restart_window:.0f}s "
                   f"(budget {self.max_restarts}); last exit "
                   f"{describe(rc)}")
            if quarantined is not None:
                msg += (f"; data shard {quarantined!r} is quarantined "
                        f"(crash loop correlated with this shard)")
            raise RestartBudgetExhausted(
                msg, rank=None if key == "store" else rank,
                shard=quarantined, cause=cause)
        return None

    def _charge_sdc(self, rank, rc):
        """Charge an ``EXIT_SDC`` verdict to the *hardware* ledger.

        Consensus verdicts never touch the code-crash budget (a flaky
        chip must not exhaust a rank's crash allowance, nor hide behind
        it); instead each verdict accrues toward quarantine, and a rank
        fingered ``sdc_quarantine_threshold`` times is handed back to
        :meth:`run` for an elastic downsize around the suspect host."""
        now = self._clock()
        dq = self._failures[f"sdc:{rank}"]
        dq.append(now)
        while dq and now - dq[0] > self.restart_window:
            dq.popleft()
        self._sdc_failures[rank] += 1
        verdicts = self._sdc_failures[rank]
        if (rank not in self.quarantined_ranks
                and verdicts >= self.sdc_quarantine_threshold):
            self.quarantined_ranks.add(rank)
            logger.error(
                "RankQuarantine: rank %d quarantined — fingered by "
                "replica consensus %d times (%s); silent data "
                "corruption is a hardware fault, and the next "
                "generation downsizes around the suspect host",
                rank, verdicts, describe(rc))
            _inc_counter("pt_supervisor_rank_quarantines_total",
                         "Ranks quarantined after repeated SDC "
                         "consensus verdicts")
            return rank
        if len(dq) > self.sdc_max_restarts:
            raise RestartBudgetExhausted(
                f"hardware restart budget exhausted: rank {rank} was "
                f"fingered by replica consensus {len(dq)} times inside "
                f"{self.restart_window:.0f}s (sdc budget "
                f"{self.sdc_max_restarts}); last exit {describe(rc)}",
                rank=rank, cause="sdc")
        return None

    # -- main loop ----------------------------------------------------------

    def run(self):
        """Supervise until the fleet finishes cleanly (returns a report
        dict) or the budget is exhausted
        (:class:`RestartBudgetExhausted`)."""
        world = self.world
        while True:
            run_id = f"{self.run_id_prefix}-g{self.generation}"
            try:
                procs = self._spawn_generation(world, run_id)
            except _ResizeNeeded as rz:
                if rz.new_world < self.min_world:
                    raise RestartBudgetExhausted(
                        f"cannot downsize below min_world="
                        f"{self.min_world}: ranks {rz.dead_ranks} dead "
                        f"past their {self.spawn_lease}s lease at "
                        f"world={world}", cause="lease_expired")
                logger.warning(
                    "generation %d: ranks %s dead past lease; "
                    "relaunching survivors at world=%d (elastic "
                    "reshard)", self.generation, rz.dead_ranks,
                    rz.new_world)
                self.resizes.append({"generation": self.generation,
                                     "from_world": world,
                                     "to_world": rz.new_world,
                                     "dead_ranks": list(rz.dead_ranks)})
                world = self.world = rz.new_world
                self._book_restart("lease_expired", 0.0)
                self.generation += 1
                continue
            rcs = self._watch(procs)
            if all(rc == 0 for rc in rcs.values()):
                return self._report(world, rcs)
            fail_t = self._clock()
            rank, rc, cause = self._diagnose(rcs)
            logger.warning(
                "generation %d failed: root cause rank %d exited %s "
                "(full rcs: %s)", self.generation, rank, describe(rc),
                {r: rcs[r] for r in sorted(rcs)})
            quarantine = self._charge(rank, rc, cause)
            if quarantine is not None:
                new_world = world - 1
                if new_world < self.min_world:
                    raise RestartBudgetExhausted(
                        f"cannot downsize below min_world="
                        f"{self.min_world}: rank {quarantine} is "
                        f"quarantined after repeated SDC consensus "
                        f"verdicts at world={world}",
                        rank=quarantine, cause="sdc")
                logger.warning(
                    "generation %d: quarantined rank %d absorbed by "
                    "elastic downsize; relaunching survivors at "
                    "world=%d", self.generation, quarantine, new_world)
                self.resizes.append({"generation": self.generation,
                                     "from_world": world,
                                     "to_world": new_world,
                                     "dead_ranks": [quarantine],
                                     "quarantined": True})
                world = self.world = new_world
            self._sleep(next(self._delays))
            outage = max(0.0, self._clock() - fail_t)
            self._book_restart(cause, outage)
            self.generation += 1

    def _book_restart(self, cause, outage_seconds):
        self.restarts[cause] += 1
        self.replay_seconds += outage_seconds
        _inc_counter("pt_supervisor_restarts_total",
                     "Fleet relaunches by the supervisor, by root cause",
                     cause=cause)
        if outage_seconds > 0.0:
            _record_replay_badput(outage_seconds)

    def _report(self, world, rcs):
        logger.info("fleet finished cleanly at generation %d (world=%d, "
                    "%d restarts)", self.generation, world,
                    sum(self.restarts.values()))
        return self.snapshot(final_rcs={r: rcs[r] for r in sorted(rcs)})

    def snapshot(self, **extra):
        """JSON-ready supervision summary (bench records embed this)."""
        snap = {
            "world": self.world,
            "generations": self.generation + 1,
            "restarts_total": sum(self.restarts.values()),
            "restarts_by_cause": dict(self.restarts),
            "promotions": (self.store_guard.promotions
                           if self.store_guard is not None else 0),
            "quarantined_shards": sorted(self.quarantined_shards),
            "quarantined_ranks": sorted(self.quarantined_ranks),
            "sdc_verdicts": {str(r): n
                             for r, n in sorted(self._sdc_failures.items())},
            "resizes": list(self.resizes),
            "restart_replay_seconds": round(self.replay_seconds, 6),
        }
        snap.update(extra)
        return snap

    def close(self):
        if self.store_guard is not None:
            self.store_guard.stop()


def supervision_snapshot():
    """Process-wide supervision summary for bench/serve records.

    Reflects the most recent :class:`Supervisor` in this process; a
    process that never supervised anything gets an all-zero block, so
    consumers (bench.py's record emitter, including its
    ``tpu_unreachable`` fast-fail path) can embed it unconditionally.
    """
    if _LAST_SUPERVISOR is not None:
        return _LAST_SUPERVISOR.snapshot()
    return {
        "world": 0,
        "generations": 0,
        "restarts_total": 0,
        "restarts_by_cause": {},
        "promotions": 0,
        "quarantined_shards": [],
        "quarantined_ranks": [],
        "sdc_verdicts": {},
        "resizes": [],
        "restart_replay_seconds": 0.0,
    }
