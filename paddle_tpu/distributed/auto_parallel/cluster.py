"""Cluster model (ref:
``python/paddle/distributed/auto_parallel/static/cluster.py:412`` —
machine/device topology + bandwidths feeding the cost model and tuner).

TPU-native: the mesh is homogeneous, so the model is per-chip specs
(HBM, peak bf16 FLOP/s) + per-link bandwidths (ICI within a host/slice,
DCN across). Auto-detected from the runtime's device kind; every number
is public-spec-sheet data and overridable.
"""
from __future__ import annotations

from dataclasses import dataclass, field, asdict

__all__ = ["Cluster", "CHIP_SPECS"]

# public spec-sheet numbers per device kind: (peak bf16 FLOP/s, HBM
# bytes, ICI GB/s per link-direction aggregate, chips/host)
CHIP_SPECS = {
    "TPU v2": (45e12, 8 << 30, 496e9, 4),
    "TPU v3": (123e12, 16 << 30, 656e9, 4),
    "TPU v4": (275e12, 32 << 30, 1200e9, 4),
    "TPU v5 lite": (197e12, 16 << 30, 400e9, 4),
    "TPU v5e": (197e12, 16 << 30, 400e9, 4),
    "TPU v5": (459e12, 96 << 30, 1200e9, 4),
    "TPU v5p": (459e12, 96 << 30, 1200e9, 4),
    "TPU v6 lite": (918e12, 32 << 30, 1600e9, 4),
    "TPU v6e": (918e12, 32 << 30, 1600e9, 4),
    "cpu": (1e12, 8 << 30, 50e9, 1),  # virtual-mesh testing fallback
}


@dataclass
class Cluster:
    num_chips: int = 1
    device_kind: str = "TPU v5e"
    peak_flops: float = 197e12          # bf16 per chip
    hbm_bytes: int = 16 << 30           # usable HBM per chip
    ici_bandwidth: float = 400e9        # bytes/s per chip, intra-slice
    dcn_bandwidth: float = 25e9         # bytes/s per host, cross-slice
    chips_per_host: int = 4
    num_slices: int = 1                 # multislice: ICI inside, DCN across
    extras: dict = field(default_factory=dict)

    @classmethod
    def auto_detect(cls, devices=None):
        """Build from the live runtime (chip count + device kind)."""
        import jax
        try:
            devices = devices if devices is not None else jax.devices()
            kind = getattr(devices[0], "device_kind", "cpu") or "cpu"
            n = len(devices)
        except Exception:
            kind, n = "cpu", 1
        spec = None
        for k in sorted(CHIP_SPECS, key=len, reverse=True):
            if kind.lower().startswith(k.lower()):
                spec = CHIP_SPECS[k]
                break
        if spec is None:
            spec = CHIP_SPECS["cpu"]
        peak, hbm, ici, cph = spec
        return cls(num_chips=n, device_kind=kind, peak_flops=peak,
                   hbm_bytes=hbm, ici_bandwidth=ici, chips_per_host=cph)

    def bandwidth(self, degree):
        """Effective collective bandwidth for a group of ``degree``
        chips. A TPU SLICE is ICI-connected across all its hosts (a pod
        is one slice of thousands of chips), so the boundary that drops
        a collective to DCN is the slice, not the host: groups that fit
        ``num_chips / num_slices`` ride ICI; only multislice groups pay
        DCN (the scaling-book rule: lay out shardings so collectives
        ride ICI)."""
        if degree <= 1:
            return self.ici_bandwidth
        chips_per_slice = max(self.num_chips // max(self.num_slices, 1), 1)
        if degree <= chips_per_slice:
            return self.ici_bandwidth
        slices = (degree + chips_per_slice - 1) // chips_per_slice
        return min(self.ici_bandwidth, self.dcn_bandwidth * slices)

    def to_dict(self):
        return asdict(self)
