"""Canonical dp×fsdp×tp ``PartitionSpec`` layout engine.

One authoritative table of partition specs for transformer-block
parameters and activations, replacing the ad-hoc per-call-site
``PartitionSpec`` construction that used to live in the TP layers, the
bench models and the bench harness (SNIPPETS [3] is the exemplar: a
frozen ``SpecLayout`` whose methods name the ROLE — qkv, attn-out,
ffn up/down — instead of the axes).  Why a table and not inline specs:

 - axis NAMES live in exactly one place, so renaming a mesh axis (or
   running a model annotated for tp on a dp-only mesh) cannot fork
   between call sites;
 - the Megatron pairing rules (column-parallel out-dim over tp, its
   bias with it; row-parallel in-dim over tp, its bias replicated) are
   encoded once, reviewable once;
 - the fsdp placement and the ZeRO optimizer-state placement share ONE
   rule (:func:`place_axis` — largest free dim divisible by the axis
   size), so parameter and state shards always align.

Everything here is mesh-free and jax-light (only ``PartitionSpec`` is
imported): the module is a leaf, importable from anywhere in the
package without cycles.  Validity against a concrete mesh (dropping
absent axes, divisibility fallback) is :func:`resolve_spec` — the one
resolution path ``train_step.param_shardings`` and the checkpoint
loader both use.

tpu-lint rule TPU015 enforces consumption: model/bench code building a
``PartitionSpec`` inline instead of asking this table is flagged.
"""
from __future__ import annotations

import dataclasses

import numpy as np
from jax.sharding import PartitionSpec as P

__all__ = ["SpecLayout", "default_layout", "resolve_spec", "place_axis",
           "spec_axes"]


def spec_axes(entry):
    """Mesh axis names of ONE PartitionSpec entry (str | tuple | None)."""
    if entry is None:
        return ()
    if isinstance(entry, tuple):
        return tuple(entry)
    return (entry,)


def place_axis(spec, shape, n, axis):
    """Insert ``axis`` on the largest dim of ``shape`` that is free in
    ``spec`` and divisible by ``n`` — the canonical fsdp/ZeRO placement
    rule (largest dim ⇒ biggest per-device byte win; divisibility ⇒ the
    shard is exact, never padded).

    Returns ``spec`` unchanged when ``n <= 1``, when ``axis`` already
    appears (a param fsdp-sharded up front keeps its placement — the
    optimizer state then inherits it), or when no free dim divides
    (replicated leaf, e.g. a rank-1 bias of odd length).
    """
    if n <= 1:
        return spec
    entries = list(spec) + [None] * (len(shape) - len(spec))
    if any(axis in spec_axes(e) for e in entries):
        return spec
    for d in sorted(range(len(shape)), key=lambda d: -shape[d]):
        if entries[d] is None and shape[d] % n == 0:
            entries[d] = axis
            return P(*entries)
    return spec


def resolve_spec(spec, shape, mesh):
    """Canonicalize an annotation against a concrete mesh: drop axis
    names the mesh doesn't have (or has at size 1), and fall back to
    replicated when a kept axis doesn't divide its dim.  ``None`` means
    un-annotated → replicated."""
    if spec is None:
        return P()
    axes = []
    for entry in spec:
        if entry is None:
            axes.append(None)
        elif isinstance(entry, tuple):
            kept = tuple(a for a in entry if a in mesh.shape
                         and mesh.shape[a] > 1)
            axes.append(kept if kept else None)
        else:
            axes.append(entry if entry in mesh.shape
                        and mesh.shape[entry] > 1 else None)
    for d, a in enumerate(axes):
        names = spec_axes(a)
        size = int(np.prod([mesh.shape[nm] for nm in names])) if names else 1
        if size > 1 and shape[d] % size:
            return P()
    return P(*axes)


@dataclasses.dataclass(frozen=True)
class SpecLayout:
    """Canonical PartitionSpecs for transformer-block parameters and
    activations over a ``data × fsdp × tp (× sep)`` mesh.

    Axis defaults match this repo's hybrid mesh names
    (``mesh.HYBRID_AXES``): ``dp`` for data, ``sharding`` for
    fsdp/ZeRO, ``mp`` for tensor parallel, ``sep`` for sequence
    parallel.  Instantiate with other names to retarget a differently
    labelled mesh — every consumer keys off the layout, not the
    literal strings.

    Parameter methods take ``fsdp=True`` to additionally place the
    fsdp axis on the conventional free dim of that role (the dim NOT
    carrying tp).  Weight convention is this repo's ``Linear``:
    ``[in_features, out_features]``.
    """

    data_axis: str = "dp"
    fsdp_axis: str = "sharding"
    tp_axis: str = "mp"
    sep_axis: str = "sep"

    # -- embeddings ---------------------------------------------------------
    def vocab_embedding(self, fsdp=False):
        """``[vocab, hidden]`` — vocab dim over tp (VocabParallel)."""
        return P(self.tp_axis, self.fsdp_axis if fsdp else None)

    def position_embedding(self, fsdp=False):
        """``[positions, hidden]`` — replicated over tp."""
        return P(self.fsdp_axis if fsdp else None, None)

    # -- attention ----------------------------------------------------------
    def qkv_weight(self, fsdp=False):
        """``[hidden, 3*hidden]`` — column parallel: out dim over tp."""
        return P(self.fsdp_axis if fsdp else None, self.tp_axis)

    def qkv_bias(self):
        """``[3*hidden]`` — follows the column shards."""
        return P(self.tp_axis)

    def attn_out_weight(self, fsdp=False):
        """``[hidden, hidden]`` — row parallel: in dim over tp."""
        return P(self.tp_axis, self.fsdp_axis if fsdp else None)

    def attn_out_bias(self):
        """``[hidden]`` — replicated; added after the row reduce."""
        return P()

    # -- mlp ----------------------------------------------------------------
    def ffn_up_weight(self, fsdp=False):
        """``[hidden, 4*hidden]`` — column parallel."""
        return P(self.fsdp_axis if fsdp else None, self.tp_axis)

    def ffn_up_bias(self):
        return P(self.tp_axis)

    def ffn_down_weight(self, fsdp=False):
        """``[4*hidden, hidden]`` — row parallel."""
        return P(self.tp_axis, self.fsdp_axis if fsdp else None)

    def ffn_down_bias(self):
        return P()

    # -- norms / head -------------------------------------------------------
    def norm(self):
        """LayerNorm scale/bias — always replicated (tiny, hot)."""
        return P()

    def lm_head(self, fsdp=False):
        """``[hidden, vocab]`` — vocab dim over tp (tied or untied)."""
        return P(self.fsdp_axis if fsdp else None, self.tp_axis)

    # -- generic megatron roles (what the parallel layer classes ask) -------
    def column_weight(self, fsdp=False):
        return P(self.fsdp_axis if fsdp else None, self.tp_axis)

    def column_bias(self):
        return P(self.tp_axis)

    def row_weight(self, fsdp=False):
        return P(self.tp_axis, self.fsdp_axis if fsdp else None)

    def row_bias(self):
        return P()

    # -- activations / data -------------------------------------------------
    def batch(self, ndim=2):
        """Input batch: leading dim over data; rest replicated."""
        return P(self.data_axis, *([None] * (ndim - 1)))

    def batch_seq(self, ndim=2):
        """``[batch, seq, ...]`` activations: batch over data, seq over
        sep (long-context sequence parallelism)."""
        return P(self.data_axis, self.sep_axis, *([None] * (ndim - 2)))

    def seq_heads(self, ndim=4, seq_dim=2):
        """``[B, H, S, D]``-shaped attention operands with the sequence
        dim over sep (ring attention's ring dimension)."""
        entries = [None] * ndim
        entries[seq_dim] = self.sep_axis
        return P(*entries)

    # -- derived placements -------------------------------------------------
    def with_fsdp(self, spec, shape):
        """``spec`` with the fsdp axis placed per :func:`place_axis`,
        sized by the ambient mesh (no-op when the axis is absent/1)."""
        from .. import mesh as _mesh_mod
        n = _mesh_mod.mesh_axis_size(self.fsdp_axis)
        return place_axis(spec if spec is not None else P(), shape, n,
                          self.fsdp_axis)

    def zero_spec(self, spec, shape, n):
        """Optimizer-state placement for ZeRO: the param's spec with
        the fsdp axis added per :func:`place_axis` (shared rule ⇒ state
        shards always align with fsdp param shards)."""
        return place_axis(spec, shape, n, self.fsdp_axis)

    def annotate_fsdp(self, layer, min_size=1024):
        """Annotate every parameter of ``layer`` (≥ ``min_size``
        elements) with an fsdp placement on top of any existing spec
        (the ``annotate_fsdp_specs`` walk, keyed by this layout's
        axis name)."""
        from ..fleet.meta_parallel.sharding_parallel import \
            annotate_fsdp_specs
        return annotate_fsdp_specs(layer, axis=self.fsdp_axis,
                                   min_size=min_size)


_DEFAULT = SpecLayout()


def default_layout() -> SpecLayout:
    """The process-wide canonical layout (this repo's hybrid axis
    names).  Models targeting a custom-named mesh construct their own
    :class:`SpecLayout` instead of mutating this one."""
    return _DEFAULT
