"""Auto-parallel Engine: strategy-driven prepare/fit/evaluate/predict.

ref: ``python/paddle/distributed/auto_parallel/static/engine.py:55``
(``Engine``), ``:854`` (``fit``), ``:1024`` (``evaluate``), ``:1115``
(``predict``). The reference Engine plans a distributed program
(completion → partition → reshard passes) then drives an executor; here
the plan IS GSPMD — ``Engine.prepare`` applies the strategy toggles
(AMP, ZeRO sharding, recompute, pipeline micro-batching) and builds ONE
compiled train step via ``distributed.train_step.build_train_step`` over
the active ``Mesh``. fit/evaluate/predict drive it with a DataLoader and
hapi callbacks.
"""
from __future__ import annotations

import os

import numpy as np
import jax
import jax.numpy as jnp

from ...tensor import Tensor
from ...nn.layer.layers import Layer
from ...jit.api import functional_call
from ...observability import get_telemetry
from ..fleet.base.distributed_strategy import DistributedStrategy
from .. import mesh as _mesh_mod
from ..train_step import build_train_step
from ..fleet.meta_parallel.pp_spmd import PP_STACK_PREFIX
from ... import autograd

__all__ = ["Engine", "to_static"]


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(np.asarray(x))


class Engine:
    """Strategy-driven hybrid-parallel trainer (ref ``engine.py:55``).

    Parameters mirror the reference: ``Engine(model, loss, optimizer,
    metrics, strategy)``; ``mesh`` defaults to the active global mesh
    (``dist.init_mesh``/``dist.get_mesh``).
    """

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 strategy=None, mesh=None, scaler=None, cluster=None):
        if not isinstance(model, Layer):
            raise TypeError("Engine requires a paddle_tpu.nn.Layer model")
        self._model = model
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = _to_list(metrics)
        self._strategy = strategy or DistributedStrategy()
        self._mesh = getattr(mesh, "mesh", mesh)  # ProcessMesh or jax Mesh
        self._scaler = scaler
        self._cluster = cluster
        self._step_fn = None
        self._state = None
        self._eval_jit = None
        self.history = {}

    @property
    def cluster(self):
        """Hardware model backing cost estimates (ref
        ``static/cluster.py``); auto-detected from the runtime on first
        access unless one was passed in."""
        if self._cluster is None:
            from .cluster import Cluster
            self._cluster = Cluster.auto_detect(
                self._mesh.devices.ravel() if self._mesh is not None
                else None)
        return self._cluster

    def estimate_cost(self, model_desc, cfg=None, global_batch_size=None):
        """Predicted (seconds_per_step, memory_bytes, fits) for running
        ``model_desc`` under ``cfg`` on this engine's cluster (the
        estimator the reference wires via auto_parallel/static/cost/)."""
        from ...cost_model.parallel_cost import predict
        return predict(model_desc, cfg or {}, self.cluster,
                       global_batch_size=global_batch_size)

    # -- strategy application ----------------------------------------------
    def prepare(self, inputs_spec=None, labels_spec=None, main_program=None,
                startup_program=None, mode="train"):
        """Apply strategy toggles and build the compiled train step
        (ref ``engine.py:1233 prepare``). Idempotent."""
        if self._step_fn is not None:
            return self
        s = self._strategy
        mesh = self._mesh or _mesh_mod.get_mesh()

        from ..fleet.base.distributed_strategy import strategy_amp_setup
        autocast, scaler = strategy_amp_setup(s, self._model)
        if self._scaler is None:
            self._scaler = scaler

        if getattr(s, "sharding", False):
            stage = int(s.sharding_configs.get("stage", 1))
            from ..sharding import group_sharded_parallel
            level = {1: "os", 2: "os_g", 3: "p_g_os"}.get(stage, "os")
            group_sharded_parallel(self._model, self._optimizer,
                                   level=level)

        if getattr(s, "recompute", False):
            # models expose per-block recompute via their config flag
            cfg = getattr(self._model, "config", None)
            if cfg is not None and hasattr(cfg, "use_recompute"):
                cfg.use_recompute = True

        n_micro, v_pp = None, 1
        if getattr(s, "pipeline", False):
            n_micro = int(s.pipeline_configs.get("accumulate_steps", 1))
            v_pp = int(s.pipeline_configs.get("virtual_pp_degree", 1))

        if mode == "train":
            if self._optimizer is None:
                raise ValueError(
                    "Engine.fit/load require an optimizer; pass one to "
                    "Engine(..., optimizer=...)")
            if self._loss is None:
                raise ValueError("Engine.fit requires a loss")
            from ..fleet.base.distributed_strategy import \
                strategy_overlap_setup
            bucket_mb, pp_overlap, coll_sched = strategy_overlap_setup(s)
            self._step_fn, self._state = build_train_step(
                self._model, self._loss_adapter, self._optimizer,
                mesh=mesh, pipeline_microbatches=n_micro,
                scaler=self._scaler, pipeline_virtual_stages=v_pp,
                autocast=autocast, grad_bucket_mb=bucket_mb,
                pipeline_overlap=pp_overlap,
                collective_schedule=coll_sched)
        return self

    def _loss_adapter(self, out, *labels):
        loss = self._loss(out, *labels)
        if isinstance(loss, (list, tuple)):
            loss = loss[0]
        return loss

    # -- training ------------------------------------------------------------
    def fit(self, train_data=None, valid_data=None, train_sample_split=None,
            batch_size=1, epochs=1, steps_per_epoch=None, log_freq=10,
            save_dir=None, save_freq=1, valid_freq=1, valid_sample_split=None,
            valid_steps=None, collate_fn=None, callbacks=None, verbose=1,
            shuffle=True, drop_last=True, num_workers=0):
        """ref ``engine.py:854``. ``train_data``: Dataset or DataLoader
        yielding ``(inputs, labels)`` batches."""
        self.prepare(mode="train")
        loader = self._loader(train_data, batch_size, shuffle=shuffle,
                              drop_last=drop_last, num_workers=num_workers,
                              collate_fn=collate_fn)
        from ...hapi.callbacks import config_callbacks
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        cbks = config_callbacks(
            callbacks, model=self, epochs=epochs, steps=steps,
            log_freq=log_freq, save_freq=save_freq, save_dir=save_dir,
            verbose=verbose, metrics=["loss"])
        history = {"loss": []}
        tel = get_telemetry()
        cbks.on_begin("train")
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            logs = {}
            for step_i, batch in enumerate(loader):
                if steps_per_epoch is not None and step_i >= steps_per_epoch:
                    break
                cbks.on_batch_begin("train", step_i, logs)
                x, labels = self._split_batch(batch)
                tok = tel.step_start()
                loss, self._state = self._step_fn(self._state, x, *labels)
                # .shape is device-array metadata — no host transfer
                tel.step_end(tok, mode="train",
                             batch_size=(x.shape[0]
                                         if getattr(x, "ndim", 0) else None))
                logs["loss"] = loss  # lazy device scalar; float on read
                cbks.on_batch_end("train", step_i, logs)
            if logs.get("loss") is not None:
                logs["loss"] = float(logs["loss"])
                history["loss"].append(logs["loss"])
            sched = self._optimizer._learning_rate_scheduler
            if sched is not None:
                sched.step()
            cbks.on_epoch_end(epoch, logs)
            if valid_data is not None and (epoch + 1) % valid_freq == 0:
                val = self.evaluate(valid_data, batch_size=batch_size,
                                    steps=valid_steps, verbose=0)
                for k, v in val.items():
                    history.setdefault("val_" + k, []).append(v)
        cbks.on_end("train", {})
        self._sync_state_to_model()
        self.history = history
        return history

    # -- evaluation / inference ----------------------------------------------
    def evaluate(self, valid_data=None, valid_sample_split=None,
                 batch_size=1, steps=None, log_freq=10, collate_fn=None,
                 callbacks=None, verbose=1, num_workers=0):
        """ref ``engine.py:1024``: loss (+ metrics) over a dataset."""
        loader = self._loader(valid_data, batch_size, shuffle=False,
                              drop_last=False, num_workers=num_workers,
                              collate_fn=collate_fn)
        self._build_eval_step()
        for m in self._metrics:
            m.reset()
        # state is loop-invariant: unstack any pp-stacked leaves ONCE
        params, buffers = self._eval_arrays()
        total, count = 0.0, 0
        for step_i, batch in enumerate(loader):
            if steps is not None and step_i >= steps:
                break
            x, labels = self._split_batch(batch)
            loss, preds = self._eval_jit(params, buffers, x,
                                         *[_arr(l) for l in labels])
            if loss is not None:
                bs = int(x.shape[0]) if hasattr(x, "shape") else 1
                total += float(loss) * bs
                count += bs
            for m in self._metrics:
                corr = m.compute(Tensor(preds), *[Tensor(_arr(l))
                                                  for l in labels])
                m.update(corr)
        out = {}
        if count:
            out["loss"] = total / count
        for m in self._metrics:
            names = _to_list(m.name())
            vals = _to_list(m.accumulate())
            out.update(dict(zip(names, vals)))
        return out

    def predict(self, test_data=None, test_sample_split=None, batch_size=1,
                steps=None, collate_fn=None, callbacks=None, verbose=1,
                num_workers=0):
        """ref ``engine.py:1115``: forward-only over a dataset."""
        loader = self._loader(test_data, batch_size, shuffle=False,
                              drop_last=False, num_workers=num_workers,
                              collate_fn=collate_fn)
        self._build_eval_step()
        params, buffers = self._eval_arrays()
        outs = []
        for step_i, batch in enumerate(loader):
            if steps is not None and step_i >= steps:
                break
            x, _ = self._split_batch(batch, allow_unlabeled=True)
            _, preds = self._eval_jit(params, buffers, x)
            outs.append(np.asarray(preds))
        return outs

    # -- save/load ------------------------------------------------------------
    def save(self, path, training=True):
        """Sharded checkpoint of the engine state (params + optimizer);
        eval-only engines (no optimizer) save plain weights."""
        from .. import checkpoint as ckpt
        if training and self._optimizer is not None:
            self.prepare(mode="train")
        if self._state is not None:
            ckpt.save_state(self._state, path)
        else:
            from ...framework.io_state import save as _save
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            _save(self._model.state_dict(), path + ".pdparams")

    def load(self, path, strict=True, load_optimizer=True):
        from .. import checkpoint as ckpt
        self.prepare(mode="train")
        self._state = ckpt.load_state(path, self._state)
        self._sync_state_to_model()

    def restore_latest(self, root):
        """Resume from the newest valid checkpoint under ``root`` — a
        :class:`~..checkpoint_manager.CheckpointManager` directory of
        ``step_<n>`` commits.  Uncommitted/corrupt steps are skipped.
        Returns the resumed step number, or None when no valid
        checkpoint exists (state untouched — fresh start)."""
        from ..checkpoint_manager import CheckpointManager
        self.prepare(mode="train")
        mgr = CheckpointManager(root)
        state, step = mgr.restore_latest(template=self._state)
        if step is not None:
            self._state = state
            self._sync_state_to_model()
        return step

    # -- plumbing -------------------------------------------------------------
    def _loader(self, data, batch_size, shuffle, drop_last, num_workers,
                collate_fn):
        from ...io import DataLoader, Dataset
        if data is None:
            raise ValueError("data is required")
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              drop_last=drop_last, num_workers=num_workers,
                              collate_fn=collate_fn)
        return data

    def _split_batch(self, batch, allow_unlabeled=False):
        batch = _to_list(batch)
        if len(batch) == 1 and allow_unlabeled:
            return _arr(batch[0]), []
        if len(batch) < 2:
            if allow_unlabeled:
                return _arr(batch[0]), []
            raise ValueError("batches must be (inputs, labels)")
        return _arr(batch[0]), [_arr(b) for b in batch[1:]]

    def _build_eval_step(self):
        if self._eval_jit is not None:
            return
        model, loss_fn = self._model, self._loss
        fwd = getattr(model, "_orig_forward", model.forward)

        def eval_step(params, buffers, x, *labels):
            out, _ = functional_call(model, params, buffers, (Tensor(x),),
                                     training=False, forward_fn=fwd)
            loss = None
            if loss_fn is not None and labels:
                loss = self._loss_adapter(out, *[Tensor(l) for l in labels])
                loss = loss._data if isinstance(loss, Tensor) else loss
            return loss, out._data

        jitted = jax.jit(eval_step)

        def run(params, buffers, x, *labels):
            with autograd.functional_guard():
                return jitted(params, buffers, x, *labels)

        self._eval_jit = run

    def _eval_arrays(self):
        """(params, buffers) for eval: engine state when trained (with
        pp-stacked leaves unstacked back to block names), else the model's
        current tensors."""
        if self._state is None:
            return ({k: p._data for k, p in self._model.named_parameters()},
                    {k: b._data for k, b in self._model.named_buffers()})
        params = {}
        stacked = {k: v for k, v in self._state["params"].items()
                   if k.startswith(PP_STACK_PREFIX)}
        if stacked:
            prefixes, _ = self._model.pipeline_blocks()
            from ..fleet.meta_parallel.pp_spmd import natural_stack
            for k, v in self._state["params"].items():
                if k.startswith(PP_STACK_PREFIX):
                    loc = k[len(PP_STACK_PREFIX):]
                    v = natural_stack(v, len(prefixes))
                    for i, pfx in enumerate(prefixes):
                        params[pfx + loc] = v[i]
                else:
                    params[k] = v
        else:
            params = dict(self._state["params"])
        return params, dict(self._state["buffers"])

    def _sync_state_to_model(self):
        """Write compiled state back into layer tensors so
        ``model.state_dict()`` reflects training."""
        if self._state is None:
            return
        params, buffers = self._eval_arrays()
        named = dict(self._model.named_parameters())
        for k, v in params.items():
            if k in named:
                named[k]._data = v
        named_b = dict(self._model.named_buffers())
        for k, v in buffers.items():
            if k in named_b:
                named_b[k]._data = v

    @property
    def main_program(self):  # static-graph parity shim
        return None

    @property
    def serial_main_program(self):
        return None


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None):
    """ref: ``paddle.distributed.to_static`` — wrap a dygraph layer into a
    strategy-driven distributed Engine (the DistModel analog)."""
    return Engine(model=layer, loss=loss, optimizer=optimizer,
                  strategy=strategy)
