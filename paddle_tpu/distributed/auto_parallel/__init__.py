"""``paddle_tpu.distributed.auto_parallel`` (ref:
``python/paddle/distributed/auto_parallel/``): annotation API
(ProcessMesh / shard_tensor / reshard, re-exported from
``auto_parallel_api``) plus the strategy-driven :class:`Engine`
(ref ``static/engine.py:55``)."""
from ..auto_parallel_api import (  # noqa: F401
    ProcessMesh, Shard, Replicate, Partial, shard_tensor, shard_layer,
    dtensor_from_fn, reshard,
)
from .engine import Engine, to_static  # noqa: F401
from .cluster import Cluster  # noqa: F401

__all__ = ["Cluster", "ProcessMesh", "Shard", "Replicate", "Partial", "shard_tensor",
           "shard_layer", "dtensor_from_fn", "reshard", "Engine",
           "to_static"]
