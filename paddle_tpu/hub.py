"""``paddle.hub`` (ref: ``python/paddle/hapi/hub.py``): load entrypoints
from a repo's ``hubconf.py``.

``source='local'`` is fully supported. ``github``/``gitee`` resolve only
from the local download cache (zero-egress deployment — see
``utils/download.py``); a cache miss raises with the path to populate.
"""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

MODULE_HUBCONF = "hubconf.py"
VAR_DEPENDENCY = "dependencies"


def _import_module(name, path):
    spec = importlib.util.spec_from_file_location(name, path)
    module = importlib.util.module_from_spec(spec)
    sys.modules[name] = module
    spec.loader.exec_module(module)
    return module


def _resolve_dir(repo_dir, source, force_reload):
    if source == "local":
        return repo_dir
    if source not in ("github", "gitee"):
        raise ValueError(
            f'Unknown source: "{source}". Allowed values: "github" | '
            f'"gitee" | "local".')
    from .utils.download import _search_dirs
    name = repo_dir.replace("/", "_").replace(":", "_")
    for d in _search_dirs():
        cand = os.path.join(d, "hub", name)
        if os.path.isdir(cand):
            return cand
    raise RuntimeError(
        f"cannot fetch hub repo {repo_dir!r}: this build runs without "
        f"network access. Unpack the repo at "
        f"{os.path.join(_search_dirs()[0], 'hub', name)} or use "
        f"source='local'.")


def _load_entry(repo_dir, source, force_reload):
    repo = _resolve_dir(repo_dir, source, force_reload)
    hubconf = os.path.join(repo, MODULE_HUBCONF)
    if not os.path.exists(hubconf):
        raise FileNotFoundError(hubconf)
    sys.path.insert(0, repo)
    try:
        module = _import_module(MODULE_HUBCONF[:-3], hubconf)
    finally:
        sys.path.remove(repo)
    deps = getattr(module, VAR_DEPENDENCY, [])
    missing = [d for d in deps if importlib.util.find_spec(d) is None]
    if missing:
        raise RuntimeError(f"Missing dependencies: {', '.join(missing)}")
    return module


def list(repo_dir, source="github", force_reload=False):
    """Entrypoint names exported by the repo's hubconf."""
    module = _load_entry(repo_dir, source, force_reload)
    return [f for f in dir(module)
            if callable(getattr(module, f)) and not f.startswith("_")]


def help(repo_dir, model, source="github", force_reload=False):
    """Docstring of one entrypoint."""
    module = _load_entry(repo_dir, source, force_reload)
    fn = getattr(module, model, None)
    if fn is None or not callable(fn):
        raise RuntimeError(f"Cannot find callable {model} in hubconf")
    return fn.__doc__


def load(repo_dir, model, source="github", force_reload=False, **kwargs):
    """Call entrypoint ``model(**kwargs)`` from the repo's hubconf."""
    module = _load_entry(repo_dir, source, force_reload)
    fn = getattr(module, model, None)
    if fn is None or not callable(fn):
        raise RuntimeError(f"Cannot find callable {model} in hubconf")
    return fn(**kwargs)
