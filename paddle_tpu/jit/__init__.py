"""``paddle_tpu.jit`` (ref: ``python/paddle/jit/__init__.py``)."""
from .api import (to_static, not_to_static, StaticFunction, InputSpec,  # noqa: F401
                  functional_call, enable_static, disable_static,
                  in_dynamic_mode, ignore_module)
from .save_load import save, load, TranslatedLayer  # noqa: F401
