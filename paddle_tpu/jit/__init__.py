"""``paddle_tpu.jit`` (ref: ``python/paddle/jit/__init__.py``)."""
from .api import (to_static, not_to_static, StaticFunction, InputSpec,  # noqa: F401
                  functional_call, enable_static, disable_static,
                  in_dynamic_mode, ignore_module)
from .save_load import save, load, TranslatedLayer  # noqa: F401
from .capture import capture_step, CapturedStep  # noqa: F401


# -- debugging toggles (ref python/paddle/jit/dy2static/logging_utils.py)
# the flags live in jit.api (the only reader); these are the setters


def set_code_level(level=100, also_to_stdout=False):
    """Log transformed code up to ``level`` (ref ``jit.set_code_level``).
    Trace-based to_static has no source transform stages; at level>0
    StaticFunction prints its traced jaxpr on build."""
    from . import api as _api
    _api._code_level = int(level)


def set_verbosity(level=0, also_to_stdout=False):
    """ref ``jit.set_verbosity``."""
    from . import api as _api
    _api._verbosity = int(level)


def enable_to_static(enable=True):
    """Globally toggle to_static compilation (ref
    ``jit.enable_to_static``): when off, decorated functions run eagerly
    (the dygraph fallback the reference provides for debugging)."""
    from . import api as _api
    _api._to_static_enabled = bool(enable)
